"""Host-side line encoding: str lines → padded uint8 device batch.

Vectorized with numpy (one ``encode()`` of the whole corpus + fancy
indexing, no per-line Python loop). Returns, per line, its byte length and
whether it needs host-side verification (non-ASCII content — where UTF-8
byte automata and Java UTF-16 semantics can diverge — content NUL bytes,
or length beyond the device padding cap).

The NUL rule is load-bearing for the device scans: every gate-free
stepper (bit tiers, dense pair-stride, union any-hit, AC prefilter)
relies on byte 0 being PADDING-ONLY — content NULs must re-match on
host, never reach a device automaton.
"""

from __future__ import annotations

import dataclasses

import numpy as np

# Lines longer than this are matched on host; padding cost on device is
# quadratic-ish in the tail, and multi-KB lines are rare in pod logs.
DEFAULT_MAX_LINE_BYTES = 4096


def _next_pow2(n: int) -> int:
    return 1 << max(0, (n - 1).bit_length())


# below this row count, batches pad to the plain next power of two: the
# absolute waste is tiny and the compile-shape set stays minimal
_QUARTER_RUNG_FLOOR = 8192
# above this row count, rungs refine to eighth-powers-of-two (≤12.5%
# padding waste): at ≥64k rows the four extra rungs per octave cost up
# to four more cached compiles per octave, but the padding rows they
# trim are pure linear scan time
_EIGHTH_RUNG_FLOOR = 65536


def _pad_rows(n: int, min_rows: int) -> int:
    """Row count for an ``n``-line batch: the next fractional-power-of-two
    rung — quarter rungs (p, 1.25p, 1.5p, 1.75p) above 8k rows, eighth
    rungs above 64k — bounding both the compile-shape set and the padding
    waste (≤25% / ≤12.5% vs ≤100% for plain pow2; device scan cost is
    linear in rows), rounded up to a multiple of ``min_rows`` (a sharded
    engine passes the mesh size, which may not be a power of two — the
    batch axis must stay divisible by it)."""
    n = max(1, n)
    if n <= _QUARTER_RUNG_FLOOR:
        rows = _next_pow2(n)
    else:
        p = _next_pow2(n) // 2  # n > p by construction
        q = p // 8 if n > _EIGHTH_RUNG_FLOOR else p // 4
        rows = p + q * (-(-(n - p) // q))
    return -(-rows // min_rows) * min_rows


@dataclasses.dataclass
class EncodedLines:
    """A padded batch: ``u8[B, T]`` with zeros beyond ``lengths``."""

    u8: np.ndarray  # uint8 [B, T]
    lengths: np.ndarray  # int32 [B] byte length clipped to T; over-long
    # lines are flagged needs_host and re-matched from the original string
    # bool [B]: non-ASCII, content NUL, or over-long. The NUL condition is
    # an invariant the gate-free device steppers depend on — byte 0 must
    # be padding-only on device (see module docstring)
    needs_host: np.ndarray
    n_lines: int


# Device scan cost is linear in the padded width T (the SCAN axis — the
# batch axis B carries the TPU's 128-lane alignment, so T only needs to be
# even for the pair scan; 32 keeps the compile-shape set small). A handful
# of over-long lines (stack frames with JSON payloads, ...) must not
# double every line's scan steps: T is capped at the rung covering this
# quantile of line lengths when that at least HALVES the full-width rung,
# and the tail is re-matched on the host via the needs_host override path
# — the same mechanism non-ASCII lines already use.
WIDTH_COVERAGE = 0.995
# capping must not buy device time with an unbounded host bill: every
# tail line re-matches through Python `re` across all device columns, so
# beyond this many tail lines the batch keeps the full width
WIDTH_MAX_HOST_TAIL = 256
DEFAULT_WIDTH_MULTIPLE = 32


def device_width(
    lengths: np.ndarray, max_line_bytes: int, pad_to_multiple: int
) -> int:
    """The padded scan width for a batch with these (true) line lengths."""

    def rung(w: int) -> int:
        return max(
            pad_to_multiple,
            _next_pow2(-(-w // pad_to_multiple) * pad_to_multiple),
        )

    full = rung(int(min(lengths.max(initial=0), max_line_bytes)))
    if len(lengths) == 0:
        return full
    cover = rung(
        int(min(np.quantile(lengths, WIDTH_COVERAGE), max_line_bytes))
    )
    if cover * 2 > full:
        return full
    if int(np.count_nonzero(lengths > cover)) > WIDTH_MAX_HOST_TAIL:
        return full
    return cover


def encode_lines(
    lines: list[str],
    max_line_bytes: int = DEFAULT_MAX_LINE_BYTES,
    pad_to_multiple: int = DEFAULT_WIDTH_MULTIPLE,
    min_rows: int = 8,
) -> EncodedLines:
    """Pack ``lines`` into a padded uint8 matrix.

    The row count is padded up to a multiple of ``min_rows`` (sharding
    needs divisibility) and the width per :func:`device_width`. Lines
    can't contain ``\\n`` (they come from the reference's split,
    AnalysisService.java:53), so a newline join is a safe single-pass
    encoding.
    """
    n = len(lines)
    if n == 0:
        return EncodedLines(
            u8=np.zeros((min_rows, pad_to_multiple), dtype=np.uint8),
            lengths=np.zeros(min_rows, dtype=np.int32),
            needs_host=np.zeros(min_rows, dtype=bool),
            n_lines=0,
        )
    try:
        blob = "\n".join(lines).encode("utf-8")
        bad_rows = None
    except UnicodeEncodeError:
        # lone surrogates reach here unmodified from the wire (json.loads
        # happily yields "\ud800" escapes as unpaired surrogates). They
        # cannot encode; replace per line and force those lines to host
        # verification — golden matches the ORIGINAL str, the device only
        # ever sees the replacement bytes, so the flag keeps them in
        # agreement (same rule as non-ASCII content).
        parts: list[bytes] = []
        bad_rows = np.zeros(n, dtype=bool)
        for i, line in enumerate(lines):
            try:
                parts.append(line.encode("utf-8"))
            except UnicodeEncodeError:
                parts.append(line.encode("utf-8", errors="replace"))
                bad_rows[i] = True
        blob = b"\n".join(parts)
    flat = np.frombuffer(blob, dtype=np.uint8)
    # line boundaries: newline positions in the joined blob
    seps = np.flatnonzero(flat == 0x0A)
    starts = np.concatenate([[0], seps + 1]).astype(np.int64)
    ends = np.concatenate([seps, [len(flat)]]).astype(np.int64)
    lengths = (ends - starts).astype(np.int32)

    # pad rows and width to rungs so jitted kernels see a small, bounded
    # set of shapes (each distinct shape costs an XLA compile)
    width = device_width(lengths, max_line_bytes, pad_to_multiple)
    rows = _pad_rows(n, min_rows)

    # fill in row chunks: a full [n, width] gather-index matrix would cost
    # ~9x the output batch in temporaries (int64 indices + bool mask) and
    # OOM on 1M-line corpora with a wide width
    u8 = np.zeros((rows, width), dtype=np.uint8)
    host_flag = np.zeros(rows, dtype=bool)
    if len(flat):
        col = np.arange(width, dtype=np.int64)[None, :]
        chunk = max(1, (64 << 20) // max(1, width))  # ~64MB of indices per chunk
        for lo in range(0, n, chunk):
            hi = min(n, lo + chunk)
            take = starts[lo:hi, None] + col
            clamped = np.minimum(lengths[lo:hi], width)
            mask = col < clamped[:, None]
            rows_u8 = np.where(mask, flat[np.clip(take, 0, len(flat) - 1)], 0)
            u8[lo:hi] = rows_u8
            # host re-match flags, accumulated chunk-wise like the fill
            # itself (a full [n, width] temporary would OOM at 1M lines):
            # non-ASCII bytes, or content NULs — zeros beyond the padding
            # count (mirrors lpn_split_fill). Keeping byte 0 padding-only
            # lets the device automata drop it from every byteset, which
            # makes the gate-free stepper paths sound.
            host_flag[lo:hi] = ((rows_u8 & 0x80) != 0).any(axis=1) | (
                (rows_u8 == 0).sum(axis=1) != (width - clamped)
            )
    over_long = np.zeros(rows, dtype=bool)
    # host re-match when the device row can't hold the full line: the
    # capped-width tail OR max_line_bytes overflow (same rule as the
    # native Corpus path: C fill flags the latter, ingest.py the former)
    over_long[:n] = (lengths > width) | (lengths > max_line_bytes)
    if bad_rows is not None:
        # replacement bytes are ASCII ('?'), invisible to host_flag above
        over_long[:n] |= bad_rows

    full_lengths = np.zeros(rows, dtype=np.int32)
    full_lengths[:n] = np.minimum(lengths, width)

    return EncodedLines(
        u8=u8,
        lengths=full_lengths,
        needs_host=host_flag | over_long,
        n_lines=n,
    )
