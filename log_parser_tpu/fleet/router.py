"""Router front-door: terminate the public transports, resolve the
tenant at the edge, proxy to the backend that owns it.

``serve --role router --backends host:port,...`` boots one of these in
front of N ordinary serving processes. The router holds NO engine — no
patterns, no jax — just the consistent-hash ring (``fleet/ring.py``),
a per-backend health view, and an :class:`~log_parser_tpu.obs.Obs`
bundle of its own (``logparser_fleet_*`` families + the ``route`` span).

Tenant resolution at the edge reuses ``runtime/tenancy.py`` verbatim
(:func:`~log_parser_tpu.runtime.tenancy.edge_tenant_id` — the same
normalization + ``_ID_RE`` validation ``TenantRegistry.resolve``
applies), so an id the backend would 400 never costs a proxy hop.

Forwarding rules (docs/OPS.md "Fleet routing & placement"):

- A backend 307 (``TenantForwarded`` / standby fence) with a
  ``Location`` inside the fleet teaches the router: the override is
  recorded on the ring and the request retries against the new owner —
  bounded hops, loop detection — so the client sees the post-move 200,
  never the redirect. A ``Location`` outside the fleet passes through
  untouched (the client's 307-follow handles it).
- A backend connect/read failure marks it down, takes it off the ring
  (its arc re-maps to the survivors) and retries the re-mapped owner;
  the health loop (fleet/placement.py) probes it back in.
- ``POST /parse/stream`` (chunked) is spliced raw — full-duplex byte
  pumps, no 307 interception mid-stream (the open-response 307 passes
  through to the client's follow logic).

The framed shim and gRPC fronts ride the same ring: the framed front
forwards Envelope frames to the owner's shim address; the gRPC front
terminates gRPC generically (raw-bytes handlers) and rides the framed
back-channel.
"""

from __future__ import annotations

import http.client
import json
import logging
import os
import socket
import socketserver
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from log_parser_tpu import _clock as pclock
from log_parser_tpu.fleet.ring import DEFAULT_VNODES, HashRing
from log_parser_tpu.obs import Obs
from log_parser_tpu.runtime import faults, pressure
from log_parser_tpu.runtime.migrate import MigrationJournal, _frame_records
from log_parser_tpu.runtime.tenancy import (
    DEFAULT_TENANT,
    TenantError,
    edge_tenant_id,
)

log = logging.getLogger(__name__)

# the fleet chaos vocabulary (tools/chaos_sweep.py --group fleet);
# tools/hygiene.py check 20 pins every key to a docs/OPS.md row AND to a
# live faults.fire site. placement_move fires in fleet/placement.py.
FAULT_SITES = {
    "route": "edge tenant resolution + ring lookup (fleet/router.py)",
    "route_backend": "one proxied backend attempt (fleet/router.py)",
    "placement_move": "placer-initiated live migration (fleet/placement.py)",
}

# request/response bodies the buffering proxy will carry — the same cap
# the backend's migration routes accept (serve/http.py _MIGRATE_MAX_BODY)
_PROXY_MAX_BODY = 64 << 20
# end-to-end hop budget for learned-forward retries: a migration chain
# is 1 hop; 4 absorbs a concurrent re-move without letting a forward
# cycle spin the router
_MAX_HOPS = 4
# hop-by-hop headers never forwarded in either direction (RFC 9110 §7.6.1)
_HOP_HEADERS = frozenset({
    "connection", "keep-alive", "proxy-authenticate", "proxy-authorization",
    "te", "trailers", "transfer-encoding", "upgrade",
})


def parse_backends(spec: str) -> list[str]:
    """``host:port,host:port`` (or full ``http://`` bases) -> normalized
    base URLs. Raises ValueError on an empty or malformed list."""
    backends: list[str] = []
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        if "://" not in part:
            part = f"http://{part}"
        parsed = urllib.parse.urlparse(part)
        if parsed.scheme != "http" or not parsed.hostname or not parsed.port:
            raise ValueError(f"bad backend {part!r}: need host:port")
        backends.append(f"http://{parsed.hostname}:{parsed.port}")
    if not backends:
        raise ValueError("--backends needs at least one host:port")
    if len(set(backends)) != len(backends):
        raise ValueError("duplicate backend in --backends")
    return backends


def _hostport(base_url: str) -> tuple[str, int]:
    parsed = urllib.parse.urlparse(base_url)
    return parsed.hostname or "127.0.0.1", int(parsed.port or 80)


def base_of(location: str) -> str | None:
    """Normalize a 307 ``Location`` to a ring-comparable base URL."""
    try:
        parsed = urllib.parse.urlparse(location)
    except ValueError:
        return None
    if parsed.scheme != "http" or not parsed.hostname or not parsed.port:
        return None
    return f"http://{parsed.hostname}:{parsed.port}"


class _BackendState:
    """Router-side health view of one backend. ``fails`` counts
    consecutive transport failures; ``down_after`` of them take the
    backend off the ring until a health probe brings it back."""

    __slots__ = ("up", "fails", "last_error", "since")

    def __init__(self) -> None:
        self.up = True
        self.fails = 0
        self.last_error = ""
        self.since = pclock.mono()


OVERRIDE_JOURNAL = "router_overrides.wal"


class OverrideJournal:
    """CRC-framed ring-override log under the router's state dir.

    Every learned placement (HTTP 307 ``Location``, framed ``migrated
    to`` refusal) and manual one (``POST /fleet/override``) is appended
    as one frame, so a router restart replays the placements the fleet
    already taught it instead of re-discovering each with a redirect
    hop. Replay applies the surviving last-record-per-tenant set through
    :meth:`~log_parser_tpu.fleet.ring.HashRing.set_override`, which is
    where stale entries self-clear: a backend that is no longer a ring
    member is refused, and an override matching the hash owner drops
    out. After replay the log is compacted to exactly the overrides the
    ring kept.

    Appends are contained: a failed write costs re-learning one
    placement after a restart, never a routed request — the ring stays
    authoritative in memory either way."""

    def __init__(self, state_dir: str):
        os.makedirs(state_dir, exist_ok=True)
        self.path = os.path.join(state_dir, OVERRIDE_JOURNAL)
        self.applied = 0
        self.stale = 0
        self.appended = 0
        self.write_errors = 0
        self._mu = threading.Lock()
        self._journal = MigrationJournal(self.path)

    def recover(self, ring: HashRing) -> dict:
        """Replay onto ``ring`` and compact. Returns counts."""
        live: dict[str, str] = {}
        for rec in MigrationJournal.replay(self.path):
            tenant = rec.get("tenant")
            if not isinstance(tenant, str) or not tenant:
                continue
            if rec.get("k") == "clear":
                live.pop(tenant, None)
            elif rec.get("k") == "override" and isinstance(
                rec.get("backend"), str
            ):
                live[tenant] = rec["backend"]
        for tenant, backend in live.items():
            # set_override returning True covers the redundant case too
            # (backend == hash owner — correctly routed, entry dropped);
            # only a non-member backend is stale
            if ring.set_override(tenant, backend):
                self.applied += 1
            else:
                self.stale += 1
        self.compact(ring)
        return {"applied": self.applied, "stale": self.stale}

    def note(self, tenant: str, backend: str | None) -> None:
        """Append one placement record (``backend=None`` is a clear)."""
        with self._mu:
            if self._journal is None:  # pragma: no cover - closed race
                return
            try:
                if backend is None:
                    self._journal.append("clear", tenant=tenant)
                else:
                    self._journal.append(
                        "override", tenant=tenant, backend=backend
                    )
                self.appended += 1
            except OSError as exc:
                self.write_errors += 1
                pressure.note_write_error(exc, "override_journal")
                log.warning("override journal append failed: %s", exc)

    def compact(self, ring: HashRing) -> None:
        """Rewrite the log to exactly the ring's live override set
        (tmp + fsync + atomic replace), so cleared and stale entries
        cannot grow the file without bound."""
        records = [
            {"k": "override", "tenant": t, "backend": b}
            for t, b in sorted(ring.overrides().items())
        ]
        raw = _frame_records(records)
        with self._mu:
            self._journal.close()
            tmp = self.path + ".compact"
            try:
                with open(tmp, "wb") as f:
                    f.write(raw)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, self.path)
            except OSError as exc:
                self.write_errors += 1
                pressure.note_write_error(exc, "override_journal")
                log.warning("override journal compaction failed: %s", exc)
            finally:
                self._journal = MigrationJournal(self.path)

    def stats(self) -> dict:
        with self._mu:
            return {
                "path": self.path,
                "applied": self.applied,
                "stale": self.stale,
                "appended": self.appended,
                "writeErrors": self.write_errors,
            }

    def close(self) -> None:
        with self._mu:
            jr, self._journal = self._journal, None
            if jr is not None:
                jr.close()


class RouterServer(ThreadingHTTPServer):
    daemon_threads = True
    request_queue_size = 128

    def handle_error(self, request, client_address) -> None:
        # a front-door eats connection aborts quietly: clients hanging
        # up mid-request (or port scanners) are routine, not tracebacks
        import sys

        exc = sys.exc_info()[1]
        if isinstance(exc, (BrokenPipeError, ConnectionResetError,
                            TimeoutError)):
            log.debug("router connection aborted from %s: %s",
                      client_address, exc)
            return
        super().handle_error(request, client_address)

    def __init__(
        self,
        address: tuple[str, int],
        backends: list[str],
        *,
        vnodes: int = DEFAULT_VNODES,
        proxy_timeout_s: float = 60.0,
        down_after: int = 2,
        obs: Obs | None = None,
        state_dir: str | None = None,
    ):
        super().__init__(address, _RouterHandler)
        self.ring = HashRing(backends, vnodes=vnodes)
        self.override_journal: OverrideJournal | None = None
        if state_dir:
            self.override_journal = OverrideJournal(state_dir)
            recovered = self.override_journal.recover(self.ring)
            if recovered["applied"] or recovered["stale"]:
                log.info(
                    "override journal replayed: %d applied, %d stale",
                    recovered["applied"], recovered["stale"],
                )
        self.all_backends = list(backends)  # membership superset, fixed
        self.proxy_timeout_s = float(proxy_timeout_s)
        self.down_after = max(1, int(down_after))
        self.obs = obs if obs is not None else Obs()
        self._lock = threading.Lock()
        self.health: dict[str, _BackendState] = {
            b: _BackendState() for b in backends
        }
        self.routed_total = self.obs.registry.counter(
            "logparser_fleet_routed_total", ("backend", "outcome"),
            max_series=256,
        )
        self.reroutes_total = self.obs.registry.counter(
            "logparser_fleet_reroutes_total", ("reason",)
        )
        self.obs.registry.register_collector("fleet", self._fleet_samples)
        # wired by serve/__main__.py --role router: the control loop and
        # the framed front; stats-only here
        self.controller = None
        self.framed_front = None
        self.grpc_front = None
        self.started_monotonic = pclock.mono()

    # --------------------------------------------------------- overrides

    def learn_override(self, tenant: str, backend: str) -> bool:
        """``set_override`` + journal: the single path every learned
        placement (HTTP 307, framed ``migrated to``, manual POST) goes
        through, so a restart replays what the fleet already taught."""
        if not self.ring.set_override(tenant, backend):
            return False
        if self.override_journal is not None:
            self.override_journal.note(tenant, backend)
        return True

    def forget_override(self, tenant: str) -> bool:
        cleared = self.ring.clear_override(tenant)
        if cleared and self.override_journal is not None:
            self.override_journal.note(tenant, None)
        return cleared

    # -------------------------------------------------------- health map

    def note_backend_error(self, backend: str, error: str) -> bool:
        """One failed transport attempt. Returns True when this crossed
        the threshold and the backend just left the ring."""
        with self._lock:
            st = self.health.get(backend)
            if st is None:
                return False
            st.fails += 1
            st.last_error = error[:200]
            if st.up and st.fails >= self.down_after:
                st.up = False
                st.since = pclock.mono()
                removed = True
            else:
                removed = False
        if removed:
            self.ring.remove(backend)
            self.reroutes_total.inc(reason="backend_down")
            log.warning("backend %s marked DOWN (%s)", backend, error)
        return removed

    def note_backend_ok(self, backend: str) -> None:
        with self._lock:
            st = self.health.get(backend)
            if st is None:
                return
            st.fails = 0
            if not st.up:
                st.up = True
                st.since = pclock.mono()
                readmitted = True
            else:
                readmitted = False
        if readmitted:
            self.ring.add(backend)
            log.info("backend %s back UP", backend)

    def backends_up(self) -> list[str]:
        with self._lock:
            return [b for b, st in self.health.items() if st.up]

    # ------------------------------------------------------------- stats

    def _fleet_samples(self):
        with self._lock:
            up = sum(1 for st in self.health.values() if st.up)
        ring = self.ring.stats()
        samples = [
            ("logparser_fleet_backends_up", {}, up),
            ("logparser_fleet_overrides", {}, len(ring["overrides"])),
        ]
        ctl = self.controller
        if ctl is not None:
            samples.extend(ctl.samples())
        return samples

    def fleet_status(self) -> dict:
        with self._lock:
            health = {
                b: {
                    "up": st.up,
                    "fails": st.fails,
                    "lastError": st.last_error,
                    "sinceS": round(pclock.mono() - st.since, 1),
                }
                for b, st in self.health.items()
            }
        status = {
            "ring": self.ring.stats(),
            "spread": self.ring.spread(),
            "backends": health,
            "uptimeS": round(pclock.mono() - self.started_monotonic, 1),
        }
        ctl = self.controller
        if ctl is not None:
            status["placement"] = ctl.stats()
        front = self.framed_front
        if front is not None:
            status["framed"] = front.stats()
        if self.override_journal is not None:
            status["overrideJournal"] = self.override_journal.stats()
        return status


class _RouterHandler(BaseHTTPRequestHandler):
    server: RouterServer
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt: str, *args) -> None:
        log.debug("%s " + fmt, self.address_string(), *args)

    # ------------------------------------------------------------ helpers

    def _send_json(self, status: int, payload: bytes,
                   headers: dict[str, str] | None = None) -> None:
        try:
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(payload)))
            for key, value in (headers or {}).items():
                self.send_header(key, value)
            self.end_headers()
            self.wfile.write(payload)
        except (BrokenPipeError, ConnectionResetError):
            self.server.obs.note_dropped("http")
            self.close_connection = True

    # ------------------------------------------------------------- routes

    def do_GET(self) -> None:
        path = self.path.split("?", 1)[0]
        if path in ("/health", "/health/live", "/health/ready", "/q/health"):
            return self._health()
        if path == "/metrics":
            try:
                self.send_response(200)
                body = self.server.obs.registry.render().encode()
                self.send_header(
                    "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
                )
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            except (BrokenPipeError, ConnectionResetError):
                self.server.obs.note_dropped("http")
                self.close_connection = True
            return
        if path == "/fleet/status":
            return self._send_json(
                200, json.dumps(self.server.fleet_status()).encode()
            )
        return self._proxy()

    def do_POST(self) -> None:
        if self.path == "/fleet/override":
            return self._fleet_override()
        return self._proxy()

    def _health(self) -> None:
        """Aggregate fleet health: UP while at least one backend serves.
        Per-backend checks mirror the single-process /q/health shape so
        the same probes work against router and backend alike."""
        up = self.server.backends_up()
        checks = []
        with self.server._lock:
            for b, st in self.server.health.items():
                checks.append({
                    "name": f"backend:{b}",
                    "status": "UP" if st.up else "DOWN",
                })
        status = "UP" if up else "DOWN"
        return self._send_json(
            200 if up else 503,
            json.dumps({"status": status, "role": "router",
                        "checks": checks}).encode(),
        )

    def _fleet_override(self) -> None:
        """``POST /fleet/override`` ``{"tenant": id, "backend": url|null}``:
        operator override surface — the manual twin of the 307-learned
        entries (runbooks: pre-warming a move, pinning a debug tenant)."""
        try:
            length = int(self.headers.get("Content-Length", 0))
            if length > 1 << 20:
                return self._send_json(413, b'{"error":"payload too large"}')
            body = json.loads(self.rfile.read(length) if length else b"{}")
        except ValueError:
            return self._send_json(400, b'{"error":"bad request body"}')
        tenant = body.get("tenant") if isinstance(body, dict) else None
        backend = body.get("backend") if isinstance(body, dict) else None
        if not isinstance(tenant, str) or not tenant:
            return self._send_json(400, b'{"error":"expected {tenant}"}')
        # same edge validation the proxy applies: an id the backends
        # would refuse can never be a routable override key
        try:
            if edge_tenant_id(tenant) is None:
                return self._send_json(
                    400, b'{"error":"cannot override the default tenant"}'
                )
        except TenantError as exc:
            return self._send_json(
                400, json.dumps({"error": str(exc)}).encode()
            )
        if backend is None:
            cleared = self.server.forget_override(tenant)
            return self._send_json(
                200, json.dumps({"cleared": cleared}).encode()
            )
        if not isinstance(backend, str) or not self.server.learn_override(
            tenant, backend
        ):
            return self._send_json(
                400, b'{"error":"backend is not a ring member"}'
            )
        return self._send_json(
            200,
            json.dumps({"tenant": tenant,
                        "owner": self.server.ring.owner(tenant)}).encode(),
        )

    # -------------------------------------------------------------- proxy

    def _proxy(self) -> None:
        server = self.server
        obs = server.obs
        rid = obs.clean_request_id(
            self.headers.get("X-Request-Id")
        ) or obs.new_request_id()
        started = obs.clock()
        raw_tenant = self.headers.get("X-Tenant")
        outcome = "ok"
        status = 200
        backend = ""
        hops = 0
        try:
            # chaos point: an injected route fault answers a structured
            # 500 below, the same containment the backend's sites have
            faults.fire("route", key=raw_tenant or DEFAULT_TENANT)
            # EDGE tenant resolution: the exact runtime/tenancy.py
            # validation, so malformed ids are refused without a hop
            tenant = edge_tenant_id(raw_tenant)
        except TenantError as exc:
            outcome, status = "invalid_tenant", exc.status
            self._send_json(
                status, json.dumps({"error": exc.reason}).encode()
            )
            self._route_done(rid, started, raw_tenant, outcome, backend,
                             hops, status)
            return
        except Exception as exc:
            outcome, status = "route_fault", 500
            self._send_json(status, json.dumps({"error": str(exc)}).encode())
            self._route_done(rid, started, raw_tenant, outcome, backend,
                             hops, status)
            return
        route_key = tenant or DEFAULT_TENANT

        chunked = "chunked" in (
            self.headers.get("Transfer-Encoding") or ""
        ).lower()
        if chunked:
            backend = server.ring.owner(route_key) or ""
            if not backend:
                outcome, status = "no_backend", 503
                self._send_json(status, b'{"error":"no backend available"}')
            else:
                outcome = self._splice(backend)
                status = {"ok": 200, "backend_error": 502}.get(outcome, 500)
            self._route_done(rid, started, raw_tenant, outcome, backend,
                             hops, status)
            return

        try:
            length = int(self.headers.get("Content-Length", 0) or 0)
        except ValueError:
            self._send_json(400, b'{"error":"bad Content-Length"}')
            self._route_done(rid, started, raw_tenant, "bad_request",
                             "", 0, 400)
            return
        if length > _PROXY_MAX_BODY:
            self._send_json(413, b'{"error":"payload too large"}')
            self._route_done(rid, started, raw_tenant, "too_large",
                             "", 0, 413)
            return
        body = self.rfile.read(length) if length else b""

        budget = pressure.retry_budget()
        attempts = 0
        seen: set[str] = set()
        while True:
            backend = server.ring.owner(route_key) or ""
            if not backend or backend in seen and hops >= _MAX_HOPS:
                outcome, status = "no_backend", 503
                self._send_json(status, b'{"error":"no backend available"}')
                break
            # retry budget: the first attempt deposits, every re-route
            # (next owner after a failure, a 307 follow) spends a token
            # — an exhausted bucket sheds instead of feeding the storm
            if attempts and budget is not None and not budget.allow(
                f"router:{backend}"
            ):
                outcome, status = "retry_shed", 503
                self._send_json(
                    status, b'{"error":"retry budget exhausted"}'
                )
                break
            attempts += 1
            if attempts == 1 and budget is not None:
                budget.note_request(f"router:{backend}")
            try:
                # chaos point: contained as one failed attempt — the
                # backend is marked down and the ring re-maps
                faults.fire("route_backend", key=backend)
                status, headers, payload = self._attempt(
                    backend, body, rid, tenant
                )
            except (OSError, http.client.HTTPException) as exc:
                server.note_backend_error(backend, str(exc))
                seen.add(backend)
                hops += 1
                if hops > _MAX_HOPS or not server.ring.backends():
                    outcome, status = "backend_error", 502
                    self._send_json(
                        status,
                        json.dumps(
                            {"error": f"backend {backend} unreachable"}
                        ).encode(),
                    )
                    break
                continue
            server.note_backend_ok(backend)
            if status == 307 and tenant is not None:
                new_base = base_of(headers.get("Location", ""))
                learned = (
                    new_base is not None
                    and new_base != backend
                    and server.learn_override(tenant, new_base)
                )
                if learned:
                    server.reroutes_total.inc(reason="forward")
                    seen.add(backend)
                    hops += 1
                    if new_base not in seen and hops <= _MAX_HOPS:
                        continue
                # hop budget spent, a forward loop, or a Location outside
                # the fleet: hand the 307 to the client's follow logic
                outcome = "forwarded"
                self._relay(status, headers, payload)
                break
            outcome = "ok" if status < 500 else "backend_5xx"
            self._relay(status, headers, payload)
            break
        self._route_done(rid, started, raw_tenant, outcome, backend,
                         hops, status)

    def _attempt(
        self, backend: str, body: bytes, rid: str, tenant: str | None
    ) -> tuple[int, dict, bytes]:
        """One buffered proxy attempt against ``backend``. Raises OSError
        / HTTPException on transport failure; HTTP statuses (307
        included) return normally."""
        host, port = _hostport(backend)
        conn = http.client.HTTPConnection(
            host, port, timeout=self.server.proxy_timeout_s
        )
        try:
            headers = {
                k: v
                for k, v in self.headers.items()
                if k.lower() not in _HOP_HEADERS
                and k.lower() not in ("host", "content-length")
            }
            headers["Host"] = f"{host}:{port}"
            headers["X-Request-Id"] = rid
            headers["Connection"] = "close"
            client = self.client_address[0] if self.client_address else ""
            prior = self.headers.get("X-Forwarded-For")
            headers["X-Forwarded-For"] = (
                f"{prior}, {client}" if prior else client
            )
            conn.request(self.command, self.path, body=body, headers=headers)
            resp = conn.getresponse()
            payload = resp.read(_PROXY_MAX_BODY + 1)
            if len(payload) > _PROXY_MAX_BODY:
                raise http.client.HTTPException(
                    f"backend response over {_PROXY_MAX_BODY} bytes"
                )
            return resp.status, dict(resp.getheaders()), payload
        finally:
            conn.close()

    def _relay(self, status: int, headers: dict, payload: bytes) -> None:
        try:
            self.send_response(status)
            for key, value in headers.items():
                if key.lower() in _HOP_HEADERS or key.lower() in (
                    "content-length", "date", "server",
                ):
                    continue
                self.send_header(key, value)
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)
        except (BrokenPipeError, ConnectionResetError):
            self.server.obs.note_dropped("http")
            self.close_connection = True

    # ----------------------------------------------------------- splice

    def _splice(self, backend: str) -> str:
        """Raw full-duplex byte splice for chunked requests
        (``POST /parse/stream``): replay the request head, then pump
        client→backend and backend→client until the backend closes.
        Returns the route outcome label."""
        host, port = _hostport(backend)
        try:
            upstream = socket.create_connection(
                (host, port), timeout=self.server.proxy_timeout_s
            )
        except OSError as exc:
            self.server.note_backend_error(backend, str(exc))
            self._send_json(502, b'{"error":"backend unreachable"}')
            return "backend_error"
        self.server.note_backend_ok(backend)
        try:
            head = [f"{self.command} {self.path} HTTP/1.1"]
            for key, value in self.headers.items():
                lk = key.lower()
                if lk in ("host", "connection"):
                    continue
                head.append(f"{key}: {value}")
            head.append(f"Host: {host}:{port}")
            head.append("Connection: close")
            upstream.sendall(("\r\n".join(head) + "\r\n\r\n").encode())

            def pump_up() -> None:
                try:
                    while True:
                        chunk = self.rfile.read1(1 << 16)
                        if not chunk:
                            break
                        upstream.sendall(chunk)
                    upstream.shutdown(socket.SHUT_WR)
                except (OSError, ValueError):
                    pass  # either side gone: the down pump notices

            feeder = threading.Thread(target=pump_up, daemon=True)
            feeder.start()
            while True:
                chunk = upstream.recv(1 << 16)
                if not chunk:
                    break
                self.wfile.write(chunk)
            self.close_connection = True
            return "ok"
        except (OSError, ValueError) as exc:
            log.debug("stream splice to %s ended: %s", backend, exc)
            self.close_connection = True
            return "stream_error"
        finally:
            try:
                upstream.close()
            except OSError:
                pass

    # ------------------------------------------------------------ account

    def _route_done(self, rid: str, started: float, raw_tenant: str | None,
                    outcome: str, backend: str, hops: int,
                    status: int) -> None:
        obs = self.server.obs
        # an id that failed edge validation is unbounded attacker input —
        # never a label value
        tenant = ("invalid" if outcome == "invalid_tenant"
                  else raw_tenant or DEFAULT_TENANT)
        duration = obs.clock() - started
        self.server.routed_total.inc(
            backend=backend or "none", outcome=outcome
        )
        # note_request ends the trace itself for non-200s; the `route`
        # span (backend + hop count) covers the successful path only
        obs.note_request("http", "route", status, tenant, duration,
                         request_id=rid, detail=outcome)
        if status == 200:
            obs.spans.end_trace(
                rid, duration, tenant=tenant, name="route",
                attrs={"backend": backend or "none", "outcome": outcome,
                       "hops": hops},
            )


# ----------------------------------------------------------- framed front


class FramedRouterFront(socketserver.ThreadingTCPServer):
    """Framed-shim front-door: Envelope frames in, Envelope frames out,
    each forwarded whole to the OWNER backend's shim address. The
    tenant rides the ``method@tenant`` envelope suffix exactly as on a
    direct shim connection; a backend refusal whose error text carries
    ``migrated to <url>`` (the framed rendering of ``TenantForwarded``)
    teaches the ring the same override the HTTP 307 does."""

    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, address: tuple[str, int], router: RouterServer,
                 shim_addrs: dict[str, tuple[str, int]]):
        super().__init__(address, _FramedFrontHandler)
        self.router = router
        # http base url -> (host, port) of that backend's framed shim
        self.shim_addrs = dict(shim_addrs)
        self.frames = 0
        self.forward_follows = 0
        self._lock = threading.Lock()

    def stats(self) -> dict:
        with self._lock:
            return {
                "frames": self.frames,
                "forwardFollows": self.forward_follows,
                "backends": {b: f"{h}:{p}"
                             for b, (h, p) in self.shim_addrs.items()},
            }


class _FramedFrontHandler(socketserver.BaseRequestHandler):
    server: FramedRouterFront

    def handle(self) -> None:
        from log_parser_tpu.shim import logparser_pb2 as pb
        from log_parser_tpu.shim.framing import (
            FramingError,
            read_frame,
            write_frame,
        )

        sock = self.request
        router = self.server.router
        while True:
            try:
                frame = read_frame(sock)
            except FramingError as exc:
                log.warning("framed front connection dropped: %s", exc)
                return
            if frame is None:
                return
            envelope = pb.Envelope()
            response: bytes
            try:
                envelope.ParseFromString(frame)
                _method, _, raw_tenant = envelope.method.partition("@")
                faults.fire("route", key=raw_tenant or DEFAULT_TENANT)
                tenant = edge_tenant_id(raw_tenant or None)
                response = self._forward(frame, envelope.method, tenant)
            except TenantError as exc:
                response = pb.Envelope(
                    method=envelope.method, error=str(exc)
                ).SerializeToString()
            except Exception as exc:  # contained per frame
                log.debug("framed front call failed: %s", exc)
                response = pb.Envelope(
                    method=envelope.method, error=f"router: {exc}"
                ).SerializeToString()
            with self.server._lock:
                self.server.frames += 1
            try:
                write_frame(sock, response)
            except OSError:
                router.obs.note_dropped("shim")
                return

    def _forward(self, frame: bytes, method: str,
                 tenant: str | None) -> bytes:
        """Proxy one frame to the owner's shim, following a bounded
        number of framed ``migrated to`` refusals the way the HTTP
        proxy follows 307s."""
        import re as _re

        from log_parser_tpu.shim import logparser_pb2 as pb
        from log_parser_tpu.shim.framing import read_frame, write_frame

        router = self.server.router
        route_key = tenant or DEFAULT_TENANT
        budget = pressure.retry_budget()
        attempts = 0
        seen: set[str] = set()
        hops = 0
        while True:
            backend = router.ring.owner(route_key)
            addr = self.server.shim_addrs.get(backend or "")
            if backend is None or addr is None:
                return pb.Envelope(
                    method=method, error="router: no backend available"
                ).SerializeToString()
            # same retry budget as the HTTP proxy: re-routes spend
            if attempts and budget is not None and not budget.allow(
                f"router:{backend}"
            ):
                return pb.Envelope(
                    method=method, error="router: retry budget exhausted"
                ).SerializeToString()
            attempts += 1
            if attempts == 1 and budget is not None:
                budget.note_request(f"router:{backend}")
            try:
                faults.fire("route_backend", key=backend)
                with socket.create_connection(
                    addr, timeout=router.proxy_timeout_s
                ) as up:
                    up.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                    write_frame(up, frame)
                    reply = read_frame(up)
            except (OSError, ConnectionError) as exc:
                router.note_backend_error(backend, str(exc))
                seen.add(backend)
                hops += 1
                if hops > _MAX_HOPS or not router.ring.backends():
                    return pb.Envelope(
                        method=method,
                        error=f"router: backend {backend} unreachable",
                    ).SerializeToString()
                continue
            router.note_backend_ok(backend)
            if reply is None:
                return pb.Envelope(
                    method=method,
                    error=f"router: backend {backend} closed mid-call",
                ).SerializeToString()
            env = pb.Envelope()
            env.ParseFromString(reply)
            moved = _re.search(r"migrated to (\S+)", env.error or "")
            if moved and tenant is not None:
                new_base = base_of(moved.group(1).rstrip(";,"))
                if (
                    new_base is not None
                    and new_base != backend
                    and router.learn_override(tenant, new_base)
                    and new_base not in seen
                    and hops < _MAX_HOPS
                ):
                    router.reroutes_total.inc(reason="forward")
                    with self.server._lock:
                        self.server.forward_follows += 1
                    seen.add(backend)
                    hops += 1
                    continue
            return reply


# ------------------------------------------------------------- gRPC front


def make_grpc_front(router: RouterServer, framed_front: FramedRouterFront,
                    host: str, port: int, max_workers: int = 8):
    """Generic gRPC front: terminate ``/logparser.LogParser/<Method>``
    with raw-bytes handlers (no per-message schema — the router never
    parses payloads) and ride the framed back-channel to the owner's
    shim. Returns the started server, or None when grpcio is absent."""
    try:
        import grpc
    except ImportError:
        log.warning("grpc front disabled: grpcio is not installed")
        return None
    from concurrent import futures

    from log_parser_tpu.shim import logparser_pb2 as pb

    def unary(method_name: str):
        def call(request: bytes, context) -> bytes:
            tenant = None
            for key, value in context.invocation_metadata() or ():
                if key == "x-tenant":
                    tenant = value or None
            try:
                tenant = edge_tenant_id(tenant)
            except TenantError as exc:
                context.abort(
                    grpc.StatusCode.INVALID_ARGUMENT, exc.reason
                )
            wire_method = (
                f"{method_name}@{tenant}" if tenant else method_name
            )
            envelope = pb.Envelope(method=wire_method, payload=request)
            handler = _FramedFrontHandler.__new__(_FramedFrontHandler)
            handler.server = framed_front
            reply = pb.Envelope()
            reply.ParseFromString(
                handler._forward(
                    envelope.SerializeToString(), wire_method, tenant
                )
            )
            if reply.error:
                context.abort(grpc.StatusCode.UNAVAILABLE, reply.error)
            return reply.payload

        return grpc.unary_unary_rpc_method_handler(
            call,
            request_deserializer=None,  # raw bytes through
            response_serializer=None,
        )

    class _Generic(grpc.GenericRpcHandler):
        def service(self, handler_call_details):
            path = handler_call_details.method or ""
            prefix = "/logparser.LogParser/"
            if not path.startswith(prefix):
                return None
            return unary(path[len(prefix):])

    server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
    server.add_generic_rpc_handlers((_Generic(),))
    server.add_insecure_port(f"{host}:{port}")
    server.start()
    return server


def make_router(
    host: str,
    port: int,
    backends: list[str],
    *,
    vnodes: int = DEFAULT_VNODES,
    proxy_timeout_s: float = 60.0,
    down_after: int = 2,
    state_dir: str | None = None,
) -> RouterServer:
    return RouterServer(
        (host, port),
        backends,
        vnodes=vnodes,
        proxy_timeout_s=proxy_timeout_s,
        down_after=down_after,
        state_dir=state_dir,
    )
