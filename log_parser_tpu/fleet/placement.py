"""Signal-driven placement: the fleet control loop.

One thread per router polls every backend's ``/q/health`` +
``/metrics`` and converts the PR 14–15 signal families into actions:

=====================  =========================================  ============================
signal                 trigger                                    action
=====================  =========================================  ============================
health probe fails     ``down_after`` consecutive failures        ring.remove (arc re-maps);
                                                                  probe keeps running, ring.add
                                                                  on recovery
``slo_burn_rate``      > ``burn_threshold`` for ``burn_polls``    move the backend's hottest
                       consecutive polls                          tenant to the least-loaded
                                                                  backend
per-tenant sheds       429/503 rate for one tenant above          move THAT tenant
(``requests_total``)   ``shed_rate``/s over the poll window
residency thrash       ``tenant_builds_total`` delta ≥            move the hottest tenant
                       ``thrash_rebuilds`` in one window          (residency pressure follows
                                                                  traffic)
=====================  =========================================  ============================

Moves are LIVE MIGRATIONS: ``POST /admin/migrate`` on the source drives
the full ``runtime/migrate.py`` protocol against the chosen target, and
on success the controller installs the router override directly — the
next request never pays the 307 hop. A per-tenant cooldown
(``move_cooldown_s``) stops a flapping signal from ping-ponging a
tenant between backends.

The same scrape feeds :class:`~log_parser_tpu.fleet.budget.FleetBudget`:
per-backend request deltas become traffic weights, and changed shares
are pushed through each backend's ``POST /admin/budget``.
"""

from __future__ import annotations

import json
import logging
import re
import threading
import time
import urllib.error
import urllib.request

from log_parser_tpu import _clock as pclock
from log_parser_tpu.fleet.budget import FleetBudget
from log_parser_tpu.runtime import faults
from log_parser_tpu.runtime.tenancy import DEFAULT_TENANT

log = logging.getLogger(__name__)

_SERIES = re.compile(r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)$')
_LABEL = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')

_SHED_STATUSES = frozenset({"429", "503"})


def parse_prom(text: str) -> dict[str, list[tuple[dict[str, str], float]]]:
    """Minimal Prometheus text parse: name -> [(labels, value)]."""
    out: dict[str, list[tuple[dict[str, str], float]]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SERIES.match(line)
        if not m:
            continue
        name, raw_labels, raw_value = m.groups()
        try:
            value = float(raw_value)
        except ValueError:
            continue
        labels = {
            k: v.replace('\\"', '"').replace("\\\\", "\\")
            for k, v in _LABEL.findall(raw_labels or "")
        }
        out.setdefault(name, []).append((labels, value))
    return out


class _Snapshot:
    """One backend's counters at one poll — deltas against the previous
    snapshot are the window signals."""

    __slots__ = ("tenant_requests", "tenant_sheds", "builds", "burn", "when")

    def __init__(self, metrics: dict, when: float):
        self.when = when
        self.tenant_requests: dict[str, float] = {}
        self.tenant_sheds: dict[str, float] = {}
        for labels, value in metrics.get("logparser_requests_total", ()):
            tenant = labels.get("tenant", DEFAULT_TENANT)
            self.tenant_requests[tenant] = (
                self.tenant_requests.get(tenant, 0.0) + value
            )
            if labels.get("status") in _SHED_STATUSES:
                self.tenant_sheds[tenant] = (
                    self.tenant_sheds.get(tenant, 0.0) + value
                )
        self.builds = sum(
            v for _, v in metrics.get("logparser_tenant_builds_total", ())
        )
        burns = [v for _, v in metrics.get("logparser_slo_burn_rate", ())]
        self.burn = max(burns) if burns else 0.0


class FleetController:
    def __init__(
        self,
        router,
        *,
        poll_s: float = 2.0,
        burn_threshold: float = 1.0,
        burn_polls: int = 3,
        shed_rate: float = 1.0,
        thrash_rebuilds: int = 3,
        move_cooldown_s: float = 30.0,
        probe_timeout_s: float = 2.0,
        migrate_timeout_s: float = 120.0,
        retry_after_s: int = 2,
        budget: FleetBudget | None = None,
        clock=pclock.mono,
    ):
        self.router = router
        self.poll_s = float(poll_s)
        self.burn_threshold = float(burn_threshold)
        self.burn_polls = max(1, int(burn_polls))
        self.shed_rate = float(shed_rate)
        self.thrash_rebuilds = max(1, int(thrash_rebuilds))
        self.move_cooldown_s = float(move_cooldown_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self.migrate_timeout_s = float(migrate_timeout_s)
        self.retry_after_s = int(retry_after_s)
        self.budget = budget
        self.clock = clock
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._prev: dict[str, _Snapshot] = {}
        self._burn_streak: dict[str, int] = {}
        self._last_move: dict[str, float] = {}  # tenant -> clock()
        self._window: dict[str, float] = {}  # backend -> requests last poll
        self.polls = 0
        self.moves_failed = 0
        self.last_errors: dict[str, str] = {}
        self.moves_total = router.obs.registry.counter(
            "logparser_fleet_moves_total", ("reason",)
        )

    # ---------------------------------------------------------- lifecycle

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="fleet-placement", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=self.poll_s + self.probe_timeout_s + 1)
            self._thread = None

    def _run(self) -> None:
        while not pclock.wait(self._stop, self.poll_s):
            try:
                self.tick()
            except Exception:
                log.exception("placement tick failed")

    # --------------------------------------------------------------- poll

    def _get(self, backend: str, path: str) -> tuple[int, bytes]:
        req = urllib.request.Request(backend + path, method="GET")
        try:
            with urllib.request.urlopen(
                req, timeout=self.probe_timeout_s
            ) as resp:
                return resp.status, resp.read(4 << 20)
        except urllib.error.HTTPError as exc:
            return exc.code, exc.read() if exc.fp else b""

    def tick(self) -> list[dict]:
        """One control round: probe, diff, act. Returns the moves
        executed (for tests and /fleet/status)."""
        router = self.router
        now = self.clock()
        window: dict[str, float] = {}
        snaps: dict[str, _Snapshot] = {}
        for backend in router.all_backends:
            try:
                status, _ = self._get(backend, "/q/health")
                if status != 200:
                    raise OSError(f"health answered {status}")
                _, body = self._get(backend, "/metrics")
            except (OSError, urllib.error.URLError) as exc:
                router.note_backend_error(backend, str(exc))
                self.last_errors[backend] = str(exc)[:200]
                self._burn_streak.pop(backend, None)
                self._prev.pop(backend, None)
                continue
            router.note_backend_ok(backend)
            self.last_errors.pop(backend, None)
            snap = _Snapshot(parse_prom(body.decode("utf-8", "replace")), now)
            snaps[backend] = snap
            prev = self._prev.get(backend)
            if prev is not None:
                window[backend] = max(
                    0.0,
                    sum(snap.tenant_requests.values())
                    - sum(prev.tenant_requests.values()),
                )
            else:
                window[backend] = 0.0

        moves = []
        for backend, snap in snaps.items():
            prev = self._prev.get(backend)
            move = self._decide(backend, snap, prev, window)
            if move is not None:
                moves.append(move)
        self._prev = snaps
        with self._lock:
            self._window = window
        self.polls += 1

        if self.budget is not None and self.budget.enabled and window:
            self._push_budgets(self.budget.recompute(window))
        return moves

    # ------------------------------------------------------------ signals

    def _decide(self, backend: str, snap: _Snapshot,
                prev: _Snapshot | None, window: dict) -> dict | None:
        if prev is None:
            self._burn_streak[backend] = 0
            return None
        dt = max(1e-3, snap.when - prev.when)

        if snap.burn > self.burn_threshold:
            self._burn_streak[backend] = self._burn_streak.get(backend, 0) + 1
        else:
            self._burn_streak[backend] = 0

        # per-tenant shed rate beats the backend-wide signals: the
        # offender is named, move exactly that tenant
        for tenant in snap.tenant_sheds:
            delta = snap.tenant_sheds[tenant] - prev.tenant_sheds.get(
                tenant, 0.0
            )
            if delta / dt >= self.shed_rate and self._movable(tenant):
                return self._move(backend, tenant, "quota_shed", window)

        if self._burn_streak.get(backend, 0) >= self.burn_polls:
            hot = self._hottest(backend, snap, prev)
            if hot is not None:
                self._burn_streak[backend] = 0
                return self._move(backend, hot, "slo_burn", window)

        if snap.builds - prev.builds >= self.thrash_rebuilds:
            hot = self._hottest(backend, snap, prev)
            if hot is not None:
                return self._move(backend, hot, "residency_thrash", window)
        return None

    def _hottest(self, backend: str, snap: _Snapshot,
                 prev: _Snapshot | None) -> str | None:
        deltas = {
            tenant: count
            - (prev.tenant_requests.get(tenant, 0.0) if prev else 0.0)
            for tenant, count in snap.tenant_requests.items()
            if self._movable(tenant)
        }
        deltas = {t: d for t, d in deltas.items() if d > 0}
        if not deltas:
            return None
        return max(deltas, key=deltas.get)

    def _movable(self, tenant: str) -> bool:
        if not tenant or tenant in (DEFAULT_TENANT, "invalid"):
            return False
        last = self._last_move.get(tenant)
        return last is None or self.clock() - last >= self.move_cooldown_s

    # -------------------------------------------------------------- moves

    def _target_for(self, source: str, window: dict) -> str | None:
        candidates = [
            b for b in self.router.backends_up() if b != source
        ]
        if not candidates:
            return None
        return min(candidates, key=lambda b: window.get(b, 0.0))

    def _move(self, source: str, tenant: str, reason: str,
              window: dict) -> dict | None:
        target = self._target_for(source, window)
        if target is None:
            return None
        self._last_move[tenant] = self.clock()  # cooldown even on failure
        outcome = "ok"
        try:
            # chaos point: a failed move leaves the tenant owned by the
            # source — the trigger simply fires again next window
            faults.fire("placement_move", key=tenant)
            body = json.dumps({
                "tenant": tenant,
                "target": target,
                "retryAfterS": self.retry_after_s,
            }).encode()
            req = urllib.request.Request(
                source + "/admin/migrate", data=body,
                headers={"Content-Type": "application/json"}, method="POST",
            )
            with urllib.request.urlopen(
                req, timeout=self.migrate_timeout_s
            ) as resp:
                if resp.status != 200:
                    raise OSError(f"migrate answered {resp.status}")
        except Exception as exc:
            self.moves_failed += 1
            outcome = str(exc)[:200]
            log.warning("move %s %s -> %s failed: %s",
                        tenant, source, target, exc)
            return {"tenant": tenant, "from": source, "to": target,
                    "reason": reason, "outcome": outcome}
        self.router.ring.set_override(tenant, target)
        self.moves_total.inc(reason=reason)
        log.info("moved tenant %s %s -> %s (%s)",
                 tenant, source, target, reason)
        return {"tenant": tenant, "from": source, "to": target,
                "reason": reason, "outcome": outcome}

    # ------------------------------------------------------------- budget

    def _push_budgets(self, changed: dict[str, dict]) -> None:
        for backend, assignment in changed.items():
            try:
                req = urllib.request.Request(
                    backend + "/admin/budget",
                    data=json.dumps(assignment).encode(),
                    headers={"Content-Type": "application/json"},
                    method="POST",
                )
                with urllib.request.urlopen(
                    req, timeout=self.probe_timeout_s
                ):
                    pass
            except (OSError, urllib.error.URLError) as exc:
                log.warning("budget push to %s failed: %s", backend, exc)

    # -------------------------------------------------------------- stats

    def samples(self):
        out = []
        if self.budget is not None:
            out.extend(self.budget.samples())
        return out

    def stats(self) -> dict:
        with self._lock:
            window = dict(self._window)
        return {
            "polls": self.polls,
            "windowRequests": window,
            "burnStreaks": dict(self._burn_streak),
            "movesFailed": self.moves_failed,
            "lastErrors": dict(self.last_errors),
            "cooldowns": len(self._last_move),
            "budget": self.budget.shares() if self.budget else {},
        }
