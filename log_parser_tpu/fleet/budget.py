"""Fleet-arbitrated budgets: router-assigned shares of one fleet-wide
cache/residency allowance, replacing the per-process ``--line-cache-mb``
and ``--tenant-budget-mb`` constants.

The arbiter splits ``--fleet-cache-mb`` / ``--fleet-tenant-budget-mb``
across live backends proportional to the request traffic each one
actually observed over the last window (requests_total deltas scraped
by fleet/placement.py). An idle backend keeps a floor share — a cold
backend with zero traffic must still be able to warm its first tenant —
and shares only re-push when they drift past a hysteresis band, so a
noisy 51/49 split does not thrash the backends' eviction loops.
"""

from __future__ import annotations

import threading

# never hand a backend less than this, whatever traffic says
MIN_SHARE_MB = 8.0
# re-push only when a share moved by this fraction of its previous value
HYSTERESIS = 0.10


class FleetBudget:
    def __init__(self, cache_mb: float, tenant_budget_mb: float):
        self.cache_mb = max(0.0, float(cache_mb))
        self.tenant_budget_mb = max(0.0, float(tenant_budget_mb))
        self._lock = threading.Lock()
        self._shares: dict[str, dict[str, float]] = {}
        self.rebalances = 0

    @property
    def enabled(self) -> bool:
        return self.cache_mb > 0 or self.tenant_budget_mb > 0

    def _split(self, total_mb: float, traffic: dict[str, float]) -> dict:
        if total_mb <= 0 or not traffic:
            return {}
        floor = min(MIN_SHARE_MB, total_mb / max(1, len(traffic)))
        pool = total_mb - floor * len(traffic)
        volume = sum(traffic.values())
        shares = {}
        for backend, observed in traffic.items():
            weight = (observed / volume) if volume > 0 else 1 / len(traffic)
            shares[backend] = round(floor + max(0.0, pool) * weight, 2)
        return shares

    def recompute(self, traffic: dict[str, float]) -> dict[str, dict]:
        """``{backend: requests-this-window}`` -> the backends whose
        assignment changed enough to push: ``{backend: {"lineCacheMb":
        x, "tenantBudgetMb": y}}``. Call with every UP backend present
        (zero traffic included) so floors are handed out fleet-wide."""
        cache = self._split(self.cache_mb, traffic)
        tenant = self._split(self.tenant_budget_mb, traffic)
        changed: dict[str, dict] = {}
        with self._lock:
            for backend in traffic:
                assignment = {}
                if self.cache_mb > 0:
                    assignment["lineCacheMb"] = cache[backend]
                if self.tenant_budget_mb > 0:
                    assignment["tenantBudgetMb"] = tenant[backend]
                if not assignment:
                    continue
                prev = self._shares.get(backend)
                if prev is None or any(
                    abs(assignment[k] - prev.get(k, 0.0))
                    > HYSTERESIS * max(prev.get(k, 0.0), MIN_SHARE_MB)
                    for k in assignment
                ):
                    self._shares[backend] = assignment
                    changed[backend] = assignment
            if changed:
                self.rebalances += 1
            # a backend that left the fleet forgets its share: when it
            # returns it re-earns one from live traffic
            for gone in set(self._shares) - set(traffic):
                del self._shares[gone]
        return changed

    def shares(self) -> dict[str, dict[str, float]]:
        with self._lock:
            return {b: dict(s) for b, s in self._shares.items()}

    def samples(self):
        """Registry-collector view: one gauge sample per (backend, kind)."""
        out = []
        for backend, share in self.shares().items():
            if "lineCacheMb" in share:
                out.append((
                    "logparser_fleet_budget_mb",
                    {"backend": backend, "kind": "line_cache"},
                    share["lineCacheMb"],
                ))
            if "tenantBudgetMb" in share:
                out.append((
                    "logparser_fleet_budget_mb",
                    {"backend": backend, "kind": "tenant"},
                    share["tenantBudgetMb"],
                ))
        return out
