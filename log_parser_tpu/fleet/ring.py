"""Consistent-hash ring with virtual nodes — the router's tenant map.

Each backend owns ``vnodes`` points on a 64-bit ring
(``blake2b(backend#i)``); a tenant routes to the first point clockwise
of ``blake2b(tenant)``. Properties the fleet depends on:

- **Stability**: adding or removing one backend re-maps only the tenants
  whose arc it owned (~1/N of the keyspace), so a rolling restart does
  not reshuffle the whole fleet's residency.
- **Spread**: virtual nodes smooth the arc lengths; 64 vnodes keeps the
  per-backend share within a few percent of uniform for small N.
- **Overrides**: live migrations (runtime/migrate.py) deliberately break
  the hash placement — the router learns the new owner from the 307
  ``Location`` envelope and records a per-tenant override here. The
  override IS the steady state: the source's forward entry can be
  dropped once the router map has converged. Overrides pointing at a
  backend that leaves the ring die with it (the hash placement takes
  back over), and an override that matches the hash owner is dropped as
  redundant.

Thread-safe: the router's handler threads and the placement loop share
one ring.
"""

from __future__ import annotations

import bisect
import hashlib
import threading

DEFAULT_VNODES = 64


def _point(key: str) -> int:
    return int.from_bytes(
        hashlib.blake2b(key.encode("utf-8", "replace"), digest_size=8).digest(),
        "big",
    )


class HashRing:
    def __init__(self, backends: list[str] | None = None,
                 vnodes: int = DEFAULT_VNODES):
        if vnodes < 1:
            raise ValueError("vnodes must be >= 1")
        self.vnodes = int(vnodes)
        self._lock = threading.Lock()
        self._points: list[int] = []  # sorted vnode hashes
        self._owners: list[str] = []  # parallel: backend per point
        self._backends: list[str] = []  # membership, insertion order
        self._overrides: dict[str, str] = {}  # tenant -> backend
        self.remaps = 0  # membership changes (add/remove)
        for b in backends or ():
            self.add(b)

    # -------------------------------------------------------- membership

    def add(self, backend: str) -> bool:
        with self._lock:
            if backend in self._backends:
                return False
            for i in range(self.vnodes):
                p = _point(f"{backend}#{i}")
                at = bisect.bisect_left(self._points, p)
                self._points.insert(at, p)
                self._owners.insert(at, backend)
            self._backends.append(backend)
            self.remaps += 1
            # an override targeting a returning backend is stale only if
            # it now matches the hash owner — drop the redundant ones
            for t in [t for t, b in self._overrides.items()
                      if b == self._owner_locked(t)]:
                del self._overrides[t]
            return True

    def remove(self, backend: str) -> bool:
        with self._lock:
            if backend not in self._backends:
                return False
            keep = [(p, o) for p, o in zip(self._points, self._owners)
                    if o != backend]
            self._points = [p for p, _ in keep]
            self._owners = [o for _, o in keep]
            self._backends.remove(backend)
            self.remaps += 1
            # overrides pointing at the dead backend die with it: the
            # hash placement (minus the backend's arcs) takes back over
            for t in [t for t, b in self._overrides.items() if b == backend]:
                del self._overrides[t]
            return True

    def backends(self) -> list[str]:
        with self._lock:
            return list(self._backends)

    def __contains__(self, backend: str) -> bool:
        with self._lock:
            return backend in self._backends

    def __len__(self) -> int:
        with self._lock:
            return len(self._backends)

    # ----------------------------------------------------------- routing

    def _owner_locked(self, tenant_id: str) -> str | None:
        if not self._points:
            return None
        at = bisect.bisect_right(self._points, _point(tenant_id))
        return self._owners[at % len(self._points)]

    def owner(self, tenant_id: str) -> str | None:
        """The backend serving ``tenant_id``: its override when one is
        installed, the clockwise vnode owner otherwise. None on an
        empty ring."""
        with self._lock:
            override = self._overrides.get(tenant_id)
            if override is not None:
                return override
            return self._owner_locked(tenant_id)

    def hash_owner(self, tenant_id: str) -> str | None:
        """The pure hash placement, ignoring overrides — what ``owner``
        converges back to once an override is cleared."""
        with self._lock:
            return self._owner_locked(tenant_id)

    # --------------------------------------------------------- overrides

    def set_override(self, tenant_id: str, backend: str) -> bool:
        """Record a learned placement (307 ``Location`` or a completed
        placement move). Only ring members are accepted — a forward to
        an address outside the fleet is the client's business, not the
        map's. Redundant overrides (matching the hash owner) clear any
        existing entry instead."""
        with self._lock:
            if backend not in self._backends:
                return False
            if self._owner_locked(tenant_id) == backend:
                self._overrides.pop(tenant_id, None)
                return True
            self._overrides[tenant_id] = backend
            return True

    def clear_override(self, tenant_id: str) -> bool:
        with self._lock:
            return self._overrides.pop(tenant_id, None) is not None

    def overrides(self) -> dict[str, str]:
        with self._lock:
            return dict(self._overrides)

    # ------------------------------------------------------------- stats

    def spread(self) -> dict[str, int]:
        """Vnode-arc share per backend over a 16k-key probe — a cheap
        uniformity diagnostic for /fleet/status, not a load measure."""
        with self._lock:
            if not self._points:
                return {}
            counts = {b: 0 for b in self._backends}
            for i in range(16384):
                counts[self._owner_locked(f"probe-{i}")] += 1
            return counts

    def stats(self) -> dict:
        with self._lock:
            return {
                "backends": list(self._backends),
                "vnodes": self.vnodes,
                "points": len(self._points),
                "overrides": dict(self._overrides),
                "remaps": self.remaps,
            }
