"""Fleet front-door: consistent-hash tenant routing, signal-driven
placement, and fleet-arbitrated budgets (docs/OPS.md "Fleet routing &
placement").

One router process (``serve --role router --backends host:port,...``)
terminates the public transports, resolves the tenant id at the edge
(the exact ``runtime/tenancy.py`` extraction), and proxies each request
to one of N backend serving processes picked by consistent hashing over
a ring with virtual nodes (``ring.py``). Tenant moves are LIVE
MIGRATIONS through ``runtime/migrate.py`` — the 307 ``TenantForwarded``
envelope is the move mechanism, the router's ring override map is the
steady state (``router.py``). A control loop (``placement.py``) polls
backend ``/metrics`` + ``/q/health`` and converts sustained SLO burn,
quota shedding, or residency thrash into those moves; ``budget.py``
re-arbitrates the engine-local cache/residency budgets from observed
per-tenant traffic.
"""

from log_parser_tpu.fleet.budget import FleetBudget
from log_parser_tpu.fleet.placement import FleetController
from log_parser_tpu.fleet.ring import HashRing
from log_parser_tpu.fleet.router import RouterServer, make_router

__all__ = [
    "FleetBudget",
    "FleetController",
    "HashRing",
    "RouterServer",
    "make_router",
]
