"""Global invariants swept after every schedule op.

Each invariant has a pinned id (``SIM-I1``..``SIM-I5``) that appears in
failure output, in the sweep JSON artifact and in the docs/OPS.md table —
hygiene check 22 keeps the three in lockstep.  A check receives the fleet
plus the event the last op produced and returns violation strings
(prefixed with its id by the sweep).

The checks only *read*: all fleet mutation happens in schedule ops, so a
sweep never perturbs the state it is judging.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

from log_parser_tpu.sim.fleet import MAX_FORWARD_HOPS, SimFleet


@dataclass(frozen=True)
class Invariant:
    id: str
    title: str
    description: str
    check: Callable[[SimFleet, dict], list[str]]


def _check_exactly_one_owner(fleet: SimFleet, event: dict) -> list[str]:
    out = []
    for tenant in fleet.tenants:
        acceptors = [
            name for name, node in fleet.nodes.items()
            if node.resident(tenant) and node.accepts(tenant)
        ]
        if len(acceptors) > 1:
            # a just-rebooted stale primary is tolerated until its next
            # ship is rejected by the standby's higher epoch, and the
            # pair standby is tolerated while this tenant's release
            # notice is still in flight to it (both documented
            # convergence windows); anything else is split-brain
            live = [n for n in acceptors if n not in fleet.fencing_pending]
            if tenant in fleet.release_unshipped:
                live = [n for n in live if n != fleet.standby_name]
            if len(live) > 1:
                out.append(
                    f"tenant {tenant}: {sorted(live)} all accept writes"
                )
    return out


def _check_frequency_parity(fleet: SimFleet, event: dict) -> list[str]:
    out = []
    if event.get("op") == "serve" and event.get("ok") \
            and fleet.parity_exact and event.get("parity") is False:
        out.append(
            f"tenant {event['tenant']}: served events diverged from the"
            f" fault-free control on {event.get('node')}"
        )
    if event.get("op") == "quiesce":
        for tenant, lag in event.get("lags", {}).items():
            if lag:
                out.append(
                    f"tenant {tenant}: replication wedged —"
                    f" {lag} bytes unshipped after quiesce"
                )
        for tenant, why in event.get("state_diffs", {}).items():
            out.append(f"tenant {tenant}: {why}")
    return out


def _check_no_unexplained_5xx(fleet: SimFleet, event: dict) -> list[str]:
    if event.get("op") == "serve" and not event.get("ok", True) \
            and event.get("reason") is None:
        return [
            f"tenant {event['tenant']}: request failed with no active"
            f" fault to blame (chain {event.get('chain')})"
        ]
    return []


def _check_forwards_quiesce(fleet: SimFleet, event: dict) -> list[str]:
    out = []
    for tenant in fleet.tenants:
        chain = fleet.route_chain(tenant)
        if len(chain) > MAX_FORWARD_HOPS:
            out.append(
                f"tenant {tenant}: forward loop {' -> '.join(chain)}"
            )
    for tenant, why in event.get("unservable", {}).items():
        out.append(
            f"tenant {tenant}: still not servable after quiesce ({why})"
        )
    return out


def _check_idempotent_replay(fleet: SimFleet, event: dict) -> list[str]:
    return [
        f"node {name}: second recover() changed state — {why}"
        for name, why in event.get("replay_diffs", {}).items()
    ]


INVARIANTS: tuple[Invariant, ...] = (
    Invariant(
        "SIM-I1", "exactly one owner",
        "No tenant ever has two live nodes accepting writes (fenced"
        " standby and forwarded source do not count; a rebooted stale"
        " primary is tolerated only until its next rejected ship).",
        _check_exactly_one_owner,
    ),
    Invariant(
        "SIM-I2", "frequency parity",
        "Every accepted request produces the same event projection as a"
        " fault-free control engine, and after quiesce the owner's"
        " recovered frequency state matches the control byte-for-byte"
        " (count-only after a backwards wall step; replication fully"
        " drained).",
        _check_frequency_parity,
    ),
    Invariant(
        "SIM-I3", "no unexplained 5xx",
        "Every failed request is attributable to an active fault (dead"
        " node, fenced standby, truncated forward chain).",
        _check_no_unexplained_5xx,
    ),
    Invariant(
        "SIM-I4", "forwards quiesce",
        "Forward chains never loop, and once every fault is lifted each"
        " tenant becomes servable again.",
        _check_forwards_quiesce,
    ),
    Invariant(
        "SIM-I5", "idempotent replay",
        "Running every node's recover() a second time changes nothing:"
        " roles, fences and forwards are fixpoints.",
        _check_idempotent_replay,
    ),
)


def sweep(fleet: SimFleet, event: dict) -> list[str]:
    """Run every invariant against the post-op state; returns id-prefixed
    violation strings."""
    out = []
    for inv in INVARIANTS:
        for msg in inv.check(fleet, event):
            out.append(f"{inv.id}: {msg}")
    return out
