"""Schedule interpreter: build a fleet, run the ops, sweep invariants.

``run_schedule`` owns the whole lifecycle — fresh state dirs, virtual
clock installed into the process-wide switchboard, fleet build, one op at
a time with an invariant sweep after each, then the quiesce phase (every
fault lifted, time advanced past every backoff, pumps and failover probes
driven to a fixpoint) and a final deep sweep.  The event log carries only
logical names — node letters, tenant ids, op outcomes — never filesystem
paths, so the sha256 digest over it is stable across runs and machines:
*byte-identical replay* means equal digests.

``minimize`` shrinks a failing schedule to the failing prefix, then
greedily drops ops that aren't needed to reproduce the violation — each
trial is a full fresh ``run_schedule``, which determinism makes exact.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import tempfile
from dataclasses import dataclass, field

from log_parser_tpu import _clock as pclock
from log_parser_tpu.sim.clock import VirtualClock
from log_parser_tpu.sim.fleet import SimFleet, write_tenant_root
from log_parser_tpu.sim.invariants import sweep
from log_parser_tpu.sim.schedule import generate_schedule

_QUIESCE_ROUNDS = 8
_QUIESCE_STEP_S = 21  # > the 15s ship-backoff cap and the 5s failover bar


@dataclass
class SimResult:
    schedule: list
    events: list
    violations: list
    digest: str
    failed_at: int | None = None
    seed: int | None = None

    @property
    def ok(self) -> bool:
        return not self.violations

    def to_dict(self) -> dict:
        return {
            "seed": self.seed,
            "ok": self.ok,
            "failed_at": self.failed_at,
            "violations": self.violations,
            "digest": self.digest,
            "n_ops": len(self.schedule),
        }


def _digest(schedule: list, events: list, violations: list) -> str:
    doc = {
        "schedule": [list(op) for op in schedule],
        "events": events,
        "violations": violations,
    }
    blob = json.dumps(doc, sort_keys=True, separators=(",", ":"),
                      default=str)
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()


def _apply(fleet: SimFleet, clk: VirtualClock, op: tuple) -> dict:
    kind = op[0]
    if kind == "serve":
        out = fleet.serve(op[1], op[2])
        out["op"] = "serve"
        return out
    if kind == "advance":
        clk.advance(op[1])
        return {"op": "advance", "s": op[1]}
    if kind == "pump":
        return {"op": "pump", "node": op[1],
                "outcomes": fleet.pump(op[1])}
    if kind == "supervise":
        return {"op": "supervise", "verdict": fleet.supervise()}
    if kind == "promote":
        return {"op": "promote", "result": fleet.promote()}
    if kind == "migrate":
        out = fleet.migrate(op[1], op[2], crash_after=op[3])
        out.update(op="migrate", tenant=op[1])
        return out
    if kind == "kill":
        return {"op": "kill", "node": op[1], "ok": fleet.kill(op[1])}
    if kind == "revive":
        summary = fleet.revive(op[1])
        node = fleet.nodes[op[1]]
        role = None
        if node.replicator is not None:
            role = node.replicator.role
        return {"op": "revive", "node": op[1],
                "ok": summary is not None, "role": role}
    if kind == "partition":
        fleet.net.partition(op[1], op[2])
        return {"op": "partition", "edge": [op[1], op[2]]}
    if kind == "heal":
        fleet.net.heal()
        return {"op": "heal"}
    if kind in ("drop", "dup", "defer"):
        getattr(fleet.net, f"{kind}_next").add((op[1], op[2]))
        return {"op": kind, "edge": [op[1], op[2]]}
    if kind == "flush_net":
        return {"op": "flush_net", "delivered": fleet.net.flush()}
    if kind == "enospc":
        return {"op": "enospc", "degraded": fleet.enter_disk_hard()}
    if kind == "disk_recover":
        return {"op": "disk_recover", "rearmed": fleet.recover_disk()}
    if kind == "clock_pause":
        clk.pause_wall(op[1])
        return {"op": "clock_pause", "s": op[1]}
    if kind == "clock_skew":
        clk.skew_wall(op[1])
        if op[1] < 0:
            # replayed journal ages clamp while in-memory state keeps raw
            # timestamps: exact parity is no longer owed (see docs/OPS.md)
            fleet.parity_exact = False
        return {"op": "clock_skew", "s": op[1]}
    if kind == "ack_skew":
        return {"op": "ack_skew", "tenant": op[1],
                "hit": fleet.ack_skew(op[1])}
    if kind == "wal_rotate":
        return {"op": "wal_rotate", "node": op[1],
                "rotated": fleet.rotate_wals(op[1])}
    raise ValueError(f"unknown schedule op {kind!r}")


def _node_signature(fleet: SimFleet, node) -> dict:
    reg = node.registry
    sig = {
        "role": node.replicator.role if node.replicator else None,
        "fence": list(reg.fence_for() or ()) if reg else None,
        "forwards": {},
    }
    if reg is not None:
        for tenant in fleet.tenants:
            fwd = reg.forward_for(tenant)
            if fwd is not None:
                sig["forwards"][tenant] = fwd[0]
    return sig


def _quiesce(fleet: SimFleet, clk: VirtualClock) -> dict:
    """Lift every fault and drive the fleet to a fixpoint, gathering the
    facts the quiesce-time invariant checks consume."""
    event: dict = {"op": "quiesce"}
    fleet.net.heal()
    fleet.net.drop_next.clear()
    fleet.net.dup_next.clear()
    fleet.net.defer_next.clear()
    event["flushed"] = fleet.net.flush()
    for name, node in fleet.nodes.items():
        if not node.alive:
            fleet.revive(name)
    if fleet.degraded:
        fleet.recover_disk()
    # a node revived while its handoff peer was still down parks the
    # resume as "pending"; with the whole fleet now up, one more recover
    # pass lets every parked handoff complete before the checks run
    for node in fleet.nodes.values():
        if node.alive:
            node.recover()
    for _ in range(_QUIESCE_ROUNDS):
        clk.advance(_QUIESCE_STEP_S)
        for name in fleet.nodes:
            fleet.pump(name)
        fleet.supervise()

    # every fault is lifted: each tenant must be servable again (SIM-I4)
    unservable = {}
    for tenant in fleet.tenants:
        res = fleet.serve(tenant, 0)
        if not res.get("ok"):
            unservable[tenant] = res.get("reason") or "unexplained"
    event["unservable"] = unservable

    # replication must be fully drained (SIM-I2: a wedged sender means
    # the standby silently fell behind)
    lags = {}
    for node in fleet.nodes.values():
        rep = node.replicator
        if rep is None or rep.role != "primary" or rep.target is None:
            continue
        with rep._lock:
            senders = dict(rep._senders)
        for tenant, sender in senders.items():
            lags[tenant] = lags.get(tenant, 0) + int(sender.lag_bytes)
    event["lags"] = lags

    # owner frequency state vs the fault-free control (SIM-I2 deep half).
    # After a backwards wall step the clamps legitimately shift eviction
    # edges between replayed and in-memory state, so no byte-exact (or
    # even count-exact) claim survives — the S1 unit tests carry that
    # precision; the sweep then only asserts nothing crashed or leaked.
    state_diffs = {}
    if fleet.parity_exact:
        for tenant in fleet.tenants:
            owner = fleet.last_owner.get(tenant)
            node = fleet.nodes.get(owner) if owner else None
            if node is None or not node.resident(tenant) \
                    or tenant in fleet.pending_reanchor:
                continue
            ctx = node.registry.resolve(tenant, ignore_forward=True)
            try:
                with ctx.engine.state_lock:
                    got = ctx.engine.frequency._save_state()
            finally:
                ctx.unpin()
            want = fleet.control(tenant).frequency._save_state()
            if got != want:
                state_diffs[tenant] = (
                    f"owner {owner} frequency state != control"
                    f" ({ {p: len(v) for p, v in got.items()} } vs"
                    f" { {p: len(v) for p, v in want.items()} })"
                )
    event["state_diffs"] = state_diffs

    # recover() must be a fixpoint (SIM-I5) — run it once more on every
    # live node and diff the externally visible signature
    replay_diffs = {}
    for name, node in fleet.nodes.items():
        if not node.alive:
            continue
        before = _node_signature(fleet, node)
        node.recover()
        after = _node_signature(fleet, node)
        if before != after:
            replay_diffs[name] = f"{before} -> {after}"
    event["replay_diffs"] = replay_diffs
    return event


def run_schedule(schedule: list, *, bug_env: dict | None = None,
                 workdir: str | None = None) -> SimResult:
    """Interpret one schedule in a fresh fleet; returns the event log,
    any invariant violations and the replay digest."""
    own_dir = workdir is None
    root = workdir or tempfile.mkdtemp(prefix="lpt-sim-")
    saved_env = {}
    for key, val in (bug_env or {}).items():
        saved_env[key] = os.environ.get(key)
        os.environ[key] = val
    clk = VirtualClock()
    pclock.install(clk)
    events: list = []
    violations: list = []
    failed_at = None
    fleet = None
    try:
        troot = write_tenant_root(os.path.join(root, "tenants"))
        fleet = SimFleet(os.path.join(root, "state"), troot, clk)
        for idx, op in enumerate(schedule):
            try:
                event = _apply(fleet, clk, op)
            except Exception as exc:  # noqa: BLE001 - a crash IS a finding
                event = {"op": op[0],
                         "error": f"{type(exc).__name__}: {exc}"}
                violations.append(
                    f"op-crash: op {idx} {op[0]} raised"
                    f" {type(exc).__name__}: {exc}"
                )
            events.append(event)
            violations.extend(sweep(fleet, event))
            if violations:
                failed_at = idx
                break
        if not violations:
            event = _quiesce(fleet, clk)
            events.append(event)
            violations.extend(sweep(fleet, event))
            if violations:
                failed_at = len(schedule) - 1
    finally:
        if fleet is not None:
            fleet.shutdown()
        pclock.install(None)
        for key, val in saved_env.items():
            if val is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = val
        if own_dir:
            shutil.rmtree(root, ignore_errors=True)
    return SimResult(
        schedule=schedule, events=events, violations=violations,
        digest=_digest(schedule, events, violations), failed_at=failed_at,
    )


def run_seed(seed: int, *, n_ops: int = 40,
             bug_env: dict | None = None) -> SimResult:
    """Expand a seed into a schedule and run it."""
    res = run_schedule(generate_schedule(seed, n_ops), bug_env=bug_env)
    res.seed = seed
    return res


def minimize(schedule: list, *, bug_env: dict | None = None) -> list:
    """Shrink a failing schedule: cut to the failing prefix, then greedily
    drop ops whose removal still reproduces a violation."""
    base = run_schedule(schedule, bug_env=bug_env)
    if base.ok:
        raise ValueError("schedule does not fail; nothing to minimize")
    cur = list(schedule[: (base.failed_at or 0) + 1])
    i = 0
    while i < len(cur):
        cand = cur[:i] + cur[i + 1:]
        if cand and not run_schedule(cand, bug_env=bug_env).ok:
            cur = cand
        else:
            i += 1
    return cur
