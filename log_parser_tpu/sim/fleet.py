"""The simulated fleet: router + backends + warm standby in one process.

Every node is the same production stack the live servers run — a
``TenantRegistry`` (per-tenant engines, journaled frequency state), a
``Migrator`` (live moves) and, on the replication pair, a ``Replicator``
(WAL shipping / fenced failover) — wired over per-node state dirs and the
shared :class:`~log_parser_tpu.sim.transport.SimNet`.  ``kill()`` is the
journal layer's own ``abandon()`` (byte-for-byte what ``kill -9`` leaves);
``revive()`` rebuilds the same objects over the same dirs and runs the
production ``recover()`` paths, exactly like the PR 16/17 crash-matrix
tests — just composed across planes instead of one boundary at a time.

Bookkeeping the invariants need (never visible to production code):

* ``controls`` — one fault-free engine per tenant on the same virtual
  clock, fed every request the owner accepted (the PR 16 parity control).
* ``durable`` — per (node, tenant), the control's raw state at the last
  instant the tenant's journal was fsync-durable; a lossy crash forks the
  control back to this checkpoint, because that is what the disk holds.
* ``acked`` — per replicated tenant, the control's raw state at the last
  zero-lag ship; a promotion forks the control here (the unshipped tail
  is the documented failover loss, not a bug).  A standby crash clears
  the checkpoints — after a lossy standby restart the shipped prefix is
  unknown, so the next promotion re-anchors instead of guessing.
"""

from __future__ import annotations

import os

from log_parser_tpu.config import ScoringConfig
from log_parser_tpu.fleet.ring import HashRing
from log_parser_tpu.models.pattern import (
    Pattern,
    PatternSet,
    PatternSetMetadata,
    PrimaryPattern,
)
from log_parser_tpu.models.pod import PodFailureData
from log_parser_tpu.patterns import load_pattern_directory
from log_parser_tpu.runtime import AnalysisEngine
from log_parser_tpu.runtime.migrate import (
    LocalTarget,
    MigrationCrash,
    MigrationError,
    Migrator,
    SOURCE_RECORDS,
)
from log_parser_tpu.runtime.replicate import (
    LocalReplicaTarget,
    Replicator,
)
from log_parser_tpu.runtime.tenancy import (
    TenantError,
    TenantForwarded,
    TenantRegistry,
)
from log_parser_tpu.sim.transport import SimMigrationTarget, SimNet, SimReplicaTarget

MAX_FORWARD_HOPS = 4

# the traffic corpus: deterministic blobs exercising multi-pattern matches
TRAFFIC = (
    "INFO boot\njava.lang.OutOfMemoryError: heap\nan ERROR here",
    "Connection refused by peer\nINFO ok",
    "ERROR twice\nERROR again\nOutOfMemoryError",
    "nothing to see",
    "Connection refused\njava.lang.OutOfMemoryError: metaspace\nERROR",
    "INFO a\nINFO b\nan ERROR here",
)

TENANT_LIBS = {
    "acme": """
metadata:
  library_id: acme-lib
patterns:
  - id: oom
    name: Out of memory
    severity: CRITICAL
    primary_pattern:
      regex: OutOfMemoryError
      confidence: 0.9
  - id: err
    name: Errors
    severity: LOW
    primary_pattern:
      regex: "\\\\bERROR\\\\b"
      confidence: 0.5
""",
    "globex": """
metadata:
  library_id: globex-lib
patterns:
  - id: conn
    name: Connection refused
    severity: HIGH
    primary_pattern:
      regex: "Connection refused"
      confidence: 0.7
""",
}


def write_tenant_root(root: str) -> str:
    """Materialize the fixed tenant libraries under ``root``."""
    for tid, text in TENANT_LIBS.items():
        d = os.path.join(root, tid)
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, "lib.yaml"), "w", encoding="utf-8") as f:
            f.write(text)
    return root


def _base_pattern_set() -> PatternSet:
    return PatternSet(
        metadata=PatternSetMetadata(library_id="base-lib", name="base-lib"),
        patterns=[
            Pattern(
                id="base", name="base", severity="LOW",
                primary_pattern=PrimaryPattern(regex="BASE", confidence=0.5),
            )
        ],
    )


def events_of(result) -> list:
    """The parity projection (the PR 16 technique): per event the line,
    pattern id and score, plus the summary verdict."""
    d = result.to_dict(drop_none=True)
    return [
        (e["lineNumber"], e["matchedPattern"]["id"], e["score"])
        for e in d.get("events", [])
    ] + [
        (d["summary"]["significantEvents"], d["summary"]["highestSeverity"])
    ]


def _data(blob: str) -> PodFailureData:
    return PodFailureData(pod={"metadata": {"name": "sim"}}, logs=blob)


def _quiet(eng):
    """Disable the background dispatch-cost lowering thread on *eng*.
    It only enriches obs span attrs, spawns real (non-virtual) work, and
    an interpreter exiting mid-lowering aborts inside XLA — three reasons
    the simulator wants none of it."""
    eng._dispatch_cost = lambda rows, width: None
    return eng


# One fully-compiled template engine per (fixed) library, shared across
# every fleet/run in the process via the ``_install_library`` transplant
# seam the fleet router's shared-pack path uses. Without it each of the
# dozens of engines a seed sweep builds would re-trace the fused device
# program — seconds per run instead of tens of milliseconds.
_TEMPLATES: dict[str, object] = {}


def _share_compiled(eng, key: str, sets_factory):
    tmpl = _TEMPLATES.get(key)
    if tmpl is None:
        tmpl = _quiet(AnalysisEngine(sets_factory(), ScoringConfig()))
        for blob in TRAFFIC:  # trace every shape the corpus dispatches
            tmpl.analyze(_data(blob))
        _TEMPLATES[key] = tmpl
    with eng.state_lock:
        eng._install_library(tmpl)
    return eng


class SimNode:
    """One simulated process: registry + migrator (+ replicator)."""

    def __init__(self, fleet: "SimFleet", name: str, *,
                 standby_of: str | None = None, standby: str | None = None):
        self.fleet = fleet
        self.name = name
        self.standby_of = standby_of   # set on the standby: its primary
        self.standby = standby         # set on the primary: its standby
        self.state_dir = os.path.join(fleet.state_root, name)
        os.makedirs(self.state_dir, exist_ok=True)
        self.registry: TenantRegistry | None = None
        self.migrator: Migrator | None = None
        self.replicator: Replicator | None = None
        self.alive = False

    # ------------------------------------------------------------ build

    def build(self) -> None:
        fleet = self.fleet
        clk = fleet.wall_clock
        state = self.state_dir

        def setup(eng, tid):
            _quiet(eng)
            _share_compiled(
                eng, tid,
                lambda: load_pattern_directory(
                    os.path.join(fleet.tenant_root, tid)
                ),
            )
            eng.attach_journal(os.path.join(state, "wal", tid), wall=clk)

        default_engine = _share_compiled(
            _quiet(AnalysisEngine(
                [_base_pattern_set()], ScoringConfig(), clock=clk
            )),
            "__base__", lambda: [_base_pattern_set()],
        )
        self.registry = TenantRegistry(
            default_engine, root=fleet.tenant_root, clock=clk,
            engine_setup=setup,
        )
        if self.standby_of is None:
            self.migrator = Migrator(
                self.registry, state_root=state,
                node_url=f"local://{self.name}",
            )
        target = None
        peer = None
        if self.standby is not None:
            target = SimReplicaTarget(
                fleet.net, self.name, self.standby,
                fleet._replica_inner(self.standby),
            )
        if self.standby_of is not None:
            peer = f"local://{self.standby_of}"
        if target is not None or peer is not None:
            self.replicator = Replicator(
                self.registry, state_root=state,
                node_url=f"local://{self.name}",
                peer_url=peer, target=target, clock=clk, wall=clk,
            )
        self.alive = True

    def recover(self) -> dict:
        """The boot-time convergence sweep each production process runs —
        migrator first, replicator last, the serve/__main__ boot order
        (the replication role's fences/forwards must win arbitration),
        then the cross-plane hooks wired and the migration ownership
        verdicts replayed through them, exactly as serve/__main__ does."""
        out = {}
        if self.migrator is not None:
            out["migrate"] = self.migrator.recover(
                self.fleet.migration_targets(self.name)
            )
        if self.replicator is not None:
            out["replica"] = self.replicator.recover()
            if self.migrator is not None:
                self.migrator.on_release = self.replicator.release_tenant
                self.migrator.on_adopt = self.replicator.adopt_tenant
                self.migrator.on_primacy_check = \
                    self.replicator.verify_primacy
                for tid in out["migrate"].get("forwards", ()):
                    fwd = self.registry.forward_for(tid)
                    if fwd:
                        self.replicator.release_tenant(
                            tid, fwd[0], ship=False
                        )
                for tid in out["migrate"].get("owned", ()):
                    self.replicator.adopt_tenant(tid, ship=False)
        return out

    # ------------------------------------------------------------- kill

    def _journaled_engines(self):
        reg = self.registry
        if reg is None:
            return
        with reg._lock:
            ctxs = list(reg._contexts.values())
        for ctx in ctxs:
            j = getattr(ctx.engine, "journal", None)
            if j is not None:
                yield j
        j = getattr(reg.default_engine, "journal", None)
        if j is not None:
            yield j

    def kill(self) -> None:
        """``kill -9``: drop every handle without the clean-shutdown
        fsync/snapshot. Per-append flush means the on-disk bytes are
        exactly the durable prefix."""
        for j in self._journaled_engines():
            j.abandon()
        if self.replicator is not None:
            try:
                self.replicator._journal.close()
            except OSError:  # pragma: no cover
                pass
        self.registry = None
        self.migrator = None
        self.replicator = None
        self.alive = False

    def shutdown(self) -> None:
        if not self.alive:
            return
        self.kill()  # journals are append-durable; abandon loses nothing here

    # ------------------------------------------------------ owner probes

    def resident(self, tenant: str) -> bool:
        if not self.alive or self.registry is None:
            return False
        with self.registry._lock:
            return tenant in self.registry._contexts

    def accepts(self, tenant: str) -> bool:
        """Would a request for *tenant* be served locally (no fence, no
        forward)? Pure probe — never builds an engine."""
        if not self.alive or self.registry is None:
            return False
        if self.registry.fence_for() is not None:
            return False
        return self.registry.forward_for(tenant) is None


class SimFleet:
    def __init__(self, state_root: str, tenant_root: str, clock,
                 *, backends=("a", "b"), standby=("s", "a"),
                 tenants=("acme", "globex")):
        self.state_root = state_root
        self.tenant_root = tenant_root
        self.clock = clock
        self.wall_clock = clock.wall  # bound method: the shared callable
        self.net = SimNet()
        self.backends = list(backends)
        self.standby_name, self.primary_name = standby
        self.tenants = list(tenants)
        self.ring = HashRing(self.backends)
        self.nodes: dict[str, SimNode] = {}
        # invariant bookkeeping
        self.controls: dict[str, AnalysisEngine] = {}
        self.durable: dict[tuple[str, str], dict] = {}
        self.acked: dict[str, dict] = {}
        self.last_owner: dict[str, str] = {}
        self.overrides: dict[str, str] = {}
        self.fencing_pending: set[str] = set()
        self.pending_reanchor: dict[str, str] = {}
        # tenants that migrated off the replication pair while the release
        # notice could not reach the standby (partition / standby down):
        # until the pump delivers it, a promotion resurrects a stale warm
        # copy there — the documented release-in-flight loss window,
        # tolerated by SIM-I1 the way fencing_pending tolerates a
        # rebooted stale primary
        self.release_unshipped: set[str] = set()
        self.parity_exact = True
        self.degraded = False
        self.serves = 0
        self.serve_failures = 0

        # standby first (the _pair idiom): its boot fence must exist
        # before the primary's first ship
        sb = SimNode(self, self.standby_name, standby_of=self.primary_name)
        self.nodes[self.standby_name] = sb
        sb.build()
        sb.recover()
        for b in self.backends:
            n = SimNode(
                self, b,
                standby=self.standby_name if b == self.primary_name else None,
            )
            self.nodes[b] = n
            n.build()
            n.recover()

    # ------------------------------------------------------- wiring help

    def _replica_inner(self, dst: str):
        def get_inner():
            node = self.nodes.get(dst)
            if node is None or not node.alive or node.replicator is None:
                return None
            return LocalReplicaTarget(node.replicator, url=f"local://{dst}")
        return get_inner

    def _migration_target(self, src: str, dst: str) -> SimMigrationTarget:
        def get_inner():
            node = self.nodes.get(dst)
            if node is None or not node.alive or node.migrator is None:
                return None
            return LocalTarget(node.migrator, url=f"local://{dst}")
        return SimMigrationTarget(self.net, src, dst, get_inner)

    def migration_targets(self, src: str) -> dict:
        return {
            f"local://{dst}": self._migration_target(src, dst)
            for dst in self.backends if dst != src
        }

    def control(self, tenant: str) -> AnalysisEngine:
        eng = self.controls.get(tenant)
        if eng is None:
            eng = _share_compiled(
                _quiet(AnalysisEngine(
                    load_pattern_directory(
                        os.path.join(self.tenant_root, tenant)
                    ),
                    ScoringConfig(), clock=self.wall_clock,
                )),
                tenant,
                lambda: load_pattern_directory(
                    os.path.join(self.tenant_root, tenant)
                ),
            )
            self.controls[tenant] = eng
        return eng

    # ------------------------------------------------------------ lifecycle

    def kill(self, name: str) -> bool:
        node = self.nodes[name]
        if not node.alive:
            return False
        node.kill()
        self.fencing_pending.discard(name)
        if name == self.standby_name:
            # after a lossy standby restart the shipped prefix on its disk
            # is unknowable from out here: drop the expectation, the next
            # promotion re-anchors
            self.acked.clear()
        return True

    def revive(self, name: str) -> dict | None:
        node = self.nodes[name]
        if node.alive:
            return None
        node.build()
        summary = node.recover()
        rep = node.replicator
        if node.standby is not None:
            sb = self.nodes.get(node.standby)
            if sb is not None and sb.alive and sb.replicator is not None \
                    and sb.replicator.role == "primary" \
                    and rep is not None and rep.role == "primary":
                # a rebooted old primary whose standby promoted meanwhile:
                # a stale owner until its first ship is rejected by the
                # higher epoch — the documented convergence window
                # invariant SIM-I1 tolerates exactly until that pump
                self.fencing_pending.add(name)
        if node.standby_of is not None and rep is not None \
                and rep.role == "primary":
            # the standby crashed mid/after-promote and recovered as the
            # owner: surface the placement signal and re-anchor controls
            primary = self.nodes.get(node.standby_of)
            if primary is not None and primary.alive \
                    and primary.replicator is not None \
                    and primary.replicator.role == "primary":
                self.fencing_pending.add(node.standby_of)
            self._note_promoted(node)
        # the disk now holds exactly the durable prefix: fork each control
        # this node owns back to its durable checkpoint
        for tenant in self.tenants:
            if self.last_owner.get(tenant) == name:
                ckpt = self.durable.get((name, tenant))
                if ckpt is not None:
                    self.control(tenant).frequency._load_state(ckpt)
        return summary

    def shutdown(self) -> None:
        for node in self.nodes.values():
            node.shutdown()

    # ------------------------------------------------------------- routing

    def route_chain(self, tenant: str) -> list[str]:
        """The nodes a request would visit: override/ring owner, then
        the forward chain, capped at MAX_FORWARD_HOPS."""
        chain = []
        cur = self.overrides.get(tenant) or self.ring.owner(tenant)
        for _ in range(MAX_FORWARD_HOPS):
            chain.append(cur)
            node = self.nodes.get(cur)
            if node is None or not node.alive or node.registry is None:
                return chain
            reg = node.registry
            fwd = reg.fence_for() or reg.forward_for(tenant)
            if fwd is None:
                return chain
            nxt = fwd[0].rsplit("://", 1)[-1]
            if nxt == cur:
                return chain
            cur = nxt
        chain.append(cur)
        return chain

    def serve(self, tenant: str, blob_idx: int) -> dict:
        """Route one request through the fleet; on success feed the
        fault-free control the same blob at the same instant and compare
        the event projections (realtime half of invariant SIM-I2)."""
        blob = TRAFFIC[blob_idx % len(TRAFFIC)]
        self.serves += 1
        chain = self.route_chain(tenant)
        end = chain[-1]
        node = self.nodes.get(end)
        out = {"tenant": tenant, "chain": chain}
        if node is None or not node.alive or len(chain) > MAX_FORWARD_HOPS:
            self.serve_failures += 1
            out.update(ok=False, reason=self._explain_failure(tenant, chain))
            return out
        try:
            ctx = node.registry.resolve(tenant)
        except (TenantForwarded, TenantError) as exc:
            self.serve_failures += 1
            out.update(
                ok=False, status=getattr(exc, "status", 500),
                reason=self._explain_failure(tenant, chain),
            )
            return out
        try:
            if self.pending_reanchor.get(tenant) == end:
                # first serve on a promoted owner that never received this
                # tenant's state: the pre-failover history is documented
                # loss, so the expectation restarts from what recovered
                with ctx.engine.state_lock:
                    self.control(tenant).frequency._load_state(
                        ctx.engine.frequency._save_state()
                    )
                del self.pending_reanchor[tenant]
            got = events_of(ctx.engine.analyze(_data(blob)))
            journal = getattr(ctx.engine, "journal", None)
            durable = journal is not None and not journal.degraded
            if node.replicator is not None and node.replicator.target is not None:
                node.replicator.attach_sender(tenant, ctx.engine)
        finally:
            ctx.unpin()
        want = events_of(self.control(tenant).analyze(_data(blob)))
        self.last_owner[tenant] = end
        if end != chain[0]:
            self.overrides[tenant] = end  # the router learns the 307
        if durable:
            self.durable[(end, tenant)] = \
                self.control(tenant).frequency._save_state()
        out.update(ok=True, node=end, blob=blob_idx,
                   parity=(got == want))
        return out

    def _explain_failure(self, tenant: str, chain: list[str]) -> str | None:
        """Attribute a failed serve to an active fault, or None —
        an unexplained 5xx (invariant SIM-I3 fires on None)."""
        end = self.nodes.get(chain[-1])
        if end is None or not end.alive:
            return f"node {chain[-1]} is down"
        if len(chain) > MAX_FORWARD_HOPS:
            # a forward loop is never explained — it IS the historical
            # A->B->A resurrection bug; report it for SIM-I4 to catch
            return None
        reg = end.registry
        if reg is not None and reg.fence_for() is not None:
            return f"node {chain[-1]} is a fenced standby"
        if reg is not None and reg.forward_for(tenant) is not None:
            return f"forward chain truncated at {chain[-1]}"
        return None

    # ---------------------------------------------------------- pump hooks

    def pump(self, name: str) -> dict:
        node = self.nodes.get(name)
        if node is None or not node.alive or node.replicator is None:
            return {}
        outcomes = node.replicator.pump_all()
        rep = node.replicator
        if self.release_unshipped:
            # the window closes when the release has nowhere left to
            # come from: no live replicator holds it pending AND no dead
            # node's journal could still produce it at revive
            any_dead = any(not n.alive for n in self.nodes.values())
            self.release_unshipped = {
                t for t in self.release_unshipped
                if any_dead or any(
                    n.alive and n.replicator is not None
                    and t in n.replicator._release_pending
                    for n in self.nodes.values()
                )
            }
        if rep.role != "primary":
            # the stale primary's ship was rejected by the standby's
            # higher epoch and it demoted (re-fencing itself): the
            # split-brain grace window is over
            self.fencing_pending.discard(name)
        if rep.role == "primary" and rep.target is not None:
            with rep._lock:
                senders = dict(rep._senders)
            for tenant, sender in senders.items():
                # zero WAL lag only proves the standby is caught up when
                # the WAL is actually receiving appends: under hard disk
                # pressure served events divert to the in-memory ring, so
                # the checkpoint must not advance past what shipped
                if sender.seeded and sender.lag_bytes == 0 \
                        and not self.degraded \
                        and tenant in self.controls:
                    self.acked[tenant] = \
                        self.control(tenant).frequency._save_state()
        return outcomes

    def _note_promoted(self, node: SimNode) -> None:
        """Placement bookkeeping after the standby became the owner: the
        replication pair's placement flips wholesale (every tenant the old
        primary effectively owned now routes to the standby), and each
        control forks to the acked prefix — the unshipped tail is the
        documented failover loss.  A tenant the standby never received
        (or whose checkpoint a lossy standby restart invalidated) has no
        trustworthy expectation: re-anchor on the recovered state, at
        promote time if resident, else lazily on its first serve."""
        old = node.standby_of or self.primary_name
        for tenant in self.tenants:
            owner = self.last_owner.get(tenant) or self.ring.owner(tenant)
            if owner != old and owner != node.name:
                continue  # a tenant migrated off the pair keeps its owner
            self.overrides[tenant] = node.name
            self.last_owner[tenant] = node.name
            ctl = self.control(tenant)
            if node.resident(tenant):
                ckpt = self.acked.get(tenant)
                if ckpt is not None:
                    ctl.frequency._load_state(ckpt)
                else:
                    reg = node.registry
                    ctx = reg.resolve(tenant, ignore_forward=True)
                    try:
                        with ctx.engine.state_lock:
                            ctl.frequency._load_state(
                                ctx.engine.frequency._save_state()
                            )
                    finally:
                        ctx.unpin()
                self.durable[(node.name, tenant)] = \
                    ctl.frequency._save_state()
            else:
                self.pending_reanchor[tenant] = node.name

    def promote(self, reason: str = "admin") -> dict | None:
        """Admin-path promotion of the standby. ``ReplicationError`` /
        ``ReplicaCrash`` propagate — the harness classifies them."""
        node = self.nodes[self.standby_name]
        if not node.alive or node.replicator is None:
            return None
        if node.replicator.role == "primary":
            return {"status": "primary"}
        out = node.replicator.promote(reason=reason)
        primary = self.nodes.get(self.primary_name)
        if primary is not None and primary.alive \
                and primary.replicator is not None \
                and primary.replicator.role == "primary":
            self.fencing_pending.add(self.primary_name)
        self._note_promoted(node)
        return out

    def migrate(self, tenant: str, dst: str,
                crash_after: str | None = None) -> dict:
        """Run a live move from the current owner to ``dst``. A
        ``crash_after`` record kind turns this into a crash-matrix op:
        the crashed side is killed at the fsync'd record boundary."""
        src = self.last_owner.get(tenant) or self.ring.owner(tenant)
        node = self.nodes.get(src)
        if src == dst or node is None or not node.alive \
                or node.migrator is None:
            return {"outcome": "noop", "src": src}
        dst_node = self.nodes.get(dst)
        if dst_node is None or not dst_node.alive \
                or dst_node.migrator is None:
            return {"outcome": "noop", "src": src}
        mig = node.migrator
        target = self._migration_target(src, dst)
        kinds = frozenset({crash_after} if crash_after else ())
        pre_epoch = self._journal_epoch(node, tenant)
        try:
            mig.crash_after = kinds
            dst_node.migrator.crash_after = kinds
            res = mig.migrate(tenant, target)
            outcome = {"outcome": res["outcome"], "src": src, "dst": dst}
        except MigrationCrash:
            # the crashed process dies at the record boundary; which side
            # depends on whose journal carries the record kind
            crashed = src if crash_after in SOURCE_RECORDS else dst
            if crashed == src and crash_after == "complete":
                # died after COMPLETE: the handoff fully landed — the
                # target activated, the forward was set and the release
                # notified — so ownership bookkeeping mirrors the
                # completed path (the release may still be pending if
                # the standby was unreachable when it was notified)
                rep = node.replicator
                released = rep is None \
                    or tenant not in rep._release_pending
                self.kill(crashed)
                self.last_owner[tenant] = dst
                self.overrides[tenant] = dst
                self.durable[(dst, tenant)] = \
                    self.control(tenant).frequency._save_state()
                if dst != self.primary_name:
                    self.acked.pop(tenant, None)
                    if src == self.primary_name and not released:
                        self.release_unshipped.add(tenant)
            elif crashed == src and crash_after == "cutover":
                # died at the commit record: ownership is committed in
                # the source's journal but the import is NOT live (the
                # target activates after cutover) and the release never
                # left the process. The tenant is unavailable until the
                # source revives and recover() resumes the handoff; the
                # standby cannot learn of the cutover until then — the
                # release-in-flight loss window SIM-I1 tolerates
                self.kill(crashed)
                if src == self.primary_name:
                    self.release_unshipped.add(tenant)
                # when the revived source resumes the handoff, the
                # target restores the bundle's age-relative frequency
                # snapshot rebased to apply time: re-anchor the raw-
                # timestamp control on the first serve at the target
                self.pending_reanchor[tenant] = dst
            elif crashed == src and crash_after in ("export", "import_ack"):
                # pre-cutover source crash, but the export fold already
                # sealed the full live state into the snapshot: the
                # source's durable prefix advanced past the last durable
                # serve, so the revive expectation must not regress
                self.kill(crashed)
                self.durable[(src, tenant)] = \
                    self.control(tenant).frequency._save_state()
            elif crashed == dst and crash_after in ("activate", "applied"):
                # post-cutover target crash: ownership committed (the
                # live source holds the forward and notified the
                # release) and the target's boot replay re-applies the
                # bundle — whose age-relative frequency snapshot rebases
                # to revive time, so the raw-timestamp control is no
                # longer owed byte-exactly: re-anchor it on the state
                # the target recovers, at its first serve there
                self.kill(crashed)
                self.pending_reanchor[tenant] = dst
                if dst != self.primary_name:
                    self.acked.pop(tenant, None)
                    rep = getattr(self.nodes.get(src), "replicator", None)
                    if rep is not None and tenant in rep._release_pending:
                        self.release_unshipped.add(tenant)
            else:
                self.kill(crashed)
            outcome = {"outcome": "crash", "src": src, "dst": dst,
                       "crashed": crashed, "at": crash_after}
        except MigrationError as exc:
            outcome = {"outcome": "refused", "src": src, "dst": dst,
                       "status": exc.status}
        finally:
            for n in (self.nodes[src], dst_node):
                if n.alive and n.migrator is not None:
                    n.migrator.crash_after = frozenset()
        if outcome["outcome"] == "completed":
            self.last_owner[tenant] = dst
            self.overrides[tenant] = dst
            self.durable[(dst, tenant)] = \
                self.control(tenant).frequency._save_state()
            if dst != self.primary_name:
                # the tenant left the replication pair: the shipped-prefix
                # checkpoint no longer predicts anything a promotion
                # could recover
                self.acked.pop(tenant, None)
                rep = getattr(self.nodes.get(src), "replicator", None)
                if rep is not None and tenant in rep._release_pending:
                    self.release_unshipped.add(tenant)
        elif self.nodes[src].alive \
                and self._journal_epoch(self.nodes[src], tenant) != pre_epoch:
            # a refusal or target-side crash after the export fold: the
            # tenant stays at the source, but the fold sealed the full
            # live state into its snapshot — the durable prefix advanced
            # past the last durable serve checkpoint
            self.durable[(src, tenant)] = \
                self.control(tenant).frequency._save_state()
        return outcome

    def _journal_epoch(self, node: SimNode, tenant: str) -> int | None:
        """The tenant engine's journal epoch on *node*, or None when the
        tenant is not resident there — snapshot_now() bumps it, so a
        changed epoch across a migration attempt means the export fold
        ran (and durably sealed the live state)."""
        if not node.alive or node.registry is None:
            return None
        ctx = node.registry.context_if_resident(tenant)
        if ctx is None:
            return None
        j = getattr(ctx.engine, "journal", None)
        return None if j is None else j.epoch

    # ------------------------------------------------------------ disk ops

    def enter_disk_hard(self) -> int:
        """Shared-disk ENOSPC: every journal diverts to its in-memory
        ring (the pressure ladder's hard response)."""
        n = 0
        self.degraded = True
        for node in self.nodes.values():
            if node.alive:
                for j in node._journaled_engines():
                    j.degrade()
                    n += 1
        return n

    def recover_disk(self) -> int:
        """Pressure cleared: re-arm every journal (snapshot + truncate),
        which makes the CURRENT live state the durable baseline."""
        n = 0
        self.degraded = False
        for node in self.nodes.values():
            if node.alive:
                for j in node._journaled_engines():
                    if j.rearm():
                        n += 1
        for tenant, owner in self.last_owner.items():
            node = self.nodes.get(owner)
            if node is not None and node.alive and node.resident(tenant):
                self.durable[(owner, tenant)] = \
                    self.control(tenant).frequency._save_state()
        return n

    def rotate_wals(self, name: str) -> int:
        node = self.nodes.get(name)
        if node is None or not node.alive:
            return 0
        if self.degraded:
            # under hard disk pressure the production snapshot writer
            # skips atomically (pressure.writes_paused()); the sim sets
            # journal-level degrade without the process-wide controller,
            # so the gate is modeled here — a forced rotate must not
            # durably seal ring-diverted state
            return 0
        return sum(1 for j in node._journaled_engines() if j.snapshot_now())

    def ack_skew(self, tenant: str, delta: int = 3) -> bool:
        """Corrupt a sender's resume offset (the misaligned-resume
        hazard): the production fix reseeds on the next pump."""
        primary = self.nodes.get(self.primary_name)
        if primary is None or not primary.alive \
                or primary.replicator is None:
            return False
        with primary.replicator._lock:
            sender = primary.replicator._senders.get(tenant)
        if sender is None or not sender.seeded or sender.acked_offset <= 0:
            return False
        sender.acked_offset = max(1, sender.acked_offset - delta)
        return True

    def supervise(self) -> str | None:
        """One standby-side failover probe (FailoverSupervisor source of
        truth: consecutive-downtime promotion)."""
        node = self.nodes[self.standby_name]
        if not node.alive or node.replicator is None \
                or node.replicator.role == "primary":
            return None
        rep = node.replicator
        if rep.supervisor is None:
            def probe():
                return (
                    self.nodes[self.primary_name].alive
                    and not self.net.partitioned(
                        self.standby_name, self.primary_name
                    )
                )

            rep.arm_failover(
                f"local://{self.primary_name}", after_s=5.0, poll_s=1.0,
            )
            rep.supervisor.probe = probe
        verdict = rep.supervisor.check_once()
        if verdict == "promoted":
            primary = self.nodes.get(self.primary_name)
            if primary is not None and primary.alive \
                    and primary.replicator is not None \
                    and primary.replicator.role == "primary":
                self.fencing_pending.add(self.primary_name)
            self._note_promoted(node)
        return verdict
