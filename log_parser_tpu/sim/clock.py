"""Virtual time for the fleet simulator.

A :class:`VirtualClock` plugs into the :mod:`log_parser_tpu.runtime.clock`
switchboard so every production ``time.*`` call site — journal aging,
stream TTLs, SLO cells, retry backoff, supervisor deadlines — reads
simulated time.  Three properties matter:

* **Integer ticks.**  The schedule only ever advances by whole seconds, so
  every ``now - (now - w)`` round trip through age-relative snapshots is
  float-exact — the bit-identical frequency-parity invariant depends on it
  (the same trick the PR 16/17 FakeClock tests use).
* **Wall and monotonic are separate streams.**  ``advance`` moves both;
  ``pause_wall`` moves only the monotonic stream (a paused wall clock —
  VM freeze, NTP hold); ``skew_wall`` steps the wall clock, negative steps
  included (the backwards-clock hazard the S1 clamps guard).
* **Single-driver threading.**  The simulation runs the whole fleet on the
  driver thread.  Background threads that production code insists on
  starting (the journal maintenance thread) park in ``wait``: a non-driver
  thread blocks on the *real* event with no timeout, so it wakes exactly
  once — at shutdown — and never injects nondeterminism.  A non-driver
  ``sleep`` yields briefly in real time without touching virtual time.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from log_parser_tpu import _clock as pclock


class VirtualClock(pclock.Clock):
    def __init__(self, start: float = 1000.0):
        self._wall = float(start)
        self._mono = float(start)
        self._lock = threading.Lock()
        self._driver = threading.get_ident()

    # ------------------------------------------------------- Clock API

    def wall(self) -> float:
        with self._lock:
            return self._wall

    def mono(self) -> float:
        with self._lock:
            return self._mono

    def sleep(self, seconds: float) -> None:
        if threading.get_ident() != self._driver:
            # a stray background thread: yield without advancing sim time
            time.sleep(0.001)
            return
        self.advance(max(0.0, seconds))

    def wait(self, event: threading.Event, timeout: Optional[float] = None) -> bool:
        if threading.get_ident() != self._driver:
            # background threads park until shutdown sets their stop event
            return event.wait()
        if event.is_set():
            return True
        if timeout is not None:
            self.advance(max(0.0, timeout))
        return event.is_set()

    # --------------------------------------------------- schedule hooks

    def advance(self, seconds: float) -> None:
        """Move wall AND monotonic time forward together."""
        with self._lock:
            self._mono += seconds
            self._wall += seconds

    def pause_wall(self, seconds: float) -> None:
        """Wall clock frozen for *seconds* of monotonic time (VM pause)."""
        with self._lock:
            self._mono += seconds

    def skew_wall(self, seconds: float) -> None:
        """Step the wall clock by *seconds* — negative means backwards
        (the NTP step the S1 clamps exist for). Monotonic never moves."""
        with self._lock:
            self._wall += seconds
