"""In-memory simulated transport with per-edge fault state.

The production in-process transports (``LocalReplicaTarget`` for WAL
shipping, ``LocalTarget`` for migration) already drive the destination
object directly — the simulator wraps them in a :class:`SimNet` edge that
consults mutable fault state on every delivery:

* **partition** — the send fails with a transport error; nothing reaches
  the receiver (the sender backs off, exactly as against a dead peer).
* **drop** — the next delivery on the edge is lost in flight (one-shot).
* **duplicate** — the next delivery is applied twice; the caller sees the
  second response (receiver-side idempotency is what's under test).
* **defer** — the next delivery is queued instead of applied, and the
  sender sees a transport error (a timeout whose request actually arrived
  — the classic ambiguous failure).  A later ``flush_net`` op delivers
  everything queued, in queue order, which by then is *out of order*
  relative to retries the sender already pushed through.

Destination objects are resolved *at delivery time* through a callable, so
a receiver that was killed and revived (a brand-new ``Replicator`` /
``Migrator`` over the same state dirs) is reached through its current
incarnation — like a TCP connect, not a stale object reference.  A dead
destination is a transport error, same as a partition.

All state is mutated only by schedule ops on the driver thread, so every
delivery decision is a pure function of the schedule prefix —
deterministic by construction.
"""

from __future__ import annotations

from typing import Callable

from log_parser_tpu.runtime.migrate import MigrationError
from log_parser_tpu.runtime.replicate import ReplicationError


class SimPartitioned(Exception):
    """Transport-level failure on a partitioned/lossy edge or dead peer."""


class SimNet:
    """Fault state for the fleet's point-to-point edges, keyed by
    ``(src, dst)`` node-name pairs. Partitions are symmetric; the one-shot
    flags (drop/duplicate/defer) are per-directed-edge."""

    def __init__(self):
        self.partitions: set[frozenset[str]] = set()
        self.drop_next: set[tuple[str, str]] = set()
        self.dup_next: set[tuple[str, str]] = set()
        self.defer_next: set[tuple[str, str]] = set()
        self.deferred: list[tuple[str, Callable[[], object]]] = []

    # ------------------------------------------------------- schedule ops

    def partition(self, a: str, b: str) -> None:
        self.partitions.add(frozenset((a, b)))

    def heal(self, a: str | None = None, b: str | None = None) -> None:
        if a is None:
            self.partitions.clear()
        else:
            self.partitions.discard(frozenset((a, b or a)))

    def partitioned(self, a: str, b: str) -> bool:
        return frozenset((a, b)) in self.partitions

    def flush(self) -> list[str]:
        """Deliver every deferred payload, in queue order. Returns labels
        of the deliveries made (for the event log). Receiver-side errors
        are swallowed — a late duplicate being rejected IS the tested
        behaviour."""
        queued, self.deferred = self.deferred, []
        labels = []
        for label, thunk in queued:
            try:
                thunk()
                labels.append(label)
            except Exception as exc:  # noqa: BLE001 - receiver rejects late junk
                labels.append(f"{label}:rejected:{type(exc).__name__}")
        return labels

    # --------------------------------------------------------- delivery

    def deliver(self, src: str, dst: str, label: str,
                thunk: Callable[[], object]):
        """Run one synchronous RPC over the (src, dst) edge under the
        current fault state. Raises :class:`SimPartitioned` when the
        sender must observe a transport failure."""
        if self.partitioned(src, dst):
            raise SimPartitioned(f"partition {src}<->{dst}")
        edge = (src, dst)
        if edge in self.drop_next:
            self.drop_next.discard(edge)
            raise SimPartitioned(f"dropped in flight {src}->{dst}")
        if edge in self.defer_next:
            self.defer_next.discard(edge)
            self.deferred.append((label, thunk))
            raise SimPartitioned(f"deferred {src}->{dst}")
        if edge in self.dup_next:
            self.dup_next.discard(edge)
            thunk()  # first copy applies; caller sees the second
        return thunk()


class SimReplicaTarget:
    """A replica target behind a :class:`SimNet` edge. Duck-typed to the
    replica target protocol (``feed(body) -> (status, doc)``); the inner
    ``LocalReplicaTarget`` is produced by ``get_inner()`` at delivery time
    (None means the destination process is dead)."""

    def __init__(self, net: SimNet, src: str, dst: str,
                 get_inner: Callable[[], object]):
        self.net = net
        self.src = src
        self.dst = dst
        self.get_inner = get_inner
        self.url = f"local://{dst}"

    def feed(self, body: dict) -> tuple[int, dict]:
        def _thunk():
            inner = self.get_inner()
            if inner is None:
                raise SimPartitioned(f"peer {self.dst} is down")
            return inner.feed(body)

        try:
            return self.net.deliver(
                self.src, self.dst, f"feed:{self.src}->{self.dst}", _thunk
            )
        except SimPartitioned as exc:
            raise ReplicationError(str(exc), status=503) from exc


class SimMigrationTarget:
    """A migration target behind a :class:`SimNet` edge (stage/activate
    are the two deliveries). Transport failures surface as exceptions:
    ``Migrator.migrate`` aborts pre-cutover and leaves a resumable journal
    post-cutover — both paths are exactly what ``recover()`` is for."""

    can_adopt_sessions = True

    def __init__(self, net: SimNet, src: str, dst: str,
                 get_inner: Callable[[], object]):
        self.net = net
        self.src = src
        self.dst = dst
        self.get_inner = get_inner
        self.url = f"local://{dst}"

    def _rpc(self, label: str, call: Callable[[object], object]):
        def _thunk():
            inner = self.get_inner()
            if inner is None:
                raise SimPartitioned(f"peer {self.dst} is down")
            return call(inner)

        try:
            return self.net.deliver(self.src, self.dst, label, _thunk)
        except SimPartitioned as exc:
            # the production HttpTarget contract: transport failure is a
            # MigrationError, so migrate() aborts pre-cutover and
            # recover() parks an unreachable resume as "pending"
            raise MigrationError(
                f"target {self.url} unreachable: {exc}"
            ) from exc

    def stage(self, bundle: dict, sha: str) -> dict:
        return self._rpc(f"stage:{self.src}->{self.dst}",
                         lambda inner: inner.stage(bundle, sha))

    def activate(self, mid: str) -> dict:
        return self._rpc(f"activate:{self.src}->{self.dst}",
                         lambda inner: inner.activate(mid))

    def adopt_session(self, tenant_id: str, sess) -> bool:
        inner = self.get_inner()
        if inner is None or self.net.partitioned(self.src, self.dst):
            return False
        return inner.adopt_session(tenant_id, sess)
