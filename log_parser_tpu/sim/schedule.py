"""Seeded fault schedules: the op vocabulary and the generator.

A schedule is a flat list of tuples — ``(op, *args)`` — interpreted by
the harness.  ``generate_schedule(seed, n_ops)`` is a pure function of its
arguments (one ``random.Random(seed)``, no ambient entropy), which is the
whole determinism story: same seed, same schedule, same fleet, same
digest.

``SCHEDULE_OPS`` is the authoritative op vocabulary; hygiene check 22
pins every name to a row in the docs/OPS.md schedule-grammar table so an
op can't be added without documenting what it simulates.
"""

from __future__ import annotations

import random

from log_parser_tpu.runtime.migrate import SOURCE_RECORDS, TARGET_RECORDS

SCHEDULE_OPS: dict[str, str] = {
    "serve": "route one tenant request through the fleet and verify "
             "parity against the fault-free control",
    "advance": "move virtual wall+monotonic time forward N whole seconds",
    "pump": "one synchronous WAL-ship round on a node's replicator",
    "supervise": "one standby failover probe (promotes after sustained "
                 "primary downtime)",
    "promote": "admin-path standby promotion",
    "migrate": "live-migrate a tenant between backends, optionally "
               "crashing at a journal record boundary",
    "kill": "kill -9 a node (journals abandoned at the durable prefix)",
    "revive": "rebuild a dead node over its state dirs and run recover()",
    "partition": "cut the network edge between two nodes (symmetric)",
    "heal": "lift every partition",
    "drop": "lose the next delivery on a directed edge in flight",
    "dup": "apply the next delivery on a directed edge twice",
    "defer": "queue the next delivery instead of applying it (ambiguous "
             "timeout); a later flush_net delivers it late and reordered",
    "flush_net": "deliver every deferred payload, in queue order",
    "enospc": "shared-disk ENOSPC: every journal degrades to its "
              "in-memory ring",
    "disk_recover": "pressure cleared: re-arm every journal "
                    "(snapshot + truncate)",
    "clock_pause": "freeze the wall clock for N seconds of monotonic "
                   "time (VM pause / NTP hold)",
    "clock_skew": "step the wall clock by N seconds, negative included "
                  "(the backwards-clock hazard)",
    "ack_skew": "corrupt a replica sender's resume offset (misaligned "
                "resume hazard; the sender must reseed)",
    "wal_rotate": "force a journal snapshot+rotate on a node "
                  "(senders must chase the epoch)",
}

_CRASH_KINDS = tuple(SOURCE_RECORDS) + tuple(TARGET_RECORDS)


def generate_schedule(
    seed: int,
    n_ops: int = 40,
    *,
    tenants: tuple[str, ...] = ("acme", "globex"),
    backends: tuple[str, ...] = ("a", "b"),
    standby: str = "s",
) -> list[tuple]:
    """Deterministically expand a seed into a serve-heavy multi-fault
    schedule. Roughly half the ops are traffic; the rest are time and
    faults, so most seeds exercise several fault families at once."""
    rng = random.Random(seed)
    nodes = tuple(backends) + (standby,)
    pumpable = (backends[0], standby)

    def _edge():
        a, b = rng.sample(nodes, 2)
        return a, b

    table = (
        (40, lambda: ("serve", rng.choice(tenants), rng.randrange(6))),
        (13, lambda: ("advance", rng.randint(1, 30))),
        (9, lambda: ("pump", rng.choice(pumpable))),
        (6, lambda: ("supervise",)),
        (2, lambda: ("promote",)),
        (5, lambda: ("migrate", rng.choice(tenants), rng.choice(backends),
                     rng.choice(_CRASH_KINDS) if rng.random() < 0.35
                     else None)),
        (4, lambda: ("kill", rng.choice(nodes))),
        (6, lambda: ("revive", rng.choice(nodes))),
        (3, lambda: ("partition", *_edge())),
        (3, lambda: ("heal",)),
        (1, lambda: ("drop", *_edge())),
        (1, lambda: ("dup", *_edge())),
        (1, lambda: ("defer", *_edge())),
        (1, lambda: ("flush_net",)),
        (1, lambda: ("enospc",)),
        (2, lambda: ("disk_recover",)),
        (1, lambda: ("clock_pause", rng.randint(1, 10))),
        (1, lambda: ("clock_skew", rng.choice((-5, -2, -1, 1, 3)))),
        (1, lambda: ("ack_skew", rng.choice(tenants))),
        (1, lambda: ("wal_rotate", rng.choice(nodes))),
    )
    weights = [w for w, _ in table]
    makers = [m for _, m in table]
    out = []
    for _ in range(n_ops):
        (maker,) = rng.choices(makers, weights=weights)
        out.append(maker())
    return out
