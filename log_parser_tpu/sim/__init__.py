"""Deterministic fleet simulation (the FoundationDB technique).

One process hosts an entire fleet — router + backends + warm standby +
migration/failover supervisors — under a :class:`~log_parser_tpu.sim.clock.
VirtualClock` and an in-memory fault-injecting transport, driven by a
seeded multi-fault schedule.  After every op a global invariant sweep runs
(`sim/invariants.py`, ids ``SIM-I1``..``SIM-I5``); any violation pins the
seed, which replays byte-identically and minimizes to the shortest failing
schedule (`sim/schedule.py`).

The point is that the simulated code paths are the *same bytes* as
production: the clock rides the :mod:`log_parser_tpu.runtime.clock`
switchboard every ``time.*`` call site already reads, transports reuse
``LocalTarget``/``LocalReplicaTarget``, crashes reuse the ``crash_after``
journal hooks, and disk faults reuse the journal degrade ladder.  See
docs/OPS.md § "Deterministic fleet simulation".
"""

from log_parser_tpu.sim.clock import VirtualClock
from log_parser_tpu.sim.harness import SimResult, minimize, run_schedule, run_seed
from log_parser_tpu.sim.invariants import INVARIANTS
from log_parser_tpu.sim.schedule import SCHEDULE_OPS, generate_schedule

__all__ = [
    "INVARIANTS",
    "SCHEDULE_OPS",
    "SimResult",
    "VirtualClock",
    "generate_schedule",
    "minimize",
    "run_schedule",
    "run_seed",
]
