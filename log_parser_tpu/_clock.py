"""Swappable process clock: wall + monotonic + sleep/wait behind one seam.

Production code calls the module-level helpers (:func:`wall`, :func:`mono`,
:func:`sleep`, :func:`wait`) instead of touching ``time.*`` directly.  By
default they delegate to a :class:`SystemClock` (real ``time.time`` /
``time.monotonic`` / ``time.sleep`` / ``Event.wait``), so live behaviour is
byte-identical to the pre-seam code.  The deterministic simulation harness
(``log_parser_tpu.sim``) installs a virtual clock via :func:`install` and the
*same* production bytes run under simulated time — the FoundationDB trick.

The switchboard mirrors ``runtime.faults`` / ``runtime.pressure``: a single
module-global read at call time, no per-object plumbing required (although
most constructors still accept an explicit ``clock=`` override, which wins).

Design notes
------------
* ``wait(event, timeout)`` exists because ``threading.Event.wait`` is a
  hidden time source: under a virtual clock a timed wait must *advance*
  virtual time rather than block the only thread.  SystemClock simply
  forwards to ``event.wait``.
* Installation is process-global and intentionally not thread-scoped — the
  simulator runs the whole fleet on one thread, and production never
  installs anything.
"""

from __future__ import annotations

import threading
import time
from typing import Optional


class Clock:
    """Interface: a source of wall time, monotonic time, and blocking."""

    def wall(self) -> float:
        raise NotImplementedError

    def mono(self) -> float:
        raise NotImplementedError

    def sleep(self, seconds: float) -> None:
        raise NotImplementedError

    def wait(self, event: threading.Event, timeout: Optional[float] = None) -> bool:
        raise NotImplementedError


class SystemClock(Clock):
    """The real thing — used unless a simulator installs a replacement."""

    def wall(self) -> float:
        return time.time()

    def mono(self) -> float:
        return time.monotonic()

    def sleep(self, seconds: float) -> None:
        time.sleep(seconds)

    def wait(self, event: threading.Event, timeout: Optional[float] = None) -> bool:
        return event.wait(timeout)


_SYSTEM = SystemClock()
_CLOCK: Clock = _SYSTEM


def install(clock: Optional[Clock]) -> None:
    """Install *clock* as the process clock (``None`` restores the system clock)."""
    global _CLOCK
    _CLOCK = clock if clock is not None else _SYSTEM


def active() -> Clock:
    """Return the currently installed clock."""
    return _CLOCK


def installed() -> bool:
    """True when a non-system clock is installed (i.e. we are in a simulation)."""
    return _CLOCK is not _SYSTEM


def wall() -> float:
    """Wall-clock seconds (``time.time`` equivalent; may step backwards)."""
    return _CLOCK.wall()


def mono() -> float:
    """Monotonic seconds (``time.monotonic`` equivalent; never steps back)."""
    return _CLOCK.mono()


def sleep(seconds: float) -> None:
    """Sleep for *seconds* on the installed clock."""
    _CLOCK.sleep(seconds)


def wait(event: threading.Event, timeout: Optional[float] = None) -> bool:
    """``event.wait(timeout)`` routed through the installed clock.

    Returns True when the event is set.  Under a virtual clock a timed wait
    advances simulated time instead of blocking the (single) thread.
    """
    return _CLOCK.wait(event, timeout)
