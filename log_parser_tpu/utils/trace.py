"""Per-phase timing + device profiler hooks.

The reference's only timing surface is the wall-clock ``processingTimeMs``
stamped into result metadata (AnalysisService.java:51,169); it has no
tracing or profiling subsystem (SURVEY.md §5.1). This framework keeps the
metadata field for API parity and adds:

- :class:`PhaseTrace` — cheap named-phase wall timers (ingest / overrides /
  device / finalize / assemble) collected per request; the engine exposes
  its latest as ``engine.last_trace``.
- :func:`profiler_trace` — context manager wrapping ``jax.profiler.trace``
  (TensorBoard-viewable device traces) gated by an output directory, so the
  hot path carries zero overhead when profiling is off.
"""

from __future__ import annotations

import contextlib
import threading
import time


class PhaseTrace:
    """Named wall-clock phase timers for one request.

    Thread-safe: the micro-batcher (runtime/batcher.py) accumulates into a
    request's trace from both the submitting thread (ingest/overrides) and
    the scheduler thread (batch_wait/device/finish phases), so the
    read-modify-write accumulation is guarded — an unguarded ``get()+set``
    would drop one side's time under interleaving."""

    def __init__(self) -> None:
        self.phases: dict[str, float] = {}
        self._lock = threading.Lock()
        # request identity for the obs trace ring (log_parser_tpu/obs):
        # the propagated X-Request-Id and the route that served it.
        # Write-once by the thread that creates/submits the request,
        # before any cross-thread handoff — no lock needed.
        self.request_id: str | None = None
        self.route: str = "device"
        # span-store carriers (obs/spans.py): the batcher's scheduler
        # thread appends the flush back-link and dispatch attributes
        # here; Obs.note_served folds them into the committed request
        # span. list.append / dict.update are single-bytecode atomic
        # and the reader runs strictly after demux hands the request
        # back, so no lock is needed.
        self.links: list = []
        self.span_attrs: dict = {}

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(name, time.perf_counter() - t0)

    def add(self, name: str, seconds: float) -> None:
        """Accumulate ``seconds`` into ``name`` (for callers that measured
        a span themselves — e.g. one shared device step attributed to every
        request of a coalesced batch)."""
        with self._lock:
            self.phases[name] = self.phases.get(name, 0.0) + seconds

    @property
    def total(self) -> float:
        with self._lock:
            return sum(self.phases.values())

    def as_dict(self) -> dict[str, float]:
        """Seconds per phase, insertion-ordered."""
        with self._lock:
            return dict(self.phases)

    def __repr__(self) -> str:
        # same guard as total/as_dict: the batcher's scheduler thread
        # mutates phases while a submitter may be formatting this
        with self._lock:
            parts = ", ".join(
                f"{k}={v * 1e3:.2f}ms" for k, v in self.phases.items()
            )
        return f"PhaseTrace({parts})"


@contextlib.contextmanager
def profiler_trace(log_dir: str | None):
    """``jax.profiler.trace`` when ``log_dir`` is set, else a no-op."""
    if not log_dir:
        yield
        return
    import jax

    with jax.profiler.trace(log_dir):
        yield
