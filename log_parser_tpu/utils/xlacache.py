"""Persistent XLA compilation cache wiring.

The fused device program costs seconds to tens of seconds to compile
(config-4's 10k-regex bank measured ~36-50s cold on the tunneled v5e,
bench_results/config4_10k_tpu.json) and is recompiled from scratch on
every process start — a server restart or cron-driven batch job pays it
again although neither the bank nor the program changed. JAX's
persistent compilation cache keys serialized executables by HLO +
platform, so enabling it turns every warm restart's compile into a disk
read. The reference has no analogue (the JVM starts interpreted and JITs
as it goes); this is the TPU-native equivalent of that "no compile at
boot" property.

Enabled by default; ``LOG_PARSER_TPU_XLA_CACHE=0`` disables, any other
value overrides the cache directory (default
``~/.cache/log_parser_tpu/xla-cache``).

The thresholds below cache *every* compile, however small, and JAX's
persistent cache has no eviction — the directory grows without bound
across bank/shape changes. Entries are content-addressed and individually
deletable, so periodic cleanup is safe: ``find <dir> -atime +30 -delete``
(or wipe the directory; the only cost is one cold compile set).
"""

from __future__ import annotations

import logging
import os

log = logging.getLogger(__name__)

_configured = False


def enable_persistent_cache() -> None:
    """Idempotently point JAX at the persistent compilation cache."""
    global _configured
    if _configured:
        return
    _configured = True
    setting = os.environ.get("LOG_PARSER_TPU_XLA_CACHE", "")
    if setting.lower() in ("0", "false", "off", "no", "disabled", "none"):
        return
    # enable-spellings mean "enabled at the default path", not a directory
    path = (
        setting
        if setting.lower() not in ("", "1", "true", "on", "yes", "enabled")
        else os.path.join(
            os.path.expanduser("~"), ".cache", "log_parser_tpu", "xla-cache"
        )
    )
    try:
        import jax

        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # cache everything, however small or quick: warm restarts should
        # replay the whole compile set, including tier probes and admin
        # paths (JAX's defaults skip sub-second compiles)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    except Exception as exc:  # pragma: no cover - cache is best-effort
        log.info("persistent XLA cache unavailable: %s", exc)
