"""Persistent XLA compilation cache wiring.

The fused device program costs seconds to tens of seconds to compile
(config-4's 10k-regex bank measured ~36-50s cold on the tunneled v5e,
bench_results/config4_10k_tpu.json) and is recompiled from scratch on
every process start — a server restart or cron-driven batch job pays it
again although neither the bank nor the program changed. JAX's
persistent compilation cache keys serialized executables by HLO +
platform, so enabling it turns every warm restart's compile into a disk
read. The reference has no analogue (the JVM starts interpreted and JITs
as it goes); this is the TPU-native equivalent of that "no compile at
boot" property.

Enabled by default; ``LOG_PARSER_TPU_XLA_CACHE=0`` disables, any other
value overrides the cache directory (default
``~/.cache/log_parser_tpu/xla-cache``).

The thresholds below cache *every* compile, however small, and JAX's
persistent cache has no eviction — the directory grows without bound
across bank/shape changes. Entries are content-addressed and individually
deletable, so periodic cleanup is safe: ``find <dir> -atime +30 -delete``
(or wipe the directory; the only cost is one cold compile set).

Crash safety: :func:`verify_cache_integrity` sweeps the directory at
enable time, keeping a sha256 sidecar per entry under ``<dir>/.integrity``
(JAX never reads that subtree). An entry whose bytes no longer match its
recorded checksum — truncated by a crashed writer, bit-rotted, torn by a
non-atomic copy — is quarantined with a ``.corrupt`` suffix, which JAX
sees as a miss and recompiles; startup never fails on a poisoned cache.
First sight of an entry records its checksum, so the sweep detects
corruption *between* runs, not a writer that crashed before the very
first sweep (JAX itself publishes entries atomically). The sweep is
best-effort: any I/O failure logs and returns — never raises into boot.
"""

from __future__ import annotations

import hashlib
import logging
import os

log = logging.getLogger(__name__)

_configured = False
# process-lifetime counters fed by JAX's monitoring events (registered in
# enable_persistent_cache); surfaced at GET /trace/last "compileCache"
# (docs/OPS.md) and in the bench artifact's boot story
_cache_dir: str | None = None
_hits = 0
_requests = 0
_listener_registered = False


def _on_event(event: str, **kwargs) -> None:
    global _hits, _requests
    if event == "/jax/compilation_cache/cache_hits":
        _hits += 1
    elif event == "/jax/compilation_cache/compile_requests_use_cache":
        _requests += 1


def stats() -> dict:
    """GET /trace/last ``compileCache`` block (docs/OPS.md): whether the
    persistent cache is wired, where, and this process's hit/miss tally
    (misses = cacheable compile requests that went to XLA)."""
    return {
        "dir": _cache_dir,
        "enabled": _cache_dir is not None,
        "compileHits": _hits,
        "compileMisses": max(0, _requests - _hits),
    }


def verify_cache_integrity(path: str) -> dict[str, int]:
    """Checksum-sweep a persistent-cache directory (see module docstring).
    Returns ``{"checked": n, "recorded": n, "quarantined": n}``."""
    from log_parser_tpu.runtime import faults

    counts = {"checked": 0, "recorded": 0, "quarantined": 0}
    side_dir = os.path.join(path, ".integrity")
    try:
        # chaos point: an injected cache fault aborts the sweep, which
        # must read as "cache cold", never as a boot failure
        faults.fire("cache")
        if not os.path.isdir(path):
            return counts
        os.makedirs(side_dir, exist_ok=True)
        entries = set()
        for name in sorted(os.listdir(path)):
            full = os.path.join(path, name)
            if name == ".integrity" or not os.path.isfile(full):
                continue
            if name.endswith((".corrupt", ".tmp")):
                continue
            # JAX pairs each immutable "-cache" payload with a "-atime"
            # marker it rewrites on every hit — mutation is its normal
            # behavior, so checksumming it would quarantine healthy entries
            if name.endswith("-atime"):
                continue
            entries.add(name)
            counts["checked"] += 1
            digest = hashlib.sha256()
            with open(full, "rb") as f:
                for chunk in iter(lambda: f.read(1 << 20), b""):
                    digest.update(chunk)
            want = digest.hexdigest()
            sidecar = os.path.join(side_dir, name + ".sum")
            if not os.path.exists(sidecar):
                tmp = sidecar + ".tmp"
                with open(tmp, "w") as f:
                    f.write(want + "\n")
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, sidecar)
                counts["recorded"] += 1
            elif open(sidecar).read().split()[0] != want:
                log.warning(
                    "XLA cache entry %s fails its checksum; quarantined "
                    "(.corrupt) — it will recompile on next use", name
                )
                os.replace(full, full + ".corrupt")
                os.unlink(sidecar)
                counts["quarantined"] += 1
        # sidecars whose entry is gone (cleanup, eviction) are dropped so
        # the subtree cannot grow without bound either
        for name in os.listdir(side_dir):
            if name.endswith(".sum") and name[: -len(".sum")] not in entries:
                os.unlink(os.path.join(side_dir, name))
    except Exception as exc:  # best-effort by contract
        log.warning("XLA cache integrity sweep aborted: %s", exc)
    return counts


def enable_persistent_cache() -> None:
    """Idempotently point JAX at the persistent compilation cache."""
    global _configured
    if _configured:
        return
    _configured = True
    setting = os.environ.get("LOG_PARSER_TPU_XLA_CACHE", "")
    if setting.lower() in ("0", "false", "off", "no", "disabled", "none"):
        return
    # enable-spellings mean "enabled at the default path", not a directory
    path = (
        setting
        if setting.lower() not in ("", "1", "true", "on", "yes", "enabled")
        else os.path.join(
            os.path.expanduser("~"), ".cache", "log_parser_tpu", "xla-cache"
        )
    )
    global _cache_dir, _listener_registered
    try:
        import jax

        os.makedirs(path, exist_ok=True)
        verify_cache_integrity(path)
        jax.config.update("jax_compilation_cache_dir", path)
        # cache everything, however small or quick: warm restarts should
        # replay the whole compile set, including tier probes and admin
        # paths (JAX's defaults skip sub-second compiles)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        if not _listener_registered:
            # hit/miss telemetry rides JAX's own monitoring events — the
            # compiler records one event per cacheable compile request
            # and one per disk hit (jax/_src/compiler.py)
            jax.monitoring.register_event_listener(_on_event)
            _listener_registered = True
        _cache_dir = path
    except Exception as exc:  # pragma: no cover - cache is best-effort
        log.info("persistent XLA cache unavailable: %s", exc)
