"""shard_map pipeline: fused match + scoring on a line-sharded batch.

One jitted SPMD program per library: every shard scans its own lines
through the DFA bank (zero communication — lines are independent for
matching, AnalysisService.java:89-113), then computes all seven scoring
factors with the narrowest collective each one needs:

==================  =========================================================
factor              communication
==================  =========================================================
chronological       none (global line index is shard offset + local index)
proximity           ``ppermute`` halo of the secondary-match columns
                    (window ≤ halo), or ``all_gather`` when shards are
                    smaller than the halo
context             same halo machinery over the four context-flag columns
temporal            ``all_gather`` of the (few) sequence-event columns —
                    the backward scan is unbounded (ScoringService.java:
                    296-305), so each shard keeps the full column and the
                    chain runs as local gathers
frequency           ``all_gather`` of per-shard slot totals for the
                    exclusive cross-shard prefix + ``psum`` for the batch
                    totals recorded into tracker state
==================  =========================================================

Everything else is elementwise/local. Halo rows are masked-valid *before*
exchange, so shard edges and batch padding contribute nothing.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from log_parser_tpu.config import ScoringConfig
from log_parser_tpu.golden.engine import (
    DENSITY_MIN_LINES,
    DENSITY_PENALTY,
    DENSITY_RATIO,
    SEQUENCE_NEAR_WINDOW,
    STACK_BONUS_CAP,
    STACK_WEIGHT,
)
from log_parser_tpu.ops.match import DfaBank
from log_parser_tpu.ops.scoring import ScoringKernel, _excl_cumsum, f64
from log_parser_tpu.parallel.mesh import DATA_AXIS
from log_parser_tpu.patterns.bank import (
    CTX_ERROR,
    CTX_EXCEPTION,
    CTX_STACK,
    CTX_WARN,
    PatternBank,
)


def _ring_halo(x: jax.Array, h: int) -> jax.Array:
    """[Bl, K] -> [h + Bl + h, K]: h rows from each ring neighbor via
    ppermute; edge shards receive zeros (ppermute's missing-source fill)."""
    d = jax.lax.axis_size(DATA_AXIS)
    from_left = jax.lax.ppermute(
        x[-h:], DATA_AXIS, [(i, i + 1) for i in range(d - 1)]
    )
    from_right = jax.lax.ppermute(
        x[:h], DATA_AXIS, [(i + 1, i) for i in range(d - 1)]
    )
    return jnp.concatenate([from_left, x, from_right], axis=0)


class ShardedAnalysisStep:
    """The full per-batch device program, shard_mapped over the mesh."""

    def __init__(self, bank: PatternBank, config: ScoringConfig, mesh, dfa_bank: DfaBank):
        self.bank = bank
        self.config = config
        self.mesh = mesh
        self.dfa_bank = dfa_bank
        # reuse the single-device kernel's precomputed static structure
        self.k = ScoringKernel(bank, config)
        self.n_shards = mesh.devices.size

        # static halo requirement per factor family
        self.h_prox = int(self.k.sec_window.max()) if len(self.k.sec_window) else 0
        has_rules = bank.has_context_rules
        self.h_ctx = int(
            max(
                bank.ctx_before[has_rules].max(initial=0),
                bank.ctx_after[has_rules].max(initial=0),
            )
        ) if bank.n_patterns else 0

        spec_rows = P(DATA_AXIS)
        self._fn = jax.jit(
            shard_map(
                self._step,
                mesh=mesh,
                in_specs=(
                    P(None, DATA_AXIS),  # lines [T, B]
                    spec_rows,  # lengths [B]
                    P(DATA_AXIS, None),  # override_mask [B, C]
                    P(DATA_AXIS, None),  # override_val [B, C]
                    P(),  # n_lines
                    P(),  # freq_base
                    P(),  # freq_exists
                ),
                out_specs=(P(DATA_AXIS, None), P(DATA_AXIS, None), P()),
                check_rep=False,
            )
        )

    # ------------------------------------------------------------- host API

    def __call__(
        self,
        lines_u8: np.ndarray,
        lengths: np.ndarray,
        override_mask: np.ndarray,
        override_val: np.ndarray,
        n_lines: int,
        freq_base: np.ndarray,
        freq_exists: np.ndarray,
    ):
        scores, pm, counts = self._fn(
            jnp.asarray(lines_u8.T),
            jnp.asarray(lengths),
            jnp.asarray(override_mask),
            jnp.asarray(override_val),
            jnp.asarray(n_lines),
            jnp.asarray(freq_base),
            jnp.asarray(freq_exists),
        )
        return np.asarray(scores), np.asarray(pm), np.asarray(counts)

    # ------------------------------------------------------------ the step

    def _step(
        self, lines_tb, lengths, override_mask, override_val, n_lines, freq_base, freq_exists
    ):
        bank, k = self.bank, self.k
        Bl = lengths.shape[0]
        P_ = bank.n_patterns
        d = jax.lax.axis_index(DATA_AXIS)
        lidx = jnp.arange(Bl, dtype=jnp.int32)
        gidx = (d * Bl + lidx).astype(jnp.int32)
        valid = gidx < n_lines

        # ---- local match (no communication) -------------------------------
        cube = self._local_match(lines_tb, lengths)
        cube = jnp.where(override_mask, override_val, cube)
        cube = cube & valid[:, None]

        if P_ == 0:
            scores = jnp.zeros((Bl, 0), dtype=f64)
            pm = jnp.zeros((Bl, 0), dtype=bool)
            counts = jnp.zeros((max(1, bank.n_freq_slots),), dtype=jnp.int64)
            return scores, pm, counts

        pm = cube[:, jnp.asarray(bank.primary_columns)]

        chrono = self._chronological(gidx, n_lines)
        prox = self._proximity(cube, lidx, Bl, P_)
        temp = self._temporal(cube, gidx, n_lines, Bl, P_)
        ctx = self._context(cube, gidx, lidx, n_lines, Bl)
        penalty, counts = self._frequency(pm, freq_base, freq_exists, Bl)

        conf = jnp.asarray(bank.confidence)[None, :]
        sev = jnp.asarray(bank.severity_multiplier)[None, :]
        scores = conf * sev * chrono[:, None] * prox * temp * ctx * (1.0 - penalty)
        scores = jnp.where(pm, scores, 0.0)
        return scores, pm, counts

    # ----------------------------------------------------------- local match

    def _local_match(self, lines_tb, lengths):
        Bl = lengths.shape[0]
        C = self.bank.n_columns
        cube = jnp.zeros((Bl, C), dtype=bool)
        if self.dfa_bank.n_regexes:
            matched = self.dfa_bank._run(lines_tb, lengths)[:, : self.dfa_bank.n_regexes]
            dfa_cols = jnp.asarray(
                [i for i, c in enumerate(self.bank.columns) if c.dfa is not None],
                dtype=np.int32,
            )
            cube = cube.at[:, dfa_cols].set(matched)
        return cube

    # -------------------------------------------------------------- factors

    def _chronological(self, gidx, n_lines):
        pos = gidx.astype(f64) / n_lines.astype(f64)
        early, penalty = self.k.chrono_early, self.k.chrono_penalty
        return jnp.where(
            pos <= early,
            1.5 + (early - pos) * self.k.chrono_bonus_quot,
            jnp.where(
                pos <= penalty,
                1.0 + (penalty - pos) * self.k.chrono_middle_quot,
                0.5 + (1.0 - pos),
            ),
        )

    def _extend(self, cols: jax.Array, h: int, Bl: int):
        """Neighborhood view of sharded columns: (extended array, offset of
        local row 0). ppermute halo when shards are big enough; all_gather
        when the halo would span multiple shards."""
        if h < Bl:
            return _ring_halo(cols, h), h  # offset is static
        gathered = jax.lax.all_gather(cols, DATA_AXIS, axis=0, tiled=True)
        d = jax.lax.axis_index(DATA_AXIS)
        return gathered, d * Bl  # offset is traced

    def _proximity(self, cube, lidx, Bl, P_):
        k = self.k
        if len(k.sec_cols) == 0:
            return jnp.ones((Bl, P_), dtype=f64)
        sm = cube[:, jnp.asarray(k.sec_cols)]
        h = max(1, self.h_prox)
        ext, off = self._extend(sm, h, Bl)
        ext_len = ext.shape[0]
        eidx = jnp.arange(ext_len, dtype=jnp.int32)[:, None]
        big = jnp.int32(1 << 30)

        prev_incl = jax.lax.cummax(jnp.where(ext, eidx, -1), axis=0)
        prev = jnp.concatenate(
            [jnp.full((1, ext.shape[1]), -1, prev_incl.dtype), prev_incl[:-1]], axis=0
        )
        nxt_incl = jnp.flip(
            jax.lax.cummin(jnp.flip(jnp.where(ext, eidx, big), axis=0), axis=0), axis=0
        )
        nxt = jnp.concatenate(
            [nxt_incl[1:], jnp.full((1, ext.shape[1]), big, nxt_incl.dtype)], axis=0
        )
        mine = off + lidx  # positions of my rows in ext coordinates
        my_prev = prev[mine]
        my_nxt = nxt[mine]
        pos = mine[:, None]
        d_prev = jnp.where(my_prev >= 0, pos - my_prev, big)
        d_next = jnp.where(my_nxt < big, my_nxt - pos, big)
        dist = jnp.minimum(d_prev, d_next)
        window = jnp.asarray(k.sec_window)[None, :]
        found = dist <= window
        decay = jnp.exp(-dist.astype(f64) / self.config.proximity_decay_constant)
        contrib = jnp.where(found, jnp.asarray(k.sec_weight)[None, :] * decay, 0.0)
        prox = jnp.ones((Bl, P_), dtype=f64)
        return prox.at[:, jnp.asarray(k.sec_owner)].add(contrib)

    def _temporal(self, cube, gidx, n_lines, Bl, P_):
        k = self.k
        temp = jnp.ones((Bl, P_), dtype=f64)
        if not k.sequences:
            return temp
        em_local = cube[:, jnp.asarray(k.seq_event_cols, dtype=np.int32)]
        em = jax.lax.all_gather(em_local, DATA_AXIS, axis=0, tiled=True)  # [B, E]
        B = em.shape[0]
        eidx = jnp.arange(B, dtype=jnp.int32)[:, None]
        prev_incl = jax.lax.cummax(jnp.where(em, eidx, -1), axis=0)
        prefix = jnp.concatenate(
            [jnp.zeros((1, em.shape[1]), jnp.int32), jnp.cumsum(em.astype(jnp.int32), axis=0)]
        )
        w = SEQUENCE_NEAR_WINDOW
        for seq in k.sequences:
            if not seq.event_columns:
                continue
            e_last = k.seq_col_pos[seq.event_columns[-1]]
            lo = jnp.clip(gidx - w, 0, B)
            hi = jnp.clip(jnp.minimum(gidx + w + 1, n_lines), 0, B).astype(jnp.int32)
            near = (prefix[hi, e_last] - prefix[lo, e_last]) > 0
            ok = near
            cur = gidx
            for col in reversed(seq.event_columns[:-1]):
                e = k.seq_col_pos[col]
                g = jnp.where(cur >= 1, prev_incl[jnp.clip(cur - 1, 0, B - 1), e], -1)
                ok = ok & (g >= 0)
                cur = jnp.clip(g, 0, B - 1)
            temp = temp.at[:, seq.pattern_idx].add(jnp.where(ok, seq.bonus, 0.0))
        return temp

    def _context(self, cube, gidx, lidx, n_lines, Bl):
        k = self.k
        if not k.ctx_shapes:
            return jnp.ones((Bl, 0), dtype=f64)
        err = cube[:, CTX_ERROR]
        warn = cube[:, CTX_WARN] & ~err
        stack = cube[:, CTX_STACK]
        exc = cube[:, CTX_EXCEPTION]
        from log_parser_tpu.golden.engine import (
            ERROR_WEIGHT,
            EXCEPTION_WEIGHT,
            WARN_WEIGHT,
        )

        line_score = (
            ERROR_WEIGHT * err.astype(f64)
            + WARN_WEIGHT * warn.astype(f64)
            + STACK_WEIGHT * stack.astype(f64)
            + EXCEPTION_WEIGHT * exc.astype(f64)
        )
        h = max(1, self.h_ctx)
        flags = jnp.stack(
            [line_score, stack.astype(f64), err.astype(f64)], axis=1
        )  # [Bl, 3]
        ext, off = self._extend(flags, h, Bl)
        prefix = jnp.concatenate(
            [jnp.zeros((1, 3), dtype=f64), jnp.cumsum(ext, axis=0)], axis=0
        )
        ext_len = ext.shape[0]
        mine = off + lidx

        cols = []
        for has_rules, before, after in k.ctx_shapes:
            if not has_rules:
                w_score = line_score
                w_stack = stack.astype(jnp.int32)
                w_err = err.astype(jnp.int32)
                total = jnp.ones_like(lidx)
            else:
                # global clamps (AnalysisService.java:142,148) expressed on
                # the global index; ext rows outside them are zero-masked
                lo_g = jnp.maximum(gidx - before, 0)
                hi_g = jnp.minimum(gidx + 1 + after, n_lines).astype(jnp.int32)
                hi_g = jnp.maximum(hi_g, lo_g)
                total = hi_g - lo_g
                lo_e = jnp.clip(mine - (gidx - lo_g), 0, ext_len)
                hi_e = jnp.clip(mine + (hi_g - gidx), 0, ext_len)
                win = prefix[hi_e] - prefix[lo_e]  # [Bl, 3]
                w_score = win[:, 0]
                w_stack = win[:, 1].astype(jnp.int32)
                w_err = win[:, 2].astype(jnp.int32)
            score = w_score + jnp.where(
                w_stack > 0,
                jnp.minimum(STACK_WEIGHT * w_stack.astype(f64), STACK_BONUS_CAP),
                0.0,
            )
            dense = (total > DENSITY_MIN_LINES) & (
                (w_stack + w_err).astype(f64) > total.astype(f64) * DENSITY_RATIO
            )
            score = jnp.where(dense, score * DENSITY_PENALTY, score)
            cols.append(jnp.minimum(1.0 + score, self.config.context_max_context_factor))
        ctx_u = jnp.stack(cols, axis=1)
        return ctx_u[:, jnp.asarray(k.pattern_ctx_shape)]

    def _frequency(self, pm, freq_base, freq_exists, Bl):
        bank, k = self.bank, self.k
        n_slots = max(1, bank.n_freq_slots)
        pm_i = pm.astype(jnp.int64)
        slot_ok = jnp.asarray(bank.freq_slot >= 0)
        safe_slot = jnp.asarray(np.maximum(bank.freq_slot, 0))

        line_slot = jnp.zeros((Bl, n_slots), dtype=jnp.int64)
        line_slot = line_slot.at[:, safe_slot].add(jnp.where(slot_ok[None, :], pm_i, 0))
        local_before = _excl_cumsum(line_slot, axis=0)
        local_total = jnp.sum(line_slot, axis=0)  # [n_slots]

        # exclusive cross-shard prefix of slot totals
        d = jax.lax.axis_index(DATA_AXIS)
        all_totals = jax.lax.all_gather(local_total, DATA_AXIS, axis=0)  # [D, n_slots]
        shard_mask = (jnp.arange(all_totals.shape[0]) < d)[:, None]
        carry = jnp.sum(jnp.where(shard_mask, all_totals, 0), axis=0)  # [n_slots]

        before_line = carry[None, :] + local_before
        prior = before_line[:, safe_slot]
        for slot, members in k.shared_slots.items():
            sub = pm_i[:, jnp.asarray(members, dtype=np.int32)]
            corr = _excl_cumsum(sub, axis=1)
            for j, p_idx in enumerate(members):
                prior = prior.at[:, p_idx].add(corr[:, j])

        if k.freq_hours == 0.0:  # zero window: every record expires instantly
            count_before = jnp.zeros_like(prior, dtype=f64)
        else:
            count_before = freq_base[safe_slot][None, :] + prior.astype(f64)
        rate = count_before / k.freq_hours
        thr = float(self.config.frequency_threshold)
        raw = jnp.minimum(float(self.config.frequency_max_penalty), (rate - thr) / thr)
        penalty = jnp.where(rate <= thr, 0.0, raw)
        never_tracked = (~freq_exists[safe_slot])[None, :] & (prior == 0)
        penalty = jnp.where(never_tracked, 0.0, penalty)
        penalty = jnp.where(slot_ok[None, :], penalty, 0.0)

        counts = jax.lax.psum(local_total, DATA_AXIS)
        return penalty, counts


class ShardedEngine:
    """AnalysisEngine variant running the fused match+score step under
    shard_map. Host-side responsibilities (split/encode, host verification,
    frequency tracker, result assembly) are shared with the single-device
    engine via delegation."""

    def __init__(self, pattern_sets, config=None, mesh=None, clock=None):
        import time as _time

        from log_parser_tpu.runtime.engine import AnalysisEngine

        self._base = AnalysisEngine(
            pattern_sets, config, clock=clock or _time.monotonic
        )
        if mesh is None:
            from log_parser_tpu.parallel.mesh import make_mesh

            mesh = make_mesh()
        self.mesh = mesh
        self.step = ShardedAnalysisStep(
            self._base.bank, self._base.config, mesh, self._base.dfa_bank
        )

    @property
    def bank(self):
        return self._base.bank

    @property
    def frequency(self):
        return self._base.frequency

    @property
    def config(self):
        return self._base.config

    @property
    def skipped_patterns(self):
        return self._base.bank.skipped_patterns

    def analyze(self, data):
        import time as _time
        import uuid as _uuid

        import numpy as _np

        from log_parser_tpu.golden.engine import (
            build_metadata,
            build_summary,
            extract_context,
        )
        from log_parser_tpu.models.analysis import AnalysisResult, MatchedEvent
        from log_parser_tpu.native.ingest import Corpus

        base = self._base
        start = _time.monotonic()
        corpus = Corpus(data.logs or "", min_rows=max(8, self.mesh.devices.size))
        lines = corpus
        enc = corpus.encoded
        B = enc.u8.shape[0]
        C = base.bank.n_columns

        # shared override construction (host columns + device-inexact lines)
        overrides = base._overrides(corpus)
        if overrides is None:
            override_mask = _np.zeros((B, C), dtype=bool)
            override_val = _np.zeros((B, C), dtype=bool)
        else:
            override_mask, override_val = overrides

        freq_base = _np.zeros(max(1, base.bank.n_freq_slots), dtype=_np.float64)
        freq_exists = _np.zeros(max(1, base.bank.n_freq_slots), dtype=bool)
        for slot, pid in enumerate(base.bank.freq_ids):
            freq_base[slot] = base.frequency.get_windowed_count(pid)
            freq_exists[slot] = base.frequency.has_entry(pid)

        scores, pm, counts = self.step(
            enc.u8, enc.lengths, override_mask, override_val, len(lines),
            freq_base, freq_exists,
        )

        for slot in range(base.bank.n_freq_slots):
            for _ in range(int(counts[slot])):
                base.frequency.record_pattern_match(base.bank.freq_ids[slot])

        events: list[MatchedEvent] = []
        for line_idx, p_idx in _np.argwhere(pm):
            pattern = base.bank.patterns[p_idx]
            events.append(
                MatchedEvent(
                    line_number=int(line_idx) + 1,
                    matched_pattern=pattern,
                    context=extract_context(lines, int(line_idx), pattern),
                    score=float(scores[line_idx, p_idx]),
                )
            )
        return AnalysisResult(
            events=events,
            analysis_id=str(_uuid.uuid4()),
            metadata=build_metadata(start, len(lines), base.bank.pattern_sets),
            summary=build_summary(events),
        )
