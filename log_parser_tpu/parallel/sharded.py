"""shard_map pipeline: fused match + integer-factor extraction on a
line-sharded batch.

One jitted SPMD program per library: every shard scans its own lines
through the DFA bank (zero communication — lines are independent for
matching, AnalysisService.java:89-113), then extracts the integer factor
components of ops/fused.py with the narrowest collective each one needs:

==================  =========================================================
factor component    communication
==================  =========================================================
chronological       none (global line index is shard offset + local index)
secondary dists     ``ppermute`` halo of the secondary-match columns
                    (window ≤ halo), or ``all_gather`` when shards are
                    smaller than the halo
context counts      same halo machinery over the four context-flag columns
sequence flags      ``all_gather`` of the (few) sequence-event columns —
                    the backward scan is unbounded (ScoringService.java:
                    296-305), so each shard keeps the full column and the
                    chain runs as local gathers
frequency           NONE — line-sharding is contiguous, so concatenating
                    per-shard record blocks in shard order reproduces global
                    discovery order, and the host finalizer recovers every
                    read-before-record prior from the stream itself
==================  =========================================================

Each shard compacts its matches into a local K-capped record buffer;
outputs are per-shard record blocks that the host concatenates (shard-major
= line-major = discovery order) and feeds to the same exact-f64 finalizer
as the single-device engine. No float64 — and no floating point at all —
ever runs on the devices.

Halo rows are masked-valid *before* exchange, so shard edges and batch
padding contribute nothing.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

from log_parser_tpu import _clock as pclock
from log_parser_tpu.config import ScoringConfig
from log_parser_tpu.ops.fused import (
    K_LADDER,
    NO_HIT,
    FusedStaticTables,
    MatchRecords,
    _prefix,
    _prev_next_dist,
    compact_records,
    sequence_flags_from_events,
)
from log_parser_tpu.parallel.mesh import DATA_AXIS
from log_parser_tpu.patterns.bank import (
    CTX_ERROR,
    CTX_EXCEPTION,
    CTX_STACK,
    CTX_WARN,
    PatternBank,
)
from log_parser_tpu.runtime.engine import AnalysisEngine


def _ring_halo(x: jax.Array, h: int, d: int) -> jax.Array:
    """[Bl, K] -> [h + Bl + h, K]: h rows from each ring neighbor via
    ppermute; edge shards receive zeros (ppermute's missing-source fill).
    ``d`` is the mesh axis size — the permutation list must be static, so
    the caller passes it rather than querying the traced axis."""
    from_left = jax.lax.ppermute(
        x[-h:], DATA_AXIS, [(i, i + 1) for i in range(d - 1)]
    )
    from_right = jax.lax.ppermute(
        x[:h], DATA_AXIS, [(i + 1, i) for i in range(d - 1)]
    )
    return jnp.concatenate([from_left, x, from_right], axis=0)


class ShardedFusedStep:
    """The full per-batch SPMD program, shard_mapped over the mesh."""

    def __init__(
        self,
        bank: PatternBank,
        config: ScoringConfig,
        mesh,
        matchers,
        multiprocess: bool | None = None,
    ):
        self.bank = bank
        self.config = config
        self.mesh = mesh
        self.matchers = matchers  # MatcherBanks: tiered Shift-Or + DFA cube
        self.t = FusedStaticTables(bank, config)
        self.n_shards = mesh.devices.size

        # static halo requirement per factor family
        self.h_prox = int(self.t.sec_window.max()) if len(self.t.sec_window) else 0
        has_rules = bank.has_context_rules
        self.h_ctx = int(
            max(
                bank.ctx_before[has_rules].max(initial=0),
                bank.ctx_after[has_rules].max(initial=0),
            )
        ) if bank.n_patterns else 0

        self._jit = jax.jit(
            lambda kl, lines, lens, om, ov, n: self._sharded(kl)(lines, lens, om, ov, n),
            static_argnums=(0,),
        )
        # one mesh may span multiple processes (parallel/distributed.py);
        # then inputs must be assembled as global arrays (each process
        # donating its addressable shards) and outputs gathered across
        # processes before host assembly. A process-local mesh inside a
        # multi-process runtime (the degrade-to-local step) passes an
        # explicit False: its collectives must never leave this process.
        self.multiprocess = (
            jax.process_count() > 1 if multiprocess is None else multiprocess
        )

    # ------------------------------------------------- host<->device helpers

    def _put(self, x, spec) -> jax.Array:
        """Device-put respecting the multi-process mesh: every process holds
        the full host value (requests are replicated by broadcast), so each
        donates the shards it addresses."""
        if not self.multiprocess:
            return jnp.asarray(x)
        from jax.sharding import NamedSharding

        arr = np.asarray(x)
        return jax.make_array_from_callback(
            arr.shape, NamedSharding(self.mesh, spec), lambda idx: arr[idx]
        )

    def _host(self, x) -> np.ndarray:
        """Fetch a (possibly process-spanning) device array to every host."""
        if not self.multiprocess:
            return np.asarray(x)
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(x, tiled=True))

    def _sharded(self, k_local: int):
        return shard_map(
            lambda lines, lens, om, ov, n: self._step(k_local, lines, lens, om, ov, n),
            mesh=self.mesh,
            in_specs=(
                P(DATA_AXIS, None),  # lines [B, T] (transposed on device)
                P(DATA_AXIS),  # lengths [B]
                P(DATA_AXIS, None),  # override_mask [B, C]
                P(DATA_AXIS, None),  # override_val [B, C]
                P(),  # n_lines
            ),
            out_specs=(
                P(DATA_AXIS),  # n_matches per shard [D]
                P(DATA_AXIS),  # rec line (global) [D*K_l]
                P(DATA_AXIS),  # rec pattern [D*K_l]
                P(DATA_AXIS, None),  # rec sec dists [D*K_l, S_max]
                P(DATA_AXIS, None),  # rec seq flags [D*K_l, Q_max]
                P(DATA_AXIS, None),  # rec ctx counts [D*K_l, 5]
            ),
            check_rep=False,
        )

    # ------------------------------------------------------------- host API

    def __call__(
        self,
        lines_u8: np.ndarray,
        lengths: np.ndarray,
        override_mask: np.ndarray,
        override_val: np.ndarray,
        n_lines: int,
        k_hint: int = 0,
    ) -> MatchRecords:
        """Runs the SPMD step, growing per-shard record buffers until every
        shard's matches fit; returns globally-ordered match records."""
        B = lines_u8.shape[0]
        D = self.n_shards
        cap_local = (B // D) * max(1, self.bank.n_patterns)
        # contiguous [B, T] upload; the step transposes on device (a host
        # .T copy measured ~9x the contiguous upload — ops/fused.py)
        lines_bt = self._put(lines_u8, P(DATA_AXIS, None))
        lens = self._put(lengths, P(DATA_AXIS))
        om = self._put(override_mask, P(DATA_AXIS, None))
        ov = self._put(override_val, P(DATA_AXIS, None))
        n = self._put(np.asarray(n_lines, dtype=np.int32), P())

        start = 0
        per_shard_hint = -(-max(1, k_hint) // D)
        while start < len(K_LADDER) - 1 and K_LADDER[start] < per_shard_hint:
            start += 1
        for k_bucket in (*K_LADDER[start:], cap_local):
            k_l = min(k_bucket, cap_local)
            out = self._jit(k_l, lines_bt, lens, om, ov, n)
            n_per_shard = self._host(out[0])
            if n_per_shard.max(initial=0) <= k_l or k_l >= cap_local:
                return self._assemble(k_l, n_per_shard, out)
        raise AssertionError("unreachable: ladder capped at per-shard B*P")

    def _assemble(self, k_l: int, n_per_shard: np.ndarray, out) -> MatchRecords:
        """Concatenate each shard's live records; shard-major order is
        line-major order because line sharding is contiguous."""
        D = self.n_shards
        line = self._host(out[1]).reshape(D, k_l)
        pat = self._host(out[2]).reshape(D, k_l)
        dist = self._host(out[3]).reshape(D, k_l, -1)
        seq = self._host(out[4]).reshape(D, k_l, -1)
        ctx = self._host(out[5]).reshape(D, k_l, -1)
        keep = [np.arange(min(int(n), k_l)) for n in n_per_shard]
        return MatchRecords(
            n_matches=int(sum(len(k) for k in keep)),
            line=np.concatenate([line[d, k] for d, k in enumerate(keep)] or [line[0, :0]]),
            pattern=np.concatenate([pat[d, k] for d, k in enumerate(keep)] or [pat[0, :0]]),
            sec_dist=np.concatenate([dist[d, k] for d, k in enumerate(keep)] or [dist[0, :0]]),
            seq_ok=np.concatenate([seq[d, k] for d, k in enumerate(keep)] or [seq[0, :0]]),
            ctx_counts=np.concatenate([ctx[d, k] for d, k in enumerate(keep)] or [ctx[0, :0]]),
        )

    # ------------------------------------------------------------ the step

    def _step(self, K, lines_bt, lengths, override_mask, override_val, n_lines):
        lines_tb = lines_bt.T  # device-side layout change (see run())
        bank, t = self.bank, self.t
        Bl = lengths.shape[0]
        P_ = bank.n_patterns
        d = jax.lax.axis_index(DATA_AXIS)
        lidx = jnp.arange(Bl, dtype=jnp.int32)
        gidx = (d * Bl + lidx).astype(jnp.int32)
        valid = gidx < n_lines

        # ---- local match (no communication; tiered Shift-Or + DFA) --------
        # barrier as in ops/fused.py: keep XLA from fusing factor
        # extraction back into the scan loops
        cube = jax.lax.optimization_barrier(
            self.matchers.cube(lines_tb, lengths)
        )
        cube = jnp.where(override_mask, override_val, cube)
        cube = cube & valid[:, None]

        if P_ == 0:
            z32 = jnp.zeros((K,), jnp.int32)
            return (
                jnp.zeros((1,), jnp.int32),
                z32,
                z32,
                jnp.full((K, max(1, t.s_max)), NO_HIT, jnp.int32),
                jnp.zeros((K, max(1, t.q_max)), bool),
                jnp.zeros((K, 5), jnp.int32),
            )

        pm = cube[:, jnp.asarray(bank.primary_columns)]  # [Bl, P]

        sec_dist = self._secondary_distances(cube, lidx, Bl)
        seq_ok = self._sequence_flags(cube, gidx, Bl, n_lines)
        ctx_counts = self._context_counts(cube, gidx, lidx, Bl, n_lines)

        # per-shard compaction: emit global line indexes, gather local rows
        n_matches, rec_gline, rec_pat, rec_dist, rec_seq, rec_ctx = compact_records(
            K, pm, t, gidx, lidx, sec_dist, seq_ok, ctx_counts
        )
        return n_matches[None], rec_gline, rec_pat, rec_dist, rec_seq, rec_ctx

    # ---------------------------------------------------------- factor parts

    def _extend(self, cols: jax.Array, h: int, Bl: int):
        """Neighborhood view of sharded columns: (extended array, offset of
        local row 0). ppermute halo when shards are big enough; all_gather
        when the halo would span multiple shards."""
        if h < Bl:
            return _ring_halo(cols, h, self.n_shards), h  # offset is static
        gathered = jax.lax.all_gather(cols, DATA_AXIS, axis=0, tiled=True)
        d = jax.lax.axis_index(DATA_AXIS)
        return gathered, d * Bl  # offset is traced

    def _secondary_distances(self, cube, lidx, Bl):
        """[Bl, n_sec_entries] int32 nearest-hit distance per local line.
        Exact for every in-window hit: any hit within window ≤ h is inside
        the extended view; farther hits report NO_HIT, which the finalizer
        treats identically to out-of-window (ScoringService.java:315-347)."""
        t = self.t
        if len(t.sec_cols) == 0:
            return jnp.full((Bl, 1), NO_HIT, jnp.int32)
        sm = cube[:, jnp.asarray(t.sec_cols)]  # [Bl, S]
        h = max(1, self.h_prox)
        ext, off = self._extend(sm, h, Bl)
        mine = off + lidx  # my rows in ext coordinates
        return _prev_next_dist(ext, jnp.arange(ext.shape[0], dtype=jnp.int32))[mine]

    def _sequence_flags(self, cube, gidx, Bl, n_lines):
        """[Bl, n_sequences] — the backward chain reads arbitrarily far back
        (ScoringService.java:296-305), so the event columns are all_gathered
        and the shared chain logic runs in global coordinates for local rows."""
        t = self.t
        if not self.bank.sequences:
            return jnp.zeros((Bl, 1), dtype=bool)
        em_local = cube[:, jnp.asarray(t.seq_event_cols, dtype=np.int32)]  # [Bl, E]
        em = jax.lax.all_gather(em_local, DATA_AXIS, axis=0, tiled=True)  # [B, E]
        return sequence_flags_from_events(self.bank.sequences, t, em, gidx, n_lines)

    def _context_counts(self, cube, gidx, lidx, Bl, n_lines):
        """[Bl, U, 5] int32 per unique context shape, window sums via
        halo-extended prefix sums with the global clamps of
        AnalysisService.java:142,148 expressed on the global index."""
        t = self.t
        err = cube[:, CTX_ERROR]
        warn = cube[:, CTX_WARN] & ~err
        stack = cube[:, CTX_STACK]
        exc = cube[:, CTX_EXCEPTION]
        flags = jnp.stack([err, warn, stack, exc], axis=1).astype(jnp.int32)  # [Bl, 4]

        h = max(1, self.h_ctx)
        ext, off = self._extend(flags, h, Bl)
        ps = _prefix(ext)  # [ext+1, 4]
        ext_len = ext.shape[0]
        mine = off + lidx

        per_shape = []
        for has_rules, before, after in t.ctx_shapes:
            if not has_rules:
                counts = flags
                total = jnp.ones((Bl,), jnp.int32)
            else:
                lo_g = jnp.maximum(gidx - before, 0)
                hi_g = jnp.minimum(gidx + 1 + after, n_lines).astype(jnp.int32)
                hi_g = jnp.maximum(hi_g, lo_g)
                total = hi_g - lo_g
                lo_e = jnp.clip(mine - (gidx - lo_g), 0, ext_len)
                hi_e = jnp.clip(mine + (hi_g - gidx), 0, ext_len)
                counts = ps[hi_e] - ps[lo_e]  # [Bl, 4]
            per_shape.append(jnp.concatenate([counts, total[:, None]], axis=1))
        return jnp.stack(per_shape, axis=1)  # [Bl, U, 5]


class ShardedEngine(AnalysisEngine):
    """AnalysisEngine whose device step is the shard_map program: the line
    batch is sharded over the mesh, and every other responsibility (ingest,
    host verification, frequency tracking, exact-f64 finalization, result
    assembly, observability) is the inherited shared pipeline."""

    def __init__(self, pattern_sets, config=None, mesh=None, clock=None):
        import time as _time

        super().__init__(pattern_sets, config, clock=clock or pclock.mono)
        if mesh is None:
            from log_parser_tpu.parallel.mesh import make_mesh

            mesh = make_mesh()
        self.mesh = mesh
        self.step = ShardedFusedStep(self.bank, self.config, mesh, self.matchers)
        self.tables = self.step.t

    def _install_library(self, source) -> None:
        # the SPMD program and its static tables are compiled against the
        # bank — rebuild both on the swapped library (hot reload)
        super()._install_library(source)
        self.step = ShardedFusedStep(
            self.bank, self.config, self.mesh, self.matchers
        )
        self.tables = self.step.t

    def _corpus_min_rows(self) -> int:
        # row padding must be divisible by the mesh size for shard_map
        return max(8, self.mesh.devices.size)

    def _run_device(self, enc, n_lines: int, om, ov, trace=None):
        B = enc.u8.shape[0]
        C = self.bank.n_columns
        if om is None:  # the SPMD program's in_specs always take overrides
            om = np.zeros((B, C), dtype=bool)
            ov = np.zeros((B, C), dtype=bool)
        return self.step(
            enc.u8, enc.lengths, om, ov, n_lines, k_hint=self._k_hint
        )
