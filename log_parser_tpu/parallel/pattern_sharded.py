"""Pattern-axis sharding — the workload's tensor-parallel analogue.

SURVEY.md §2.2: for high-cardinality libraries (BASELINE config 4, 10k
regexes) the compiled automaton bank itself is the big operand, so it is
partitioned across devices instead of the lines: device d holds the DFA
bank of pattern block d and scans the *full* (replicated) line batch
through it. Blocks are embarrassingly parallel — JAX's async dispatch runs
all D programs concurrently, one per device — and there is no collective
at all: each block emits its own K-capped integer match records
(ops/fused.py) with *global* pattern indexes, the host merges the blocks
by (line, pattern) — restoring the reference's discovery order
(line-major, then pattern order, AnalysisService.java:89-113) — and the
shared exact-f64 finalizer recovers frequency priors from the merged
stream.

Matcher columns shared between patterns in different blocks (interned
regexes) are re-scanned per block: duplicated compute is the standard
tensor-parallel trade for never materializing a [lines × 10k-pattern]
cube on one chip.

Composes with line sharding: a 2D fleet runs this engine per line shard.

Tenant placement (multi-tenant fleets, runtime/tenancy.py) is the third
partitioning axis: each tenant's bank is DISJOINT, so there is nothing to
merge — :class:`TenantPlacement` round-robins whole tenant engines across
the visible chips and pins each engine's device step there. One tenant's
traffic then never contends for another tenant's chip, and a tenant bank
rebuild recompiles only on its own device.
"""

from __future__ import annotations

import time
from typing import Callable

import jax
import numpy as np

from log_parser_tpu import _clock as pclock
from log_parser_tpu.config import ScoringConfig
from log_parser_tpu.models.pattern import PatternSet, PatternSetMetadata
from log_parser_tpu.ops.fused import FusedMatchScore, MatchRecords
from log_parser_tpu.ops.match import MatcherBanks
from log_parser_tpu.patterns.bank import PatternBank
from log_parser_tpu.runtime.engine import AnalysisEngine


def partition_pattern_sets(
    pattern_sets: list[PatternSet], n_blocks: int
) -> list[list[PatternSet]]:
    """Split a library into ``n_blocks`` contiguous pattern blocks of
    near-equal pattern count, preserving set-major discovery order. Each
    block becomes a list of (synthetic, single-slice) PatternSets so every
    block's PatternBank sees the same per-set structure."""
    flat: list[tuple[PatternSet, object]] = []
    for ps in pattern_sets:
        for p in ps.patterns or []:
            flat.append((ps, p))
    n_blocks = max(1, min(n_blocks, max(1, len(flat))))
    base, extra = divmod(len(flat), n_blocks)  # balanced: no empty blocks
    blocks: list[list[PatternSet]] = []
    lo = 0
    for b in range(n_blocks):
        hi = lo + base + (1 if b < extra else 0)
        chunk = flat[lo:hi]
        lo = hi
        sets: list[PatternSet] = []
        for src, pattern in chunk:
            if sets and sets[-1].metadata is src.metadata:
                sets[-1].patterns.append(pattern)
            else:
                sets.append(
                    PatternSet(metadata=src.metadata, patterns=[pattern])
                )
        blocks.append(sets)
    return blocks


class PatternShardedEngine(AnalysisEngine):
    """AnalysisEngine whose device step fans the pattern blocks out over
    the visible devices (or ``devices``), one fused program per block."""

    def __init__(
        self,
        pattern_sets: list[PatternSet],
        config: ScoringConfig | None = None,
        devices: list | None = None,
        n_blocks: int | None = None,
        clock: Callable[[], float] = pclock.mono,
    ):
        # the base engine's bank carries the FULL library: finalization,
        # frequency slots, event assembly, and global pattern indexes all
        # come from it. Per-block banks drive only the device programs.
        super().__init__(pattern_sets, config, clock=clock)
        self.devices = devices if devices is not None else jax.devices()
        n = n_blocks if n_blocks is not None else len(self.devices)
        self.blocks = partition_pattern_sets(pattern_sets, n)

        self._block_engines: list[tuple[FusedMatchScore, np.ndarray, object]] = []
        offset = 0
        for b, block_sets in enumerate(self.blocks):
            # single-block partition == the full library: reuse the base
            # bank instead of compiling a duplicate (halves boot time on
            # one device; the 10k warm ctor measured 3.4 -> ~1.8 s)
            bank = self.bank if len(self.blocks) == 1 else PatternBank(block_sets)
            fused = FusedMatchScore(bank, self.config, MatcherBanks(bank))
            # block-local pattern idx -> global pattern idx (discovery order
            # is preserved by contiguous partitioning)
            global_idx = np.arange(offset, offset + bank.n_patterns, dtype=np.int32)
            offset += bank.n_patterns
            device = self.devices[b % len(self.devices)]
            self._block_engines.append((fused, global_idx, device))
        assert offset == self.bank.n_patterns, (
            "block partition must cover the full bank exactly "
            f"({offset} != {self.bank.n_patterns})"
        )

    def _approx_sources_token(self) -> tuple:
        return tuple(f.matchers for f, _g, _d in self._block_engines)

    def _approx_col_sources(self):
        """Each block's device program truncates against its OWN bank
        (role sets are computed per block, so a column primary-only in
        one block may stay exact in another); union every block's
        (approx_cols, bank, global pattern offset) so flagged events of
        any block get host-verified."""
        out = []
        offset = 0
        for fused, _global_idx, _dev in self._block_engines:
            out.append(
                (getattr(fused.matchers, "approx_cols", []), fused.bank, offset)
            )
            offset += fused.bank.n_patterns
        return out

    def _block_overrides(self, fused: FusedMatchScore, om, ov):
        """Overrides index the FULL bank's columns; each block re-derives
        its slice by interned regex key."""
        if om is None:
            return None, None
        cols = [
            self._col_index.get((c.regex, c.case_insensitive))
            for c in fused.bank.columns
        ]
        missing = [
            fused.bank.columns[i].regex for i, c in enumerate(cols) if c is None
        ]
        # block patterns are by construction a subset of the full bank; a
        # lookup miss means the intern table and the blocks diverged, and
        # defaulting would silently apply the wrong column's overrides.
        # RuntimeError, not assert: this invariant must hold under -O too
        # (ADVICE.md r2) — an object array of Nones would otherwise fail
        # obscurely downstream.
        if missing:
            raise RuntimeError(
                f"block columns missing from full bank: {missing[:3]}"
            )
        take = np.asarray(cols)
        return np.ascontiguousarray(om[:, take]), np.ascontiguousarray(ov[:, take])

    def _run_device(self, enc, n_lines: int, om, ov, trace=None):
        """Fan every block out asynchronously — one fused program per
        device — and only then start the blocking reads, so device work
        overlaps (wall-clock ≈ slowest block, not the sum). Blocks whose
        record buffer overflows re-dispatch at the next ladder rung."""
        k_hint = max(1, self._k_hint // max(1, len(self._block_engines)))
        pending = []
        for fused, global_idx, device in self._block_engines:
            b_om, b_ov = self._block_overrides(fused, om, ov)
            ladder, _ = fused.k_ladder(enc.u8, k_hint)
            with jax.default_device(device):
                out = fused.dispatch(
                    ladder[0], enc.u8, enc.lengths, n_lines, b_om, b_ov
                )
            pending.append((fused, global_idx, device, b_om, b_ov, ladder, out))

        outs: list[MatchRecords] = []
        for fused, global_idx, device, b_om, b_ov, ladder, out in pending:
            recs = fused.resolve(out)
            for k in ladder[1:]:
                if recs is not None:
                    break
                with jax.default_device(device):
                    out = fused.dispatch(k, enc.u8, enc.lengths, n_lines, b_om, b_ov)
                recs = fused.resolve(out)
            assert recs is not None, "K ladder is capped at B*P"
            outs.append(self._globalize(recs, global_idx))
        return self._merge(outs)

    @property
    def _col_index(self) -> dict:
        return self.bank._column_by_key

    def _approx_global_cols(self) -> set:
        """Union of every block's approximate columns, translated from
        block-local to full-bank indexes by interned (regex, ci) key —
        conservative (see AnalysisEngine._approx_secondaries): a column
        exact in the block that ran a given pattern repairs as a no-op."""
        out: set = set()
        for fused, _global_idx, _dev in self._block_engines:
            for c in getattr(fused.matchers, "approx_cols", []):
                col = fused.bank.columns[c]
                g = self._col_index.get((col.regex, col.case_insensitive))
                if g is not None:
                    out.add(g)
        return out

    def _globalize(self, recs: MatchRecords, global_idx: np.ndarray) -> MatchRecords:
        """Rewrite block-local pattern indexes to full-bank indexes."""
        m = recs.n_matches
        if m:
            recs.pattern = recs.pattern.copy()
            recs.pattern[:m] = global_idx[recs.pattern[:m]]
        return recs

    def _merge(self, outs: list[MatchRecords]) -> MatchRecords:
        """Merge block record streams into global discovery order. Records
        within a block are (line, pattern)-sorted already; blocks partition
        the pattern axis contiguously, so a stable sort on (line, pattern)
        restores line-major-then-pattern order."""
        t = self.tables
        s_max = max(1, t.s_max)
        q_max = max(1, t.q_max)
        line = np.concatenate([o.line[: o.n_matches] for o in outs])
        pat = np.concatenate([o.pattern[: o.n_matches] for o in outs])

        def pad(a: np.ndarray, width: int, fill) -> np.ndarray:
            if a.shape[1] == width:
                return a
            out = np.full((a.shape[0], width), fill, dtype=a.dtype)
            out[:, : a.shape[1]] = a
            return out

        from log_parser_tpu.ops.fused import NO_HIT

        # per-block S/Q pads differ; records carry the block's own pattern
        # tables' layout, which matches the global tables because blocks
        # preserve each pattern's own secondary/sequence lists
        sec = np.concatenate(
            [pad(o.sec_dist[: o.n_matches], s_max, NO_HIT) for o in outs]
        )
        seq = np.concatenate(
            [pad(o.seq_ok[: o.n_matches], q_max, False) for o in outs]
        )
        ctx = np.concatenate([o.ctx_counts[: o.n_matches] for o in outs])

        order = np.lexsort((pat, line))  # stable: line-major, then pattern
        return MatchRecords(
            n_matches=len(order),
            line=line[order],
            pattern=pat[order],
            sec_dist=sec[order],
            seq_ok=seq[order],
            ctx_counts=ctx[order],
        )


def pin_engine(engine: AnalysisEngine, device) -> AnalysisEngine:
    """Pin one engine's device step to ``device``: every fused dispatch
    (and its compilation cache) lands on that chip via
    ``jax.default_device``, while host phases (ingest, finalize, events)
    stay wherever the caller runs them. Idempotent re-pin: wraps the
    CURRENT step, so pinning twice just narrows to the newer device."""
    inner = engine._run_device

    def pinned(enc, n_lines, om, ov):
        with jax.default_device(device):
            return inner(enc, n_lines, om, ov)

    engine._run_device = pinned
    engine.placement_device = device
    return engine


class TenantPlacement:
    """Tenant-placement mode: disjoint per-tenant banks, one chip each.

    Unlike the pattern blocks above, tenant banks share NOTHING — no
    merge, no global index rewrite — so placement is pure scheduling:
    round-robin each new tenant engine onto the next device and pin its
    device step there. The ``assign`` method matches the
    ``engine_setup(engine, tenant_id)`` hook of
    :class:`~log_parser_tpu.runtime.tenancy.TenantRegistry`, so a serving
    fleet opts in with ``engine_setup=placement.assign`` (composed after
    any per-tenant cache/batcher setup). ``bench_mesh.py --tenants N``
    drives this mode end-to-end on a virtual or real mesh.
    """

    def __init__(self, devices: list | None = None, load=None):
        self.devices = list(devices) if devices is not None else jax.devices()
        if not self.devices:
            raise ValueError("TenantPlacement needs at least one device")
        self.assignments: dict[str, object] = {}
        self._next = 0
        # optional ``load(device) -> float``: when given, NEW tenants
        # prefer the least-loaded device — the single-process analogue
        # of the fleet placer (fleet/placement.py). No callback keeps
        # blind round-robin, which is also the fallback when the
        # callback itself fails (a broken load signal must not stop
        # placement).
        self.load = load

    def _pick(self):
        if self.load is not None:
            try:
                return min(self.devices, key=self.load)
            except Exception:
                pass
        device = self.devices[self._next % len(self.devices)]
        self._next += 1
        return device

    def assign(self, engine: AnalysisEngine, tenant_id: str) -> AnalysisEngine:
        """Place ``engine`` on the least-loaded device (with a load
        callback) or the next in rotation. A tenant re-assigned after
        eviction+rebuild lands back on ITS device, not the rotation's
        next one — placement stays stable under churn."""
        device = self.assignments.get(str(tenant_id))
        if device is None:
            device = self._pick()
            self.assignments[str(tenant_id)] = device
        return pin_engine(engine, device)

    def move(self, tenant_id: str, device=None) -> object:
        """Re-place a tenant: the next build of its engine pins to
        ``device`` (or the rotation's next chip). Placement moves are
        MIGRATIONS, not bare re-pins — the caller runs
        ``Migrator.migrate(tenant_id, LocalTarget(...))`` (runtime/
        migrate.py) so the tenant's frequency history, parked candidates
        and open sessions travel with it; this method only records where
        the rebuilt engine must land."""
        tid = str(tenant_id)
        if device is None:
            device = self._pick()
        self.assignments[tid] = device
        return device

    def stats(self) -> dict:
        return {
            "devices": len(self.devices),
            "placements": {t: str(d) for t, d in self.assignments.items()},
        }
