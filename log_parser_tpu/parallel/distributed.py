"""Multi-process (DCN) scale-out: one `jax.sharding.Mesh` spanning
processes, coordinated by `jax.distributed` (SURVEY.md §2.2/§5.8).

The reference is a single JVM with no inter-process communication at all;
its only network surface is HTTP :8080 (Dockerfile.native:28). The
TPU-native equivalent of "scale beyond one host" is NOT a message bus but
a bigger mesh: `jax.distributed.initialize` connects N processes (each
owning its local chips) into one runtime, `jax.devices()` becomes the
global device list, and the existing `shard_map` program from
parallel/sharded.py runs unchanged — XLA routes `ppermute`/`all_gather`
over ICI within a host and DCN between hosts.

Serving model: process 0 (the coordinator) owns the HTTP/gRPC surface.
Every process must participate in every SPMD dispatch, so the coordinator
broadcasts each request's raw payload to the followers
(`broadcast_one_to_all` rides the same distributed runtime), and every
process runs the identical analyze() pipeline in lockstep. Followers
discard their (identical) results; the coordinator answers the client.

Frequency note: each process evolves its own host-side frequency tracker
from the same deterministic request stream, so trackers agree except for
sub-second wall-clock skew at window boundaries. Device dispatches take no
frequency input (finalization is host-side, runtime/finalize.py), so skew
can never desynchronize the collectives; the coordinator's scores are the
canonical response. Admin mutations (reset/restore) apply on the
coordinator only — snapshot/restore across a restart re-seeds followers.
"""

from __future__ import annotations

import json
import logging

import numpy as np

from log_parser_tpu.models.pod import PodFailureData
from log_parser_tpu.parallel.sharded import ShardedEngine

log = logging.getLogger(__name__)

_SHUTDOWN = b"\x00shutdown"


def init_distributed(
    coordinator: str,
    num_processes: int,
    process_id: int,
    initialization_timeout: int = 120,
) -> None:
    """Join this process into the distributed runtime. After this call
    `jax.devices()` is the GLOBAL device list across all processes and
    `make_mesh()` builds a mesh spanning them."""
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
        initialization_timeout=initialization_timeout,
    )
    log.info(
        "distributed runtime up: process %d/%d, %d local + %d global devices",
        jax.process_index(),
        jax.process_count(),
        jax.local_device_count(),
        jax.device_count(),
    )


def broadcast_bytes(payload: bytes | None) -> bytes:
    """Broadcast a byte string from process 0 to every process (two
    fixed-shape collectives: an int64 length header, then the buffer).
    Non-coordinators pass ``None`` and receive the coordinator's bytes."""
    from log_parser_tpu.runtime import faults

    # chaos point BEFORE the first collective: an injected raise/hang here
    # models a coordinator dying (or stalling) pre-broadcast — the one
    # window where failure must not desync the follower group
    faults.fire("broadcast")
    from jax.experimental import multihost_utils as mh

    header = np.array(
        [len(payload) if payload is not None else 0], dtype=np.int64
    )
    n = int(np.asarray(mh.broadcast_one_to_all(header))[0])
    if n == 0:
        return b""
    buf = (
        np.frombuffer(payload, dtype=np.uint8)
        if payload is not None
        else np.zeros((n,), dtype=np.uint8)
    )
    out = np.asarray(mh.broadcast_one_to_all(buf))
    return out.tobytes()


class DistributedShardedEngine(ShardedEngine):
    """ShardedEngine over a process-spanning mesh with request fan-out.

    On the coordinator, :meth:`analyze` first replicates the request to
    every follower, then runs the inherited pipeline (whose device step
    all processes enter together). Followers sit in :meth:`follower_loop`
    replaying broadcast requests until :meth:`shutdown_followers`.
    """

    def __init__(self, pattern_sets, config=None, mesh=None, clock=None):
        super().__init__(pattern_sets, config, mesh=mesh, clock=clock)
        if self._is_multiprocess():
            # the golden host fallback is UNSAFE here: a device error on
            # one process would abandon an in-flight collective while the
            # other processes stay blocked inside it, desynchronizing (or
            # deadlocking) the mesh. All processes must fail the same
            # request symmetrically; the server answers with a 500 and the
            # group stays in lockstep for the next broadcast.
            self.fallback_to_golden = False

    def _is_multiprocess(self) -> bool:
        import jax

        return jax.process_count() > 1

    def _is_coordinator(self) -> bool:
        import jax

        return jax.process_index() == 0

    def analyze(self, data: PodFailureData):
        if self._is_multiprocess() and self._is_coordinator():
            payload = json.dumps(
                {"pod": data.pod, "logs": data.logs, "events": data.events}
            ).encode("utf-8")
            broadcast_bytes(payload)
        return super().analyze(data)

    def analyze_pipelined(self, data: PodFailureData):
        """Multi-process requests cannot pipeline: each request is a
        broadcast + lockstep SPMD dispatch on every process, so two
        concurrent prepare phases would interleave their broadcasts and
        desync the mesh. Serialize the whole request instead."""
        if self._is_multiprocess():
            with self.state_lock:
                return self.analyze(data)
        return super().analyze_pipelined(data)

    def follower_loop(self) -> None:
        """Run on processes > 0: participate in every broadcast request's
        SPMD dispatches until the coordinator shuts the group down."""
        if self._is_coordinator():
            raise RuntimeError("follower_loop must not run on the coordinator")
        while True:
            payload = broadcast_bytes(None)
            if payload == _SHUTDOWN or payload == b"":
                log.info("follower shutting down")
                return
            d = json.loads(payload.decode("utf-8"))
            data = PodFailureData(
                pod=d.get("pod"), logs=d.get("logs") or "", events=d.get("events")
            )
            try:
                super().analyze(data)
            except Exception:
                # containment: the coordinator saw the same failure on the
                # same deterministic input and answered the client with a
                # 500; the follower stays alive for the next request
                log.exception("follower analyze failed")

    def shutdown_followers(self) -> None:
        if self._is_multiprocess() and self._is_coordinator():
            broadcast_bytes(_SHUTDOWN)
