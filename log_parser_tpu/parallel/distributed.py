"""Multi-process (DCN) scale-out: one `jax.sharding.Mesh` spanning
processes, coordinated by `jax.distributed` (SURVEY.md §2.2/§5.8).

The reference is a single JVM with no inter-process communication at all;
its only network surface is HTTP :8080 (Dockerfile.native:28). The
TPU-native equivalent of "scale beyond one host" is NOT a message bus but
a bigger mesh: `jax.distributed.initialize` connects N processes (each
owning its local chips) into one runtime, `jax.devices()` becomes the
global device list, and the existing `shard_map` program from
parallel/sharded.py runs unchanged — XLA routes `ppermute`/`all_gather`
over ICI within a host and DCN between hosts.

Serving model: process 0 (the coordinator) owns the HTTP/gRPC surface.
Every process must participate in every SPMD dispatch, so the coordinator
broadcasts each request's raw payload to the followers
(`broadcast_one_to_all` rides the same distributed runtime), and every
process runs the identical analyze() pipeline in lockstep. Followers
discard their (identical) results; the coordinator answers the client.

Resilience (parallel/resilience.py): every coordinator→follower dispatch
runs under a deadline and is retried with backoff while it provably never
entered a collective; a group that stops acking is declared dead and the
coordinator flips to **degrade-to-local** — requests run on its local
devices through a private single-process `ShardedFusedStep` (or the
golden host path when it has none), stamped ``metadata.degraded =
"distributed-fallback"``. A background heartbeat (`_PING` broadcast +
ack `process_allgather`) keeps per-follower liveness fresh and re-admits
the mesh once followers respond again. The control-plane collectives are
behind a swappable :class:`Transport` so single-process tests can drive
the whole ladder with a stub follower group.

Frequency note: each process evolves its own host-side frequency tracker
from the same deterministic request stream, so trackers agree except for
sub-second wall-clock skew at window boundaries. Device dispatches take no
frequency input (finalization is host-side, runtime/finalize.py), so skew
can never desynchronize the collectives; the coordinator's scores are the
canonical response. Admin mutations (reset/restore) apply on the
coordinator only — snapshot/restore across a restart re-seeds followers.
During a degraded window only the coordinator advances its tracker; on
readmission followers resume from their pre-window state, which widens
the same benign skew and keeps the coordinator canonical.
"""

from __future__ import annotations

import json
import logging
import os
import threading

import numpy as np

from log_parser_tpu.models.pod import PodFailureData
from log_parser_tpu.parallel.resilience import (
    DEGRADED_MARKER,
    ENV_HEARTBEAT_S,
    MeshHealth,
    MeshUnavailable,
    RetryPolicy,
    dispatch_with_retry,
)
from log_parser_tpu.parallel.sharded import ShardedEngine, ShardedFusedStep
from log_parser_tpu.runtime import faults

log = logging.getLogger(__name__)

_SHUTDOWN = b"\x00shutdown"
_PING = b"\x00ping"
# reload-epoch broadcast: sentinel prefix + JSON {"epoch": N, "sets": [...]}
# — followers rebuild the library and swap in lockstep (runtime/reload.py)
_RELOAD = b"\x00reload:"


def init_distributed(
    coordinator: str,
    num_processes: int,
    process_id: int,
    initialization_timeout: int = 120,
) -> None:
    """Join this process into the distributed runtime. After this call
    `jax.devices()` is the GLOBAL device list across all processes and
    `make_mesh()` builds a mesh spanning them."""
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
        initialization_timeout=initialization_timeout,
    )
    log.info(
        "distributed runtime up: process %d/%d, %d local + %d global devices",
        jax.process_index(),
        jax.process_count(),
        jax.local_device_count(),
        jax.device_count(),
    )


class JaxProcessTransport:
    """The real control plane: byte broadcast + ack allgather as collectives
    over the `jax.distributed` runtime."""

    def process_count(self) -> int:
        import jax

        return jax.process_count()

    def process_index(self) -> int:
        import jax

        return jax.process_index()

    def broadcast(self, payload: bytes | None) -> bytes:
        """Broadcast a byte string from process 0 to every process (two
        fixed-shape collectives: an int64 length header, then the buffer).
        Non-coordinators pass ``None`` and receive the coordinator's
        bytes."""
        from jax.experimental import multihost_utils as mh

        header = np.array(
            [len(payload) if payload is not None else 0], dtype=np.int64
        )
        n = int(np.asarray(mh.broadcast_one_to_all(header))[0])
        if n == 0:
            return b""
        buf = (
            np.frombuffer(payload, dtype=np.uint8)
            if payload is not None
            else np.zeros((n,), dtype=np.uint8)
        )
        out = np.asarray(mh.broadcast_one_to_all(buf))
        return out.tobytes()

    def allgather(self, row: np.ndarray) -> np.ndarray:
        """Every process contributes one fixed-shape row; all receive the
        [P, ...] stack — the heartbeat ack channel."""
        from jax.experimental import multihost_utils as mh

        return np.asarray(mh.process_allgather(row))


_TRANSPORT: JaxProcessTransport = JaxProcessTransport()


def transport():
    return _TRANSPORT


def install_transport(t) -> object:
    """Swap the control-plane transport (tests install a stub follower
    group; ``None`` restores the real one). Returns the previous
    transport so callers can restore it."""
    global _TRANSPORT
    prev = _TRANSPORT
    _TRANSPORT = t if t is not None else JaxProcessTransport()
    return prev


def broadcast_bytes(payload: bytes | None) -> bytes:
    """Broadcast through the installed transport. The chaos point sits
    BEFORE the first collective: an injected raise/hang here models a peer
    dying (or stalling) pre-broadcast — the one window where failure must
    not desync the follower group."""
    faults.fire("broadcast")  # conlint: contained-by-caller (dispatch_with_retry / pre_swap)
    return transport().broadcast(payload)


class DistributedShardedEngine(ShardedEngine):
    """ShardedEngine over a process-spanning mesh with request fan-out.

    On the coordinator, :meth:`analyze` first replicates the request to
    every follower (bounded + retried, see module docstring), then runs
    the inherited pipeline (whose device step all processes enter
    together); with the follower group declared dead it serves locally
    instead. Followers sit in :meth:`follower_loop` replaying broadcast
    requests until :meth:`shutdown_followers`.
    """

    _LOCAL_STEP_UNBUILT = object()

    def __init__(self, pattern_sets, config=None, mesh=None, clock=None):
        super().__init__(pattern_sets, config, mesh=mesh, clock=clock)
        self.follower_errors = 0  # follower-side malformed-payload count
        self.mesh_health: MeshHealth | None = None
        self.retry_policy = RetryPolicy.from_env()
        self._local_step_cache = self._LOCAL_STEP_UNBUILT
        self._health_thread: threading.Thread | None = None
        self._health_stop: threading.Event | None = None
        if self._is_multiprocess():
            # the golden host fallback is UNSAFE here: a device error on
            # one process would abandon an in-flight collective while the
            # other processes stay blocked inside it, desynchronizing (or
            # deadlocking) the mesh. All processes must fail the same
            # request symmetrically; the server answers with a 500 and the
            # group stays in lockstep for the next broadcast.
            self.fallback_to_golden = False
            self.mesh_health = MeshHealth(transport().process_count())

    def _is_multiprocess(self) -> bool:
        return transport().process_count() > 1

    def _is_coordinator(self) -> bool:
        return transport().process_index() == 0

    # ----------------------------------------------------- bounded dispatch

    def _dispatch_broadcast(
        self, payload: bytes, label: str = "broadcast",
        trace_id: str | None = None,
    ) -> None:
        """One bounded, retried coordinator→follower broadcast. The fault
        sites and the cancellation check both sit BEFORE
        ``enter_collective``, so an abandoned (hung) attempt can never
        emit a stale broadcast after its deadline. When ``trace_id`` is
        given the dispatch stages a ``broadcast`` child span on that
        trace, so mesh fan-out attributes to its originating request."""

        def attempt(ctx):
            faults.fire("follower")  # conlint: contained-by-caller (dispatch_with_retry)
            faults.fire("broadcast")  # conlint: contained-by-caller (dispatch_with_retry)
            ctx.enter_collective()
            transport().broadcast(payload)

        recorder = None
        if trace_id is not None:
            spans = self.obs.spans

            def recorder(duration_s, attrs):
                spans.annotate(trace_id, "broadcast", duration_s, attrs=attrs)

        dispatch_with_retry(
            attempt, self.retry_policy, self.mesh_health, label=label,
            recorder=recorder,
        )

    # ------------------------------------------------------------- analyze

    def analyze(self, data: PodFailureData, request_id: str | None = None):
        if self._is_multiprocess() and self._is_coordinator():
            health = self.mesh_health
            if not health.degraded:
                # the trace id rides the broadcast payload so follower-side
                # work (logs, frames) can attribute to the originating
                # request; followers tolerate the extra key
                payload = json.dumps(
                    {"pod": data.pod, "logs": data.logs,
                     "events": data.events, "rid": request_id}
                ).encode("utf-8")
                try:
                    self._dispatch_broadcast(payload, trace_id=request_id)
                except MeshUnavailable as exc:
                    # the retry budget (or a wedge) already updated health;
                    # make the flip explicit even below the dead_after
                    # threshold — this REQUEST could not be dispatched
                    health.declare_degraded(str(exc))
                    log.error("degrading to local serving: %s", exc)
            if health.degraded:
                return self._analyze_degraded(data)
        return super().analyze(data, request_id=request_id)

    def analyze_pipelined(self, data: PodFailureData, request_id: str | None = None):
        """Multi-process requests cannot pipeline: each request is a
        broadcast + lockstep SPMD dispatch on every process, so two
        concurrent prepare phases would interleave their broadcasts and
        desync the mesh. Serialize the whole request instead (the
        heartbeat probe serializes on the same lock).

        The request scope is entered BEFORE ``state_lock`` — the same
        order :meth:`apply_library` relies on (quiesce, then lock). The
        nested scope inside ``analyze`` is reentrant, so this costs one
        thread-local increment."""
        if self._is_multiprocess():
            with self._request_scope():
                with self.state_lock:
                    return self.analyze(data, request_id=request_id)
        return super().analyze_pipelined(data, request_id=request_id)

    # ----------------------------------------------------- degrade-to-local

    @property
    def _local_step(self) -> ShardedFusedStep | None:
        """Lazy single-process SPMD step over this process's local devices
        — the degraded serving path. None when local devices are unusable
        (then the golden host path serves)."""
        if self._local_step_cache is self._LOCAL_STEP_UNBUILT:
            self._local_step_cache = None
            try:
                import jax

                local = jax.local_devices()
                if local:
                    from log_parser_tpu.parallel.mesh import make_mesh

                    self._local_step_cache = ShardedFusedStep(
                        self.bank,
                        self.config,
                        make_mesh(devices=local),
                        self.matchers,
                        multiprocess=False,
                    )
                    log.info(
                        "degrade-to-local: %d local devices ready", len(local)
                    )
            except Exception:
                log.exception(
                    "degrade-to-local: local step unavailable; degraded "
                    "requests will serve from the golden host path"
                )
        return self._local_step_cache

    def _run_device(self, enc, n_lines: int, om, ov, trace=None):
        # batch rows are padded to a multiple of the GLOBAL mesh size
        # (_corpus_min_rows), which the local device count divides — the
        # local shard_map sees the same shapes, just fewer shards
        if (
            self.mesh_health is not None
            and self.mesh_health.degraded
            and self._is_coordinator()
        ):
            step = self._local_step
            if step is None:
                raise RuntimeError("degraded mode: no usable local devices")
            B = enc.u8.shape[0]
            C = self.bank.n_columns
            if om is None:
                om = np.zeros((B, C), dtype=bool)
                ov = np.zeros((B, C), dtype=bool)
            return step(enc.u8, enc.lengths, om, ov, n_lines, k_hint=self._k_hint)
        return super()._run_device(enc, n_lines, om, ov, trace=trace)

    def _analyze_degraded(self, data: PodFailureData):
        """Serve one request without the followers: local SPMD step when
        this process owns devices, golden host path otherwise. The
        response is marked so callers can see it was served degraded."""
        health = self.mesh_health
        health.record_degraded_request()
        if self._local_step is not None:
            result = ShardedEngine.analyze(self, data)
        else:
            result = self._golden_serve(data)
        if result.metadata is not None:
            result.metadata.degraded = DEGRADED_MARKER
        return result

    # ------------------------------------------------------------ heartbeat

    def probe_mesh(self) -> bool:
        """One bounded heartbeat round-trip: broadcast the ``_PING``
        sentinel, gather one ack row ``[process_index, follower_errors]``
        per process, refresh :class:`MeshHealth`, and re-admit a degraded
        mesh on success. Callers in concurrent settings hold
        ``state_lock`` (a probe must never interleave with a request
        broadcast)."""
        if not (self._is_multiprocess() and self._is_coordinator()):
            return True
        health = self.mesh_health
        if health.wedged:
            return False
        t = transport()

        def attempt(ctx):
            faults.fire("heartbeat")  # conlint: contained-by-caller (dispatch_with_retry)
            ctx.enter_collective()
            t.broadcast(_PING)
            row = np.array([t.process_index(), 0], dtype=np.int64)
            return t.allgather(row)

        try:
            acks = dispatch_with_retry(
                attempt, self.retry_policy, health, label="heartbeat"
            )
        except MeshUnavailable as exc:
            health.record_probe(False)
            log.warning("heartbeat failed: %s", exc)
            return False
        for pid, errors in np.asarray(acks).reshape(-1, 2):
            if int(pid) != 0:
                health.record_ack(int(pid), int(errors))
        health.record_probe(True)
        if health.degraded:
            health.readmit()
        return True

    def start_health_loop(self, interval_s: float | None = None):
        """Coordinator-side heartbeat daemon: probes the follower group
        every ``interval_s`` (env ``LOG_PARSER_TPU_HEARTBEAT_S``; 0
        disables). Serializes with requests on ``state_lock``."""
        if not (self._is_multiprocess() and self._is_coordinator()):
            return None
        if self._health_thread is not None:
            return self._health_thread
        if interval_s is None:
            try:
                interval_s = float(os.environ.get(ENV_HEARTBEAT_S, "10"))
            except ValueError:
                interval_s = 10.0
        if interval_s <= 0:
            return None
        stop = threading.Event()

        def loop():
            while not stop.wait(interval_s):
                if self.mesh_health.wedged:
                    continue
                with self.state_lock:
                    if stop.is_set():
                        break
                    self.probe_mesh()

        thread = threading.Thread(target=loop, name="mesh-health", daemon=True)
        self._health_stop = stop
        self._health_thread = thread
        thread.start()
        log.info("mesh health loop up (every %gs)", interval_s)
        return thread

    def stop_health_loop(self) -> None:
        if self._health_stop is not None:
            self._health_stop.set()
        thread = self._health_thread
        self._health_thread = None
        self._health_stop = None
        if thread is not None:
            thread.join(timeout=0.5)  # best-effort; the thread is a daemon

    # ------------------------------------------------------------ followers

    def follower_loop(self) -> None:
        """Run on processes > 0: participate in every broadcast request's
        SPMD dispatches until the coordinator shuts the group down.
        Heartbeat pings are acked inline; malformed payloads are counted
        and skipped — a follower must outlive a coordinator bug."""
        if self._is_coordinator():
            raise RuntimeError("follower_loop must not run on the coordinator")
        t = transport()
        while True:
            payload = broadcast_bytes(None)
            if payload == _SHUTDOWN or payload == b"":
                log.info("follower shutting down")
                return
            if payload == _PING:
                row = np.array(
                    [t.process_index(), self.follower_errors], dtype=np.int64
                )
                t.allgather(row)
                continue
            if payload.startswith(_RELOAD):
                self._apply_reload_payload(payload[len(_RELOAD):])
                continue
            try:
                d = json.loads(payload.decode("utf-8"))
                data = PodFailureData(
                    pod=d.get("pod"),
                    logs=d.get("logs") or "",
                    events=d.get("events"),
                )
            except Exception as exc:
                self.follower_errors += 1
                log.warning(
                    "follower %d: malformed broadcast payload "
                    "(%d bytes, error #%d): %s — skipped",
                    t.process_index(),
                    len(payload),
                    self.follower_errors,
                    exc,
                )
                continue
            try:
                super().analyze(data)
            except Exception:
                # containment: the coordinator saw the same failure on the
                # same deterministic input and answered the client with a
                # 500; the follower stays alive for the next request
                log.exception("follower analyze failed")

    def _apply_reload_payload(self, raw: bytes) -> None:
        """Follower side of a reload-epoch broadcast: rebuild the library
        from the serialized pattern sets and swap in lockstep with the
        coordinator. The coordinator already canary-validated this exact
        library, so the follower applies without its own canary; a
        follower that still fails to build/apply keeps the old banks live
        and counts the error — the next heartbeat ack carries the count
        and the operator sees the epoch skew on /trace/last."""
        from log_parser_tpu.models.pattern import PatternSet
        from log_parser_tpu.runtime.engine import AnalysisEngine

        try:
            doc = json.loads(raw.decode("utf-8"))
            sets = [PatternSet.from_dict(d) for d in doc["sets"]]
            source = AnalysisEngine(sets, self.config)
            self.apply_library(source)
            log.info(
                "follower %d: reload epoch %s applied (%d pattern set(s))",
                transport().process_index(),
                doc.get("epoch"),
                len(sets),
            )
        except Exception:
            self.follower_errors += 1
            log.exception(
                "follower reload failed (error #%d); old banks stay live",
                self.follower_errors,
            )

    def broadcast_reload(self, sets) -> None:
        """Coordinator side: ship the new library to every follower as one
        reload-epoch broadcast. Runs inside apply_library's quiesced
        critical section (see runtime/reload.py), so it can never
        interleave with a request broadcast. A mesh that cannot take the
        broadcast marks itself DEGRADED and the coordinator swaps alone —
        degraded serving is coordinator-local, so responses stay
        consistent until the group is re-seeded."""
        if not (self._is_multiprocess() and self._is_coordinator()):
            return
        health = self.mesh_health
        if health is not None and health.degraded:
            return  # followers are already out of the serving path
        payload = _RELOAD + json.dumps(
            {
                "epoch": self.reload_epoch + 1,
                "sets": [s.to_dict() for s in sets],
            }
        ).encode("utf-8")
        try:
            self._dispatch_broadcast(payload, label="reload")
        except MeshUnavailable as exc:
            if health is not None:
                health.declare_degraded(str(exc))
            log.error(
                "reload broadcast failed — mesh DEGRADED, coordinator "
                "swaps alone: %s", exc,
            )

    def _install_library(self, source) -> None:
        super()._install_library(source)
        # the degrade-to-local step caches a program compiled against the
        # old bank — rebuild lazily on next degraded request
        self._local_step_cache = self._LOCAL_STEP_UNBUILT

    def shutdown_followers(self) -> None:
        if not (self._is_multiprocess() and self._is_coordinator()):
            return
        self.stop_health_loop()
        health = self.mesh_health
        if health is not None and health.wedged:
            # a sentinel into a torn collective would hang this process
            # too; followers exit on their own second-signal path
            log.warning("mesh wedged: skipping the shutdown sentinel")
            return
        try:
            self._dispatch_broadcast(_SHUTDOWN, label="shutdown")
        except MeshUnavailable as exc:
            log.warning(
                "followers unreachable for the shutdown sentinel (%s); "
                "they exit via their own signal handling",
                exc,
            )
