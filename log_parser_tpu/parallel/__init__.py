"""Distributed execution: line-axis data parallelism over a TPU mesh.

The reference is a single JVM thread (AnalysisService.java:89-113; SURVEY.md
§2.2 records zero parallelism). The TPU-native design shards the *line axis*
— the workload's one natural parallel axis — across the mesh with
``shard_map``, and reconstructs every cross-line dependency with the
narrowest possible collective (SURVEY.md §5.7-5.8):

- proximity / context windows read ≤ max(window) neighboring lines →
  ``ppermute`` halo exchange with the two ring neighbors (ICI traffic only);
- the unbounded backward sequence scan reads any earlier line → the (few)
  sequence-event columns are ``all_gather``-ed, then chains run locally;
- the frequency penalty needs a cross-shard exclusive prefix of per-slot
  match counts → ``all_gather`` of per-shard totals (+ ``psum`` for the
  batch total recorded into tracker state — the one collective the scoring
  *semantics* require, SURVEY.md §2.2);
- the chronological factor needs only the global line index — scalar math.

Multi-process (DCN) scale-out lives in ``parallel.distributed``: the same
mesh and shard_map program spanning processes via ``jax.distributed``,
with the coordinator broadcasting requests (imported lazily — it pulls in
``jax.experimental.multihost_utils``).
"""

from log_parser_tpu.parallel.mesh import make_mesh
from log_parser_tpu.parallel.pattern_sharded import (
    PatternShardedEngine,
    TenantPlacement,
    pin_engine,
)
from log_parser_tpu.parallel.sharded import ShardedEngine, ShardedFusedStep

__all__ = [
    "PatternShardedEngine",
    "ShardedEngine",
    "ShardedFusedStep",
    "TenantPlacement",
    "make_mesh",
    "pin_engine",
]
