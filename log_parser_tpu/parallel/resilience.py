"""Distributed resilience: follower health, bounded broadcast dispatch,
retry with backoff, and the degrade-to-local state machine.

The lockstep serving model of parallel/distributed.py has one structural
weakness: every coordinator→follower broadcast is a *collective*, so a
single dead or wedged follower stalls `broadcast_one_to_all` forever and
takes every future request down with it. The reference has no analogue
(one JVM, no peers); the admission ladder of PR 1 stops at the process
boundary. This module extends the same degrade-don't-block discipline
across the mesh:

- :class:`MeshHealth` — the coordinator's view of the follower group:
  per-follower last-ack time / consecutive-failure counts / error
  counters, the serving mode (``distributed`` / ``degraded`` /
  ``wedged``), and the counters surfaced on ``GET /trace/last``.
- :func:`bounded_call` — run a dispatch attempt on a worker thread under
  a deadline, exactly like the device watchdog (runtime/engine.py
  DeviceWatchdog): on timeout the worker is *abandoned*, never killed.
  The :class:`DispatchContext` handed to the attempt closes the inherent
  race: the attempt must call :meth:`DispatchContext.enter_collective`
  immediately before its first collective, which atomically refuses if
  the deadline already expired — so an abandoned attempt can never emit
  a stale broadcast that would desynchronize the follower group.
- :func:`dispatch_with_retry` — bounded attempts with exponential
  backoff + jitter up to a budget. Only *timeouts* are retried (and only
  when the attempt provably never entered a collective); exceptions
  propagate — an injected ``follower_raise`` models a logic bug exactly
  like every other non-device site. A timeout that fired *inside* a
  collective is unrecoverable by construction (the group's collective
  state is torn): the mesh is marked ``wedged`` and stays degraded until
  restart — no probe can re-admit a torn collective.

Knobs (env, mirrored by ``serve`` flags):

==============================================  ===========================
``LOG_PARSER_TPU_BROADCAST_TIMEOUT_S``          per-attempt deadline
                                                (default 60; 0 disables)
``LOG_PARSER_TPU_BROADCAST_RETRIES``            extra attempts (default 2)
``LOG_PARSER_TPU_BROADCAST_BACKOFF_S``          base backoff (default 0.05,
                                                doubled per retry + jitter)
``LOG_PARSER_TPU_HEARTBEAT_S``                  probe interval (default 10;
                                                0 disables the loop)
``LOG_PARSER_TPU_DEAD_AFTER``                   consecutive dispatch
                                                failures before the group
                                                is declared dead (def. 3)
==============================================  ===========================
"""

from __future__ import annotations

import dataclasses
import logging
import os
import random
import threading
import time
from log_parser_tpu import _clock as pclock

log = logging.getLogger(__name__)

ENV_TIMEOUT_S = "LOG_PARSER_TPU_BROADCAST_TIMEOUT_S"
ENV_RETRIES = "LOG_PARSER_TPU_BROADCAST_RETRIES"
ENV_BACKOFF_S = "LOG_PARSER_TPU_BROADCAST_BACKOFF_S"
ENV_HEARTBEAT_S = "LOG_PARSER_TPU_HEARTBEAT_S"
ENV_DEAD_AFTER = "LOG_PARSER_TPU_DEAD_AFTER"

MODE_DISTRIBUTED = "distributed"
MODE_DEGRADED = "degraded"

DEGRADED_MARKER = "distributed-fallback"


class BroadcastTimeout(RuntimeError):
    """One bounded dispatch attempt blew its deadline. ``entered_collective``
    records whether the abandoned worker had already committed to a
    collective when the deadline fired — True means retrying is unsafe."""

    def __init__(self, label: str, timeout_s: float, entered_collective: bool):
        state = "inside a collective" if entered_collective else "pre-collective"
        super().__init__(f"{label} dispatch exceeded {timeout_s:g}s ({state})")
        self.label = label
        self.timeout_s = timeout_s
        self.entered_collective = entered_collective


class MeshUnavailable(RuntimeError):
    """The retry budget is exhausted (or the mesh is wedged): the follower
    group cannot be reached. Callers degrade to local serving."""


class DispatchCancelled(Exception):
    """Raised inside an abandoned attempt at ``enter_collective`` — the
    deadline expired first, so the attempt must not touch the group."""


class DispatchContext:
    """Handshake between a bounded attempt and its deadline watcher."""

    def __init__(self):
        self._lock = threading.Lock()
        self._cancelled = False
        self._entered = False

    @property
    def cancelled(self) -> bool:
        return self._cancelled

    def enter_collective(self) -> None:
        """Commit to the first collective. Atomic vs. :meth:`cancel`: after
        this returns the watcher sees ``entered``; if the deadline won the
        race, :class:`DispatchCancelled` aborts the attempt before it can
        emit anything the followers would see."""
        with self._lock:
            if self._cancelled:
                raise DispatchCancelled()
            self._entered = True

    def cancel(self) -> bool:
        """Abandon the attempt; returns whether it had already entered a
        collective (observed atomically against :meth:`enter_collective`)."""
        with self._lock:
            self._cancelled = True
            return self._entered


def bounded_call(fn, timeout_s: float, label: str = "broadcast"):
    """Run ``fn(ctx)`` under a deadline on a daemon worker thread; on
    timeout the worker is abandoned (a blocked collective cannot be
    interrupted — same policy as the device watchdog) and
    :class:`BroadcastTimeout` carries whether it had entered a collective.
    ``timeout_s <= 0`` runs inline, unbounded."""
    ctx = DispatchContext()
    if timeout_s is None or timeout_s <= 0:
        return fn(ctx)
    box: dict = {}
    done = threading.Event()

    def run():
        try:
            box["value"] = fn(ctx)
        except BaseException as exc:  # surfaced to the caller below
            box["error"] = exc
        finally:
            done.set()

    worker = threading.Thread(target=run, name=f"dispatch-{label}", daemon=True)
    worker.start()
    if not done.wait(timeout_s):
        entered = ctx.cancel()
        raise BroadcastTimeout(label, timeout_s, entered_collective=entered)
    err = box.get("error")
    if err is not None:
        if isinstance(err, DispatchCancelled):  # lost the race post-cancel
            raise BroadcastTimeout(label, timeout_s, entered_collective=False)
        raise err
    return box.get("value")


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Deadline + budget for one logical dispatch."""

    timeout_s: float = 60.0
    retries: int = 2
    backoff_s: float = 0.05
    max_backoff_s: float = 2.0
    jitter: float = 0.5  # +[0, jitter) fraction of the delay

    @classmethod
    def from_env(cls, env=None) -> "RetryPolicy":
        env = os.environ if env is None else env

        def _f(key: str, default: float) -> float:
            try:
                return float(env.get(key, default))
            except (TypeError, ValueError):
                return default

        return cls(
            timeout_s=_f(ENV_TIMEOUT_S, cls.timeout_s),
            retries=max(0, int(_f(ENV_RETRIES, cls.retries))),
            backoff_s=_f(ENV_BACKOFF_S, cls.backoff_s),
        )

    def delay_for(self, attempt: int) -> float:
        """Exponential backoff + jitter before retry ``attempt`` (1-based)."""
        base = min(self.max_backoff_s, self.backoff_s * (2 ** (attempt - 1)))
        return base * (1.0 + self.jitter * random.random())


def dispatch_with_retry(
    fn,
    policy: RetryPolicy,
    health: "MeshHealth | None" = None,
    label: str = "broadcast",
    sleep=pclock.sleep,
    recorder=None,
):
    """Bounded attempts of ``fn(ctx)`` with backoff between them. Retries
    ONLY pre-collective timeouts; an in-collective timeout wedges the mesh
    (see module docstring) and exceptions propagate unretried. Raises
    :class:`MeshUnavailable` when the budget is spent.

    ``recorder`` (optional) is called once with ``(duration_s, attrs)``
    after the dispatch resolves — the span hook that attributes mesh
    work to its originating request trace (``broadcast`` spans,
    obs/spans.py). Recorder failures never fail a dispatch."""
    t0 = pclock.mono()

    def _record(outcome: str, attempts: int) -> None:
        if recorder is None:
            return
        try:
            recorder(
                pclock.mono() - t0,
                {"label": label, "outcome": outcome, "attempts": attempts},
            )
        except Exception:  # pragma: no cover - observability is best-effort
            log.exception("%s: dispatch recorder failed", label)

    last: BroadcastTimeout | None = None
    attempts = 0
    for attempt in range(policy.retries + 1):
        if attempt:
            if health is not None:
                health.record_retry()
            sleep(policy.delay_for(attempt))
        attempts = attempt + 1
        try:
            result = bounded_call(fn, policy.timeout_s, label=label)
        except BroadcastTimeout as exc:
            last = exc
            if health is not None:
                health.record_broadcast_timeout()
            if exc.entered_collective:
                if health is not None:
                    health.mark_wedged(str(exc))
                log.error("%s: %s — collective state torn, not retrying", label, exc)
                break
            log.warning(
                "%s: %s (attempt %d/%d)", label, exc, attempt + 1, policy.retries + 1
            )
        else:
            _record("ok", attempts)
            return result
    _record("exhausted", attempts)
    raise MeshUnavailable(f"{label}: retry budget exhausted: {last}") from last


class MeshHealth:
    """Coordinator-side liveness view of the follower group.

    Thread-safe; updated from the request path (dispatch timeouts), the
    heartbeat loop (acks / probe outcomes), and read by ``/trace/last``
    and ``/q/health``. Followers are identified by process index 1..P-1."""

    def __init__(
        self,
        process_count: int,
        dead_after: int | None = None,
        clock=pclock.mono,
    ):
        if dead_after is None:
            try:
                dead_after = int(os.environ.get(ENV_DEAD_AFTER, "3"))
            except ValueError:
                dead_after = 3
        self._lock = threading.Lock()
        self._clock = clock
        self.process_count = process_count
        self.dead_after = max(1, dead_after)
        self.mode = MODE_DISTRIBUTED
        self.wedged = False
        self.reason: str | None = None
        self.followers: dict[int, dict] = {
            pid: {"last_seen": None, "consecutive_failures": 0, "errors": 0}
            for pid in range(1, process_count)
        }
        self.broadcast_timeouts = 0
        self.broadcast_retries = 0
        self.degraded_requests = 0
        self.probes = 0
        self.probe_failures = 0
        self.readmissions = 0

    # ------------------------------------------------------------ transitions

    @property
    def degraded(self) -> bool:
        return self.mode != MODE_DISTRIBUTED

    def record_broadcast_timeout(self) -> None:
        """One bounded attempt timed out: every follower is a suspect (the
        collective gives no per-peer attribution). Crossing ``dead_after``
        consecutive failures declares the group dead."""
        with self._lock:
            self.broadcast_timeouts += 1
            worst = 0
            for row in self.followers.values():
                row["consecutive_failures"] += 1
                worst = max(worst, row["consecutive_failures"])
            if worst >= self.dead_after and self.mode == MODE_DISTRIBUTED:
                self._declare(
                    f"{worst} consecutive dispatch failures (threshold "
                    f"{self.dead_after})"
                )

    def record_retry(self) -> None:
        with self._lock:
            self.broadcast_retries += 1

    def declare_degraded(self, reason: str) -> None:
        with self._lock:
            if self.mode == MODE_DISTRIBUTED:
                self._declare(reason)

    def _declare(self, reason: str) -> None:  # caller holds the lock
        self.mode = MODE_DEGRADED
        self.reason = reason
        log.error("mesh degraded: %s — serving locally until followers ack", reason)

    def mark_wedged(self, reason: str) -> None:
        """A dispatch died inside a collective: the group's collective
        state is torn and no probe can restore it — degraded for good."""
        with self._lock:
            self.wedged = True
            if self.mode == MODE_DISTRIBUTED:
                self._declare(reason)
            self.reason = f"wedged: {reason}"

    def record_ack(self, pid: int, errors: int) -> None:
        """A heartbeat ack from follower ``pid`` (its malformed-payload
        error counter rides along for observability)."""
        with self._lock:
            row = self.followers.get(pid)
            if row is None:
                return
            row["last_seen"] = self._clock()
            row["consecutive_failures"] = 0
            row["errors"] = int(errors)

    def record_probe(self, ok: bool) -> None:
        with self._lock:
            self.probes += 1
            if not ok:
                self.probe_failures += 1

    def record_degraded_request(self) -> None:
        with self._lock:
            self.degraded_requests += 1

    def readmit(self) -> bool:
        """Back to distributed serving after a successful probe. Refused
        while wedged."""
        with self._lock:
            if self.wedged or self.mode == MODE_DISTRIBUTED:
                return False
            self.mode = MODE_DISTRIBUTED
            self.reason = None
            self.readmissions += 1
            for row in self.followers.values():
                row["consecutive_failures"] = 0
            log.info("mesh readmitted: followers ack again, distributed serving on")
            return True

    # ------------------------------------------------------------ reporting

    def stats(self) -> dict:
        """camelCase snapshot for ``GET /trace/last``."""
        with self._lock:
            now = self._clock()
            return {
                "mode": self.mode,
                "wedged": self.wedged,
                "reason": self.reason,
                "processCount": self.process_count,
                "deadAfter": self.dead_after,
                "followers": {
                    str(pid): {
                        "lastSeenAgoS": (
                            None
                            if row["last_seen"] is None
                            else round(now - row["last_seen"], 3)
                        ),
                        "consecutiveFailures": row["consecutive_failures"],
                        "errors": row["errors"],
                    }
                    for pid, row in self.followers.items()
                },
                "broadcastTimeouts": self.broadcast_timeouts,
                "broadcastRetries": self.broadcast_retries,
                "degradedRequests": self.degraded_requests,
                "probes": self.probes,
                "probeFailures": self.probe_failures,
                "readmissions": self.readmissions,
            }
