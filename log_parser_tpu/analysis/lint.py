"""Pattern-library lint: orchestrates every static pass over a library.

``lint_pattern_sets`` takes parsed :class:`PatternSet` models (NOT an
engine — nothing here compiles a bank or touches a device) and runs:

1. schema/metadata validation (ids, severities, confidences);
2. tier classification of every distinct column regex
   (:mod:`.tiers` — same entry points, same reason codes as the build);
3. ReDoS shape detection on the host-fallback path (:mod:`.redos`);
4. prefilter-quality scoring from the classifier's literal stats;
5. cross-pattern subsumption over the primary DFAs (:mod:`.subsumption`).

The report is consumed by ``tools/pattern_lint.py`` (CLI), the reload
ladder's lint stage (runtime/reload.py — findings become the structured
409 body under ``--lint-patterns=block``), and ``/trace/last``.
"""

from __future__ import annotations

import dataclasses

from log_parser_tpu.analysis import redos, subsumption
from log_parser_tpu.analysis.rules import Finding
from log_parser_tpu.analysis.tiers import (
    HOST,
    SKIPPED,
    TierPrediction,
    classify_regex,
)
from log_parser_tpu.models.pattern import PatternSet
from log_parser_tpu.patterns.loader import VALID_SEVERITIES
from log_parser_tpu.patterns.regex.parser import (
    RegexUnsupportedError,
    parse_java_regex,
)

_MIN_LITERAL_LEN = 4


@dataclasses.dataclass
class LintReport:
    findings: list[Finding]
    tiers: dict[str, dict]  # pattern id -> primary tier prediction json
    stats: dict

    @property
    def gating_findings(self) -> list[Finding]:
        return [f for f in self.findings if f.gating]

    @property
    def gating(self) -> bool:
        return bool(self.gating_findings)

    def counts(self) -> dict:
        out = {"error": 0, "warn": 0, "info": 0}
        for f in self.findings:
            out[f.severity] += 1
        return out

    def summary(self) -> dict:
        """Small envelope for /trace/last and the reload response."""
        return {
            "findings": len(self.findings),
            **self.counts(),
            "gating": self.gating,
        }

    def to_json(self) -> dict:
        return {
            "findings": [f.to_json() for f in self.findings],
            "tiers": self.tiers,
            "stats": self.stats,
            "summary": self.summary(),
        }


def _set_label(pattern_set: PatternSet, index: int) -> str:
    meta = pattern_set.metadata
    if meta is not None and meta.library_id:
        return meta.library_id
    return f"<set {index}>"


def lint_pattern_sets(
    sets: list[PatternSet],
    *,
    check_subsumption: bool = True,
    max_product_states: int = subsumption.DEFAULT_MAX_PRODUCT_STATES,
) -> LintReport:
    findings: list[Finding] = []
    tiers: dict[str, dict] = {}

    # ---- schema / metadata --------------------------------------------
    id_first_set: dict[str, str] = {}
    # (pattern_id, set, regex, role) per distinct column key, build order
    column_roles: dict[tuple[str, bool], list[tuple[str, str, str]]] = {}
    primary_of: list[tuple[str, str, str]] = []  # (pattern_id, set, regex)

    for idx, ps in enumerate(sets):
        set_id = _set_label(ps, idx)
        if ps.metadata is None or not ps.metadata.library_id:
            findings.append(
                Finding(
                    rule="schema-no-library-id",
                    detail="pattern set has no metadata.library_id",
                    set_id=set_id,
                )
            )
        for pat in ps.patterns or []:
            pid = pat.id or ""
            if not pid.strip():
                findings.append(
                    Finding(
                        rule="schema-empty-id",
                        detail="pattern has a blank id",
                        set_id=set_id,
                    )
                )
            elif pid in id_first_set:
                findings.append(
                    Finding(
                        rule="schema-duplicate-id",
                        detail=f"id also defined in {id_first_set[pid]}",
                        pattern_id=pid,
                        set_id=set_id,
                    )
                )
            else:
                id_first_set[pid] = set_id
            severity = pat.severity or ""
            if severity and severity.upper() not in VALID_SEVERITIES:
                findings.append(
                    Finding(
                        rule="schema-unknown-severity",
                        detail=f"severity {severity!r} is not one of "
                        f"{sorted(VALID_SEVERITIES)}",
                        pattern_id=pid,
                        set_id=set_id,
                    )
                )
            if pat.primary_pattern is None:
                findings.append(
                    Finding(
                        rule="schema-missing-primary",
                        detail="no primary_pattern",
                        pattern_id=pid,
                        set_id=set_id,
                    )
                )
                continue
            regex = pat.primary_pattern.regex or ""
            if not regex:
                findings.append(
                    Finding(
                        rule="schema-empty-regex",
                        detail="primary_pattern.regex is empty",
                        pattern_id=pid,
                        set_id=set_id,
                    )
                )
                continue
            confidence = pat.primary_pattern.confidence
            if not 0.0 < confidence <= 1.0:
                findings.append(
                    Finding(
                        rule="schema-bad-confidence",
                        detail=f"confidence {confidence!r} outside (0, 1]",
                        pattern_id=pid,
                        set_id=set_id,
                    )
                )
            primary_of.append((pid, set_id, regex))
            column_roles.setdefault((regex, False), []).append(
                (pid, set_id, "primary")
            )
            for sec in pat.secondary_patterns or []:
                if sec.regex:
                    column_roles.setdefault((sec.regex, False), []).append(
                        (pid, set_id, "secondary")
                    )
            for seq in pat.sequence_patterns or []:
                for ev in seq.events or []:
                    if ev.regex:
                        column_roles.setdefault((ev.regex, False), []).append(
                            (pid, set_id, "sequence")
                        )

    # ---- tier classification + ReDoS + prefilter, per distinct column --
    predictions: dict[tuple[str, bool], TierPrediction] = {}
    for (regex, ci), roles in column_roles.items():
        pred = classify_regex(regex, ci)
        predictions[(regex, ci)] = pred
        pid, set_id, role = roles[0]
        where = f"{role} regex" + (
            f" (+{len(roles) - 1} more use(s))" if len(roles) > 1 else ""
        )
        if pred.tier == SKIPPED:
            findings.append(
                Finding(
                    rule="schema-invalid-regex",
                    detail=f"{where}: {pred.detail}",
                    pattern_id=pid,
                    set_id=set_id,
                    regex=regex,
                    code=pred.reason_code,
                )
            )
            continue
        if pred.tier == HOST:
            findings.append(
                Finding(
                    rule="tier-host-fallback",
                    detail=f"{where}: {pred.detail}",
                    pattern_id=pid,
                    set_id=set_id,
                    regex=regex,
                    code=pred.reason_code,
                )
            )
            if pred.literal_count == 0:
                findings.append(
                    Finding(
                        rule="prefilter-none-host",
                        detail=f"{where}: no required literal extractable "
                        "even with lenient widening",
                        pattern_id=pid,
                        set_id=set_id,
                        regex=regex,
                    )
                )
        else:
            if pred.literal_count == 0:
                findings.append(
                    Finding(
                        rule="prefilter-none-device",
                        detail=f"{where}: no required literal extractable",
                        pattern_id=pid,
                        set_id=set_id,
                        regex=regex,
                    )
                )
        if 0 < pred.max_literal_len < _MIN_LITERAL_LEN:
            findings.append(
                Finding(
                    rule="prefilter-short-literal",
                    detail=f"{where}: longest required literal is "
                    f"{pred.max_literal_len} byte(s)",
                    pattern_id=pid,
                    set_id=set_id,
                    regex=regex,
                )
            )
        findings.extend(
            _redos_findings(regex, ci, pid, set_id, where)
        )

    for pid, _set_id, regex in primary_of:
        pred = predictions.get((regex, False))
        if pred is not None and pid not in tiers:
            tiers[pid] = pred.to_json()

    # ---- cross-pattern subsumption over primary DFAs -------------------
    stats: dict = {
        "patterns": sum(len(ps.patterns or []) for ps in sets),
        "sets": len(sets),
        "columns": len(column_roles),
    }
    if check_subsumption:
        findings.extend(
            _subsumption_findings(
                primary_of, predictions, stats, max_product_states
            )
        )
    return LintReport(findings=findings, tiers=tiers, stats=stats)


def _redos_findings(
    regex: str, ci: bool, pid: str, set_id: str, where: str
) -> list[Finding]:
    """ReDoS scan on the strict AST, or the lenient (widened) AST for
    host-only shapes — widening only ever ADDS repeats, so a clean
    lenient scan is clean for the true pattern too."""
    node = None
    try:
        node = parse_java_regex(regex, ci)
    except RegexUnsupportedError:
        try:
            node = parse_java_regex(regex, ci, lenient=True)
        except (RegexUnsupportedError, ValueError):
            return [
                Finding(
                    rule="redos-unanalyzable",
                    detail=f"{where}: outside the analyzable dialect",
                    pattern_id=pid,
                    set_id=set_id,
                    regex=regex,
                )
            ]
    return [
        Finding(
            rule=rule,
            detail=f"{where}: {detail}",
            pattern_id=pid,
            set_id=set_id,
            regex=regex,
        )
        for rule, detail in redos.scan_redos(node)
    ]


def _subsumption_findings(
    primary_of: list[tuple[str, str, str]],
    predictions: dict[tuple[str, bool], TierPrediction],
    stats: dict,
    max_product_states: int,
) -> list[Finding]:
    findings: list[Finding] = []
    # identical primary regex on two pattern ids: trivially equal
    # languages, no product BFS needed (the bank interns one column)
    by_regex: dict[str, tuple[str, str]] = {}
    entries: list[tuple[str, subsumption.CompiledDfa]] = []
    no_dfa = 0
    for pid, set_id, regex in primary_of:
        prior = by_regex.get(regex)
        if prior is not None:
            findings.append(
                Finding(
                    rule="subsume-duplicate",
                    detail=f"primary regex is identical to pattern "
                    f"{prior[0]!r} in {prior[1]}",
                    pattern_id=pid,
                    set_id=set_id,
                    regex=regex,
                )
            )
            continue
        by_regex[regex] = (pid, set_id)
        pred = predictions.get((regex, False))
        if pred is None or pred.dfa is None:
            if pred is not None and pred.tier not in (HOST, SKIPPED):
                no_dfa += 1  # device column whose DFA declined (rare)
            continue
        entries.append((pid, pred.dfa))
    relations, undecided = subsumption.compare_all(
        entries, max_product_states
    )
    set_of = {pid: set_id for pid, set_id, _ in primary_of}
    for pid_a, pid_b, rel in relations:
        if rel == subsumption.EQUAL:
            findings.append(
                Finding(
                    rule="subsume-duplicate",
                    detail=f"primary accepts exactly the same lines as "
                    f"pattern {pid_b!r} in {set_of.get(pid_b, '?')}",
                    pattern_id=pid_a,
                    set_id=set_of.get(pid_a, ""),
                )
            )
        else:
            narrow, broad = (
                (pid_a, pid_b) if rel == subsumption.A_IN_B else (pid_b, pid_a)
            )
            findings.append(
                Finding(
                    rule="subsume-shadowed",
                    detail=f"every line this primary matches also fires "
                    f"pattern {broad!r} in {set_of.get(broad, '?')}",
                    pattern_id=narrow,
                    set_id=set_of.get(narrow, ""),
                )
            )
    stats["subsumptionCompared"] = len(entries)
    stats["subsumptionUndecided"] = undecided
    stats["subsumptionNoDfa"] = no_dfa
    return findings
