"""Device-compilability classifier: predict a regex's matcher tier.

Predicts which tier a column lands in *without building an engine*, by
running the SAME compile entry points :class:`PatternBank._intern_column`
runs (patterns/bank.py) and catching the same typed exceptions:

==========  =========================================================
tier        meaning
==========  =========================================================
shiftor     fixed byte-class sequences — bit-parallel Shift-Or capable
dfa         compiles to a packed DFA (dense / union multi-DFA tiers)
host        automaton path declined — host ``re`` fallback column
skipped     even the host translation fails — pattern is dropped
==========  =========================================================

``reason_code`` cites :mod:`log_parser_tpu.patterns.regex.reasons` via
the exception's own ``code`` attribute, so the prediction and an actual
build failure can never disagree on the reason — they are the same
object. ``bit_capable`` is the orthogonal capability bit for the
gather-free bit-parallel engine (ops/match.py admits bit programs per
platform/word budget; capability here is the platform-independent part:
the program compiles and fits the column position cap).

The classifier is deliberately *capability*-level: MatcherBanks picks
the executed tier per bank size and platform (e.g. Shift-Or only beyond
``shiftor_min_columns``), but artifacts are what the build produces and
what the parity test (tests/test_patlint.py) pins column-for-column.
"""

from __future__ import annotations

import dataclasses
import re

from log_parser_tpu.golden.javacompat import compile_java_regex
from log_parser_tpu.patterns.regex import reasons
from log_parser_tpu.patterns.regex.bitprog import (
    BitUnsupportedError,
    compile_bitprog,
)
from log_parser_tpu.patterns.regex.cache import compile_regex_to_dfa_cached
from log_parser_tpu.patterns.regex.dfa import CompiledDfa, DfaLimitError
from log_parser_tpu.patterns.regex.literals import (
    exact_sequences,
    extract_literals,
)
from log_parser_tpu.patterns.regex.parser import (
    RegexUnsupportedError,
    parse_java_regex,
)

# mirror of ops/match.py MatcherBanks.BITGLUSH_MAX_COLUMN_POSITIONS — the
# platform-independent per-column cap (asserted equal in test_patlint.py)
BIT_MAX_COLUMN_POSITIONS = 512

SHIFTOR, DFA, HOST, SKIPPED = "shiftor", "dfa", "host", "skipped"


@dataclasses.dataclass
class TierPrediction:
    regex: str
    case_insensitive: bool
    tier: str  # shiftor | dfa | host | skipped
    reason_code: str  # reasons.* — SUPPORTED unless host/skipped
    detail: str = ""
    bit_capable: bool = False
    bit_reason_code: str = ""  # reasons.* when not bit_capable
    literal_count: int = 0  # extractable required literals (0 = none)
    max_literal_len: int = 0  # longest required literal in bytes
    dfa: CompiledDfa | None = None  # kept for subsumption reuse

    def to_json(self) -> dict:
        out = {
            "regex": self.regex,
            "tier": self.tier,
            "reason": self.reason_code,
            "bitCapable": self.bit_capable,
            "literals": self.literal_count,
        }
        if self.detail:
            out["detail"] = self.detail
        if self.bit_reason_code:
            out["bitReason"] = self.bit_reason_code
        return out


def classify_regex(regex: str, case_insensitive: bool = False) -> TierPrediction:
    """Predict the matcher tier of one column regex.

    Runs host compile → parse → exact_sequences → extract_literals →
    DFA → bit program, in the bank's order, reusing the bank's own disk
    cache for the DFA so a lint pass warms the subsequent build.
    """
    try:
        compile_java_regex(regex, case_insensitive)
    except (re.error, ValueError) as exc:
        return TierPrediction(
            regex=regex,
            case_insensitive=case_insensitive,
            tier=SKIPPED,
            reason_code=reasons.RX_SYNTAX,
            detail=str(exc),
        )

    try:
        node = parse_java_regex(regex, case_insensitive)
    except RegexUnsupportedError as exc:
        literal_count, max_len = _lenient_literals(regex, case_insensitive)
        return TierPrediction(
            regex=regex,
            case_insensitive=case_insensitive,
            tier=HOST,
            reason_code=exc.code,
            detail=str(exc),
            literal_count=literal_count,
            max_literal_len=max_len,
        )

    exact = exact_sequences(node)
    literals = extract_literals(node)
    literal_count = len(literals) if literals else 0
    max_len = max((len(l.text) for l in literals), default=0) if literals else 0

    try:
        dfa = compile_regex_to_dfa_cached(regex, case_insensitive, node=node)
    except (RegexUnsupportedError, DfaLimitError) as exc:
        if exact is None:
            return TierPrediction(
                regex=regex,
                case_insensitive=case_insensitive,
                tier=HOST,
                reason_code=exc.code,
                detail=str(exc),
                literal_count=literal_count,
                max_literal_len=max_len,
            )
        # exact_seqs survive a DFA decline: the column still rides
        # Shift-Or (bank.py keeps exact_seqs; MatcherBanks never needs
        # the DFA for a shiftor column)
        dfa = None

    bit_capable, bit_reason = _bit_capability(node)
    return TierPrediction(
        regex=regex,
        case_insensitive=case_insensitive,
        tier=SHIFTOR if exact is not None else DFA,
        reason_code=reasons.SUPPORTED,
        bit_capable=bit_capable,
        bit_reason_code=bit_reason,
        literal_count=literal_count,
        max_literal_len=max_len,
        dfa=dfa,
    )


def _bit_capability(node) -> tuple[bool, str]:
    try:
        prog = compile_bitprog(node)
    except BitUnsupportedError as exc:
        return False, exc.code
    if prog.n_positions > BIT_MAX_COLUMN_POSITIONS:
        return False, reasons.BIT_TOO_WIDE
    return True, ""


def _lenient_literals(regex: str, case_insensitive: bool) -> tuple[int, int]:
    """Literal prefilter stats for a host-only column, via the same
    lenient language-widening parse the bank attempts (bank.py)."""
    try:
        literals = extract_literals(
            parse_java_regex(regex, case_insensitive, lenient=True)
        )
    except (RegexUnsupportedError, ValueError):
        return 0, 0
    if not literals:
        return 0, 0
    return len(literals), max(len(l.text) for l in literals)
