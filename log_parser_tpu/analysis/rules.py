"""Lint rule registry + finding model.

Every finding cites a rule id from :data:`RULES`; the rule fixes the
severity. ``error`` and ``warn`` findings *gate* (CLI exits nonzero,
``--lint-patterns=block`` rejects the reload); ``info`` findings are
advisory. The builtin bank must be clean of gating findings — hygiene
check 10 enforces that, and the doc-drift check pins every rule id to a
row in docs/PATTERNS.md.
"""

from __future__ import annotations

import dataclasses

ERROR, WARN, INFO = "error", "warn", "info"

# rule id -> (severity, description)
RULES: dict[str, tuple[str, str]] = {
    # ---- YAML schema / metadata ----------------------------------------
    "schema-duplicate-id": (
        ERROR,
        "the same pattern id appears more than once across the library "
        "(duplicates silently share one frequency counter)",
    ),
    "schema-unknown-severity": (
        ERROR,
        "severity is not a scoring-table value — it would silently "
        "score at 1.0x, below INFO",
    ),
    "schema-invalid-regex": (
        ERROR,
        "the regex does not compile even on the host path — the "
        "pattern can never match and is skipped at build time",
    ),
    "schema-empty-regex": (
        ERROR,
        "an empty regex matches every line",
    ),
    "schema-bad-confidence": (
        WARN,
        "primary confidence outside (0, 1] distorts every downstream "
        "score factor",
    ),
    "schema-missing-primary": (
        INFO,
        "no primary_pattern: the pattern is carried but never matches",
    ),
    "schema-empty-id": (
        INFO,
        "blank pattern id: the pattern is excluded from frequency "
        "tracking",
    ),
    "schema-no-library-id": (
        INFO,
        "pattern set has no metadata.library_id",
    ),
    # ---- ReDoS on the host fallback path -------------------------------
    "redos-nested-quantifier": (
        ERROR,
        "an unbounded repeat directly pumps another variable repeat "
        "(e.g. (a+)+) — exponential backtracking on the host re path",
    ),
    "redos-overlapping-alternation": (
        ERROR,
        "alternation with overlapping branches under an unbounded "
        "repeat (e.g. (a|ab)*) — exponential backtracking on the host "
        "re path",
    ),
    "redos-adjacent-overlap": (
        WARN,
        "two adjacent unbounded repeats over overlapping byte sets "
        "(e.g. .*.*) — superlinear backtracking on the host re path",
    ),
    "redos-unanalyzable": (
        INFO,
        "regex compiles on the host but is outside the analyzable "
        "dialect even with lenient widening — ReDoS rules not applied",
    ),
    # ---- device-compilability tiers ------------------------------------
    "tier-host-fallback": (
        INFO,
        "regex lands on the host re tier; the reason code names the "
        "construct that declined the automaton path",
    ),
    # ---- prefilter quality ---------------------------------------------
    "prefilter-none-host": (
        WARN,
        "host-tier regex with NO extractable required literal: every "
        "request pays a full host-re scan over every line",
    ),
    "prefilter-none-device": (
        INFO,
        "device-tier regex with no extractable literal cannot join the "
        "Aho-Corasick prefilter on wide banks",
    ),
    "prefilter-short-literal": (
        INFO,
        "best required literal is under 4 bytes — weak prefilter "
        "selectivity",
    ),
    # ---- cross-pattern subsumption -------------------------------------
    "subsume-duplicate": (
        ERROR,
        "two patterns' primary regexes accept exactly the same language "
        "(product-DFA equality) — one is redundant",
    ),
    "subsume-shadowed": (
        INFO,
        "one primary's language strictly contains another's: every line "
        "the narrow pattern matches also fires the broad one",
    ),
}

VALID_RULE_SEVERITIES = frozenset({ERROR, WARN, INFO})
GATING_SEVERITIES = frozenset({ERROR, WARN})


@dataclasses.dataclass
class Finding:
    """One lint finding; ``severity`` comes from the rule registry."""

    rule: str
    detail: str
    pattern_id: str = ""
    set_id: str = ""
    regex: str = ""
    code: str = ""  # reason code (patterns/regex/reasons.py) when relevant

    @property
    def severity(self) -> str:
        return RULES[self.rule][0]

    @property
    def gating(self) -> bool:
        return self.severity in GATING_SEVERITIES

    def to_json(self) -> dict:
        out = {
            "rule": self.rule,
            "severity": self.severity,
            "detail": self.detail,
        }
        for key in ("pattern_id", "set_id", "regex", "code"):
            value = getattr(self, key)
            if value:
                out[key] = value
        return out
