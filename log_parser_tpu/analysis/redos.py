"""Static ReDoS detection on the regex AST.

The device tiers are immune to catastrophic backtracking (a DFA or bit
program is linear per byte by construction), but the *host* paths are
not: the golden engine, canary validation, shadow verification, and the
quarantine re-serve all run Python ``re`` — a backtracking engine — over
every pattern in the library. One hostile-or-unlucky pattern shape plus
one adversarial log line is a denial of service on every one of those
paths, so pattern shapes with superlinear backtracking are rejected at
lint time, before the library reaches the reload ladder.

Three rules, all standard static ReDoS heuristics on the parsed AST
(no NFA simulation needed):

- ``redos-nested-quantifier`` — an unbounded repeat whose body is, up to
  nullable context, another variable repeat (``(a+)+``, ``(x*y?)*``):
  a run of the inner atom can be split between the loops in
  exponentially many ways.
- ``redos-overlapping-alternation`` — an alternation under an unbounded
  repeat where two branches can start with the same byte AND one branch
  can be a prefix of a string the other matches (``(a|ab)*``): each
  iteration has two viable parses.
- ``redos-adjacent-overlap`` — two adjacent unbounded repeats over
  overlapping byte sets (``.*.*``): O(n²) split points, flagged at warn
  because it is superlinear but not exponential.

Heuristics over-approximate reachability (an ambiguous subexpression
that no suffix can ever force to backtrack is still flagged) — that is
the right trade for a lint gate: the fix is a one-line rewrite.
"""

from __future__ import annotations

from log_parser_tpu.patterns.regex.parser import (
    Alt,
    Assertion,
    Cat,
    Empty,
    Lit,
    Node,
    Rep,
)


def _nullable(node: Node) -> bool:
    if isinstance(node, (Empty, Assertion)):
        return True
    if isinstance(node, Lit):
        return False
    if isinstance(node, Cat):
        return all(_nullable(p) for p in node.parts)
    if isinstance(node, Alt):
        return any(_nullable(o) for o in node.options)
    if isinstance(node, Rep):
        return node.lo == 0 or _nullable(node.child)
    return False


def _first_bytes(node: Node) -> frozenset[int]:
    """Over-approximate set of bytes a match of ``node`` can start with."""
    if isinstance(node, Lit):
        return node.byteset
    if isinstance(node, (Empty, Assertion)):
        return frozenset()
    if isinstance(node, Alt):
        out: frozenset[int] = frozenset()
        for opt in node.options:
            out |= _first_bytes(opt)
        return out
    if isinstance(node, Rep):
        return _first_bytes(node.child) if node.hi != 0 else frozenset()
    if isinstance(node, Cat):
        out = frozenset()
        for part in node.parts:
            out |= _first_bytes(part)
            if not _nullable(part):
                break
        return out
    return frozenset()


def _variable(rep: Rep) -> bool:
    """The repeat can consume a *variable* number of copies."""
    return rep.hi is None or rep.hi > rep.lo


def _pumpable_inner_rep(node: Node) -> Rep | None:
    """A variable repeat reachable from ``node`` through nullable context
    only — i.e. strings of the inner atom reach the outer loop with no
    mandatory separator byte pinning the split points."""
    if isinstance(node, Rep):
        if _variable(node) and not _nullable(node.child):
            return node
        return _pumpable_inner_rep(node.child)
    if isinstance(node, Alt):
        for opt in node.options:
            found = _pumpable_inner_rep(opt)
            if found is not None:
                return found
        return None
    if isinstance(node, Cat):
        for i, part in enumerate(node.parts):
            others = node.parts[:i] + node.parts[i + 1 :]
            if all(_nullable(o) for o in others):
                found = _pumpable_inner_rep(part)
                if found is not None:
                    return found
        return None
    return None


def _overlapping_alt(node: Node) -> tuple[Node, Node] | None:
    """Two branches of an alternation under ``node`` where one branch's
    full language can prefix the other's (approximated: first bytes
    intersect and the shorter branch's language is not forced apart by
    its own next byte — we settle for the first-byte intersection plus
    both branches non-nullable, which captures (a|ab)* and (a|a)* while
    leaving disjoint-first alternations like (ERROR|FATAL) alone)."""
    if isinstance(node, Alt):
        opts = node.options
        for i in range(len(opts)):
            for j in range(i + 1, len(opts)):
                a, b = opts[i], opts[j]
                if _nullable(a) or _nullable(b):
                    continue
                if _first_bytes(a) & _first_bytes(b):
                    return a, b
        for opt in opts:
            found = _overlapping_alt(opt)
            if found is not None:
                return found
        return None
    if isinstance(node, Cat):
        for part in node.parts:
            found = _overlapping_alt(part)
            if found is not None:
                return found
        return None
    if isinstance(node, Rep):
        return _overlapping_alt(node.child)
    return None


def _unbounded_first(node: Node) -> frozenset[int] | None:
    """If ``node`` is (or trivially wraps) an unbounded repeat, the byte
    set its loop consumes; None otherwise."""
    if isinstance(node, Rep) and node.hi is None:
        return _first_bytes(node.child)
    return None


def scan_redos(node: Node) -> list[tuple[str, str]]:
    """Walk the AST; return ``(rule_id, detail)`` tuples."""
    findings: list[tuple[str, str]] = []
    seen_rules: set[str] = set()

    def add(rule: str, detail: str) -> None:
        if rule not in seen_rules:  # one finding per rule per regex
            seen_rules.add(rule)
            findings.append((rule, detail))

    def walk(n: Node) -> None:
        if isinstance(n, Rep):
            if n.hi is None or n.hi > 1:
                inner = _pumpable_inner_rep(n.child)
                if inner is not None:
                    add(
                        "redos-nested-quantifier",
                        "unbounded repeat pumps an inner variable repeat "
                        "through nullable-only context",
                    )
                if n.hi is None:
                    overlap = _overlapping_alt(n.child)
                    if overlap is not None:
                        add(
                            "redos-overlapping-alternation",
                            "alternation branches with overlapping first "
                            "bytes under an unbounded repeat",
                        )
            walk(n.child)
            return
        if isinstance(n, Cat):
            prev_loop: frozenset[int] | None = None
            for part in n.parts:
                if isinstance(part, (Assertion, Empty)):
                    continue  # zero-width: does not separate the loops
                loop = _unbounded_first(part)
                if (
                    prev_loop is not None
                    and loop is not None
                    and prev_loop & loop
                ):
                    add(
                        "redos-adjacent-overlap",
                        "adjacent unbounded repeats over overlapping "
                        "byte sets",
                    )
                prev_loop = loop
                walk(part)
            return
        if isinstance(n, Alt):
            for opt in n.options:
                walk(opt)
            return

    walk(node)
    return findings
