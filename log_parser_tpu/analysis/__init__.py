"""Static analysis of pattern libraries and runtime invariants.

Two consumers:

- :mod:`tools.pattern_lint` / the reload ladder's pre-canary lint stage
  (:mod:`log_parser_tpu.runtime.reload`) call
  :func:`log_parser_tpu.analysis.lint.lint_pattern_sets` to vet a pattern
  library *before* any engine is built — ReDoS shapes on the host
  fallback path, tier prediction with the build's own reason codes,
  cross-pattern subsumption, prefilter quality, schema hygiene;
- :mod:`tools.conlint` (hygiene check 10) enforces the runtime's
  concurrency invariants on the source tree itself.
"""

from log_parser_tpu.analysis.lint import LintReport, lint_pattern_sets
from log_parser_tpu.analysis.rules import Finding, RULES
from log_parser_tpu.analysis.tiers import TierPrediction, classify_regex

__all__ = [
    "Finding",
    "LintReport",
    "RULES",
    "TierPrediction",
    "classify_regex",
    "lint_pattern_sets",
]
