"""Cross-pattern subsumption via product-DFA language comparison.

Two patterns whose primary regexes accept the *same* language are a
copy-paste bug: both fire on every matching line, silently sharing (or
splitting) frequency and double-reporting events. A strictly contained
language is legitimate layering (a specific pattern refined by a broad
one) but worth surfacing — the broad pattern fires on every line the
specific one does.

Comparison is exact over the compiled DFAs (patterns/regex/dfa.py): a
line matches iff ``accept_end`` holds at end-of-input, so language
comparison is a BFS over the product automaton tracking two one-way
difference flags. One traversal answers both directions:

- neither ``a\\b`` nor ``b\\a`` reachable → equal languages;
- only one reachable → strict containment;
- both → incomparable (the common case, reached fast).

Pairs whose product exceeds ``max_product_states`` are reported as
*undecided*, never silently dropped — the caller surfaces the count.
DFAs here are containment matchers (unanchored prefix baked in), so
"language" means "set of whole lines containing a match", exactly the
engine's per-line semantics.
"""

from __future__ import annotations

from collections import deque

from log_parser_tpu.patterns.regex.dfa import CompiledDfa

DEFAULT_MAX_PRODUCT_STATES = 20_000

EQUAL = "equal"
A_IN_B = "a-in-b"  # L(a) ⊊ L(b)
B_IN_A = "b-in-a"
INCOMPARABLE = "incomparable"
UNDECIDED = "undecided"  # product-state budget exceeded
DIFFERENT = "different"  # multi-DFA output bisimulation found a mismatch


def _product_classes(a: CompiledDfa, b: CompiledDfa) -> list[tuple[int, int]]:
    """Distinct (byte_class_a, byte_class_b) pairs realized by some byte —
    the product automaton's alphabet (usually far under 256)."""
    pairs = {
        (int(a.byte_class[byte]), int(b.byte_class[byte]))
        for byte in range(256)
    }
    return sorted(pairs)


def compare_dfas(
    a: CompiledDfa,
    b: CompiledDfa,
    max_product_states: int = DEFAULT_MAX_PRODUCT_STATES,
) -> str:
    """Classify the relation between L(a) and L(b); see module docstring."""
    classes = _product_classes(a, b)
    start = (int(a.start), int(b.start))
    seen = {start}
    queue = deque([start])
    a_minus_b = b_minus_a = False
    while queue:
        sa, sb = queue.popleft()
        acc_a = bool(a.accept_end[sa])
        acc_b = bool(b.accept_end[sb])
        if acc_a and not acc_b:
            a_minus_b = True
        if acc_b and not acc_a:
            b_minus_a = True
        if a_minus_b and b_minus_a:
            return INCOMPARABLE
        for ca, cb in classes:
            nxt = (int(a.trans[sa, ca]), int(b.trans[sb, cb]))
            if nxt not in seen:
                if len(seen) >= max_product_states:
                    return UNDECIDED
                seen.add(nxt)
                queue.append(nxt)
    if not a_minus_b and not b_minus_a:
        return EQUAL
    return A_IN_B if not a_minus_b else B_IN_A


def compare_multi_dfas(
    a,
    b,
    max_product_states: int = DEFAULT_MAX_PRODUCT_STATES,
) -> str:
    """Exact output bisimulation between two union multi-DFAs
    (patterns/regex/multidfa.py ``CompiledMultiDfa``) over the same
    pattern list: EQUAL iff every reachable product state agrees on the
    end-of-input ``accept_words`` AND on the ``out2`` row read for every
    outgoing byte (the row index depends on the byte's word-ness, which
    both byte-class partitions refine, so the pair agrees per byte).

    Pointwise output agreement is exactly the congruence partition
    refinement preserves, so this is the differential pin for the
    minimizer (tests/test_dfa_minimize.py): a correct minimization always
    passes, and any merge of observably distinct states is caught at the
    first reachable witness. DIFFERENT on disagreement, UNDECIDED past
    the product budget."""
    import numpy as np

    if a.n_patterns != b.n_patterns or a.n_words != b.n_words:
        return DIFFERENT
    # product alphabet: distinct (class_a, class_b) pairs + the shared
    # word-ness of the bytes realizing each (both partitions refine
    # WORD_BYTES membership, so word-ness is a function of the pair)
    pairs: dict[tuple[int, int], int] = {}
    for byte in range(256):
        key = (int(a.byte_class[byte]), int(b.byte_class[byte]))
        pairs.setdefault(key, int(a.cls_is_word[key[0]]))
    start = (int(a.start), int(b.start))
    seen = {start}
    queue = deque([start])
    while queue:
        sa, sb = queue.popleft()
        if not np.array_equal(a.accept_words[sa], b.accept_words[sb]):
            return DIFFERENT
        for (ca, cb), rw in pairs.items():
            if not np.array_equal(a.out2[sa * 2 + rw], b.out2[sb * 2 + rw]):
                return DIFFERENT
            nxt = (int(a.trans[sa, ca]), int(b.trans[sb, cb]))
            if nxt not in seen:
                if len(seen) >= max_product_states:
                    return UNDECIDED
                seen.add(nxt)
                queue.append(nxt)
    return EQUAL


def compare_all(
    entries: list[tuple[str, CompiledDfa]],
    max_product_states: int = DEFAULT_MAX_PRODUCT_STATES,
) -> tuple[list[tuple[str, str, str]], int]:
    """Pairwise comparison of ``(label, dfa)`` entries.

    Returns ``(relations, undecided_count)`` where ``relations`` holds
    ``(label_a, label_b, relation)`` for every EQUAL/containment pair.
    Identical-regex entries should be deduplicated by the caller first
    (the bank interns them into one column anyway).
    """
    out: list[tuple[str, str, str]] = []
    undecided = 0
    for i in range(len(entries)):
        label_a, dfa_a = entries[i]
        for j in range(i + 1, len(entries)):
            label_b, dfa_b = entries[j]
            rel = compare_dfas(dfa_a, dfa_b, max_product_states)
            if rel == UNDECIDED:
                undecided += 1
            elif rel != INCOMPARABLE:
                out.append((label_a, label_b, rel))
    return out, undecided
