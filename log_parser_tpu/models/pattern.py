"""Pattern-library models — the YAML schema of the reference's pattern files.

Surface reconstructed from call sites in the reference (SURVEY.md §2.3):
``Pattern`` accessors at ScoringService.java:64-69,85 and
AnalysisService.java:62,68,75,104,201; the YAML shape at
docs/SCORING_ALGORITHM.md:29-33 (``primary_pattern: {regex, confidence}``)
plus ``secondary_patterns``, ``sequence_patterns``, ``context_extraction``,
and remediation info (PatternService.java:25-26).

Unlike the reference — which mutates shared singleton pattern objects with
``setCompiledRegex`` on every request (AnalysisService.java:62-83, a latent
data race, SURVEY.md §5.2) — these models carry no compiled-regex slot.
Compilation happens once at load time into an immutable matcher bank
(:mod:`log_parser_tpu.patterns`).
"""

from __future__ import annotations

import dataclasses
from typing import Any

from log_parser_tpu.models._base import Model


@dataclasses.dataclass
class PrimaryPattern(Model):
    """``primary_pattern {regex, confidence}`` — docs/SCORING_ALGORITHM.md:30-33;
    accessors AnalysisService.java:62-65, ScoringService.java:65."""

    regex: str = ""
    confidence: float = 0.0


@dataclasses.dataclass
class SecondaryPattern(Model):
    """``secondary_patterns [{regex, weight, proximity_window}]`` —
    ScoringService.java:172-186,319,330."""

    regex: str = ""
    weight: float = 0.0
    proximity_window: int = 0


@dataclasses.dataclass
class SequenceEvent(Model):
    """One event regex inside a sequence — ScoringService.java:280-281,299-300."""

    regex: str = ""


@dataclasses.dataclass
class SequencePattern(Model):
    """``sequence_patterns [{description, bonus_multiplier, events}]`` —
    ScoringService.java:208-215,232."""

    description: str = ""
    bonus_multiplier: float = 0.0
    events: list[SequenceEvent] | None = None


@dataclasses.dataclass
class ContextExtraction(Model):
    """``context_extraction {lines_before, lines_after, include_stack_trace}``
    — AnalysisService.java:142,148,153 (``include_stack_trace`` is unused in
    the reference, an open TODO at AnalysisService.java:153)."""

    lines_before: int = 0
    lines_after: int = 0
    include_stack_trace: bool = False


@dataclasses.dataclass
class Pattern(Model):
    """One failure pattern — accessors ScoringService.java:64-69,85,
    AnalysisService.java:62,68,75,104,201.

    ``remediation`` is carried opaquely (any YAML value): the parser never
    reads it, but pattern files include remediation info
    (PatternService.java:25-26) and it must survive round-tripping.

    ``generated`` marks provenance: ``True`` means the pattern was
    synthesized by the template miner (mining/synthesize.py), not
    hand-authored. Mined ids get shadow verification forced on in auto
    admission mode (docs/PATTERNS.md "Generated patterns"); scoring is
    identical either way.
    """

    id: str = ""
    name: str = ""
    severity: str = ""
    primary_pattern: PrimaryPattern | None = None
    secondary_patterns: list[SecondaryPattern] | None = None
    sequence_patterns: list[SequencePattern] | None = None
    context_extraction: ContextExtraction | None = None
    remediation: Any = None
    generated: bool = False


@dataclasses.dataclass
class PatternSetMetadata(Model):
    """Pattern-set metadata; ``library_id`` read at AnalysisService.java:175."""

    library_id: str = ""
    name: str = ""
    version: str = ""
    description: str = ""


@dataclasses.dataclass
class PatternSet(Model):
    """One YAML pattern file — AnalysisService.java:57,60; PatternService.java:80."""

    metadata: PatternSetMetadata | None = None
    patterns: list[Pattern] | None = None
