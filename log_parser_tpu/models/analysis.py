"""Analysis-result models — the REST response surface of the reference.

Surface reconstructed from call sites (SURVEY.md §2.3):
``AnalysisResult`` at AnalysisService.java:115-120, ``MatchedEvent`` at
AnalysisService.java:100-107, ``EventContext`` at AnalysisService.java:134-151,
``AnalysisMetadata`` at AnalysisService.java:168-177, ``AnalysisSummary`` at
AnalysisService.java:190-212, ``PatternFrequency`` at
FrequencyTrackingService.java:48-55,74,113,125.

These serialize with camelCase keys (Jackson bean convention for the REST
JSON, e.g. ``lineNumber`` from ``setLineNumber`` at AnalysisService.java:101).
"""

from __future__ import annotations

import dataclasses
import time
from typing import ClassVar

from log_parser_tpu import _clock as pclock
from log_parser_tpu.javamath import java_div
from log_parser_tpu.models._base import Model
from log_parser_tpu.models.pattern import Pattern


@dataclasses.dataclass
class EventContext(Model):
    """Context window around a match — AnalysisService.java:132-156.

    ``lines_before``/``lines_after`` stay ``None`` (not empty lists) when the
    pattern has no ``context_extraction`` rules, matching the reference's
    early return at AnalysisService.java:137-139.
    """

    _camel_output: ClassVar[bool] = True

    matched_line: str | None = None
    lines_before: list[str] | None = None
    lines_after: list[str] | None = None


@dataclasses.dataclass
class MatchedEvent(Model):
    """One scored primary-pattern match — AnalysisService.java:100-109.

    ``line_number`` is 1-based (AnalysisService.java:101); ``matched_pattern``
    embeds the full pattern object (AnalysisService.java:102).
    """

    _camel_output: ClassVar[bool] = True

    line_number: int = 0
    matched_pattern: Pattern | None = None
    context: EventContext | None = None
    score: float = 0.0


@dataclasses.dataclass
class AnalysisMetadata(Model):
    """Result metadata — AnalysisService.java:166-180."""

    _camel_output: ClassVar[bool] = True

    processing_time_ms: int = 0
    total_lines: int = 0
    analyzed_at: str = ""
    patterns_used: list[str] | None = None
    # set (e.g. "distributed-fallback") when the response was served on a
    # degraded path instead of the full mesh; None (omitted from JSON via
    # drop_none) on the normal path — the reference has no such field
    degraded: str | None = None


@dataclasses.dataclass
class AnalysisSummary(Model):
    """Result summary — AnalysisService.java:188-215."""

    _camel_output: ClassVar[bool] = True

    significant_events: int = 0
    highest_severity: str = "NONE"
    severity_distribution: dict[str, int] | None = None


@dataclasses.dataclass
class AnalysisResult(Model):
    """The ``POST /parse`` response body — AnalysisService.java:115-121."""

    _camel_output: ClassVar[bool] = True

    events: list[MatchedEvent] | None = None
    analysis_id: str = ""
    metadata: AnalysisMetadata | None = None
    summary: AnalysisSummary | None = None


class PatternFrequency:
    """Sliding-window match counter for one pattern id.

    The reference's ``PatternFrequency`` lives in the external common-lib jar;
    its behavior is inferred from the call sites
    (FrequencyTrackingService.java:48-55,74,113,125): constructed with a time
    window, ``increment_count()`` records a match, ``get_current_count()``
    returns matches inside the sliding window, ``get_hourly_rate()`` is the
    windowed count normalized to matches/hour, ``reset()`` clears.

    ``clock`` is injectable so the golden reference and the device kernels can
    agree on a deterministic time model in parity tests.
    """

    def __init__(self, window_seconds: float, clock=pclock.mono):
        self.window_seconds = float(window_seconds)
        self._clock = clock
        self._timestamps: list[float] = []

    def _prune(self, now: float) -> None:
        cutoff = now - self.window_seconds
        # timestamps are appended in order; drop the expired prefix
        i = 0
        while i < len(self._timestamps) and self._timestamps[i] <= cutoff:
            i += 1
        if i:
            del self._timestamps[:i]

    def increment_count(self) -> None:
        now = self._clock()
        self._prune(now)
        self._timestamps.append(now)

    def increment_count_bulk(self, n: int) -> None:
        """Record ``n`` matches in one call: one clock read, one prune,
        one list extend. A device batch's matches land at one timestamp
        (the per-match loop's stamps differed only by the microseconds
        between appends — never observable through the hours-scale
        window semantics, and identical under the deterministic test
        clocks, which return a fixed value until advanced)."""
        if n <= 0:
            return
        now = self._clock()
        self._prune(now)
        self._timestamps.extend([now] * n)

    def get_current_count(self) -> int:
        self._prune(self._clock())
        return len(self._timestamps)

    def get_hourly_rate(self) -> float:
        """Windowed count normalized to matches per hour.

        Java double semantics on a zero-length window (count/0.0):
        Infinity when matches exist, NaN when the count is 0 — no exception.
        """
        return java_div(self.get_current_count(), self.window_seconds / 3600.0)

    def reset(self) -> None:
        self._timestamps.clear()
