"""Corpus: fused Java-split + device encode with lazy line materialization.

The reference splits the whole log into a String[] up front
(AnalysisService.java:53). For a 1M-line corpus that is a million Python
string objects on the host hot path — so here the native library scans the
UTF-8 blob once, fills the padded uint8 device batch directly, and keeps
only byte offsets. Per-line ``str`` objects are decoded lazily (context
extraction touches a handful of window lines per matched event; host regex
verification touches only flagged lines).

Sequence semantics match ``java_split_lines`` exactly (trailing empty lines
dropped; no separator → the whole input, even empty) — property-tested
against the Python implementation in tests/test_native.py.
"""

from __future__ import annotations

import codecs

import numpy as np

from log_parser_tpu.golden.javacompat import java_split_lines
from log_parser_tpu.native import get_lib
from log_parser_tpu.ops.encode import (
    DEFAULT_MAX_LINE_BYTES,
    DEFAULT_WIDTH_MULTIPLE,
    EncodedLines,
    _pad_rows,
    device_width,
    encode_lines,
)


def normalize_blob(logs: str | None) -> bytes:
    """THE ingest normalization: the one byte-level view of a request's
    logs shared by every identity derived from content — the quarantine
    fingerprint (runtime/quarantine.py) and the line-cache keys
    (runtime/linecache.py). ``errors="replace"`` mirrors the per-line
    device encode, so a line's slice of this blob equals the bytes the
    match cube saw regardless of transport (HTTP / framed shim / gRPC all
    deliver the same ``str``)."""
    return (logs or "").encode("utf-8", errors="replace")


class StreamNormalizer:
    """Chunk-boundary-safe ingest normalization: the streaming analogue of
    :func:`normalize_blob` for byte tails that arrive in arbitrary splits.

    A multi-byte UTF-8 sequence split across two chunks must decode to the
    same characters as the joined blob — a naive per-chunk
    ``chunk.decode("utf-8", errors="replace")`` would replace the dangling
    prefix AND the orphaned continuation bytes, diverging from the blob
    path. ``codecs`` incremental decoding holds the incomplete tail
    sequence in the decoder and is split-invariant for ``errors="replace"``
    (pinned by tests/test_stream.py); only ``flush()`` at end-of-stream
    resolves a truncated trailing sequence, with the same replacement the
    blob path produces for it.
    """

    def __init__(self) -> None:
        self._decoder = codecs.getincrementaldecoder("utf-8")("replace")

    def feed(self, data: bytes) -> str:
        """Decode a chunk, carrying any incomplete trailing UTF-8 sequence
        into the next call. Returns the newly-completed text (may be
        empty while a sequence straddles the boundary)."""
        return self._decoder.decode(data, False)

    def flush(self) -> str:
        """End-of-stream: resolve a held incomplete sequence (truncated
        trailing multi-byte → U+FFFD, same as the blob path) and reset."""
        out = self._decoder.decode(b"", True)
        self._decoder.reset()
        return out


def _split_offsets(flat: np.ndarray) -> tuple[np.ndarray, np.ndarray, int]:
    """Byte-level ``java_split_lines``: separators are ``\\n`` and
    ``\\r\\n`` only (a lone ``\\r`` is content), trailing empty parts
    dropped, no separator → the whole input even when empty. Valid UTF-8
    never embeds 0x0A/0x0D inside a multi-byte sequence, so splitting the
    encoded blob is character-for-character the str split. Returns
    ``(starts, ends, n)`` with ``starts``/``ends`` int64 over ``flat``
    (sized to the raw part count; only ``[:n]`` is meaningful)."""
    seps = np.flatnonzero(flat == 0x0A)
    nparts = len(seps) + 1
    starts = np.empty(nparts, dtype=np.int64)
    starts[0] = 0
    starts[1:] = seps + 1
    ends = np.empty(nparts, dtype=np.int64)
    ends[-1] = len(flat)
    if len(seps):
        # \r\n: the \r belongs to the separator. The byte before a part's
        # start is always \n, so a \r preceding a separator is necessarily
        # this part's own content — no emptiness guard needed beyond sep>0.
        crlf = (seps > 0) & (flat[np.maximum(seps - 1, 0)] == 0x0D)
        ends[:-1] = seps - crlf
    if nparts == 1:
        return starts, ends, 1  # no separator — whole input, even if empty
    nonempty = np.flatnonzero(ends > starts)
    n = int(nonempty[-1]) + 1 if nonempty.size else 0
    return starts, ends, n


def _vectorized_encode(
    flat: np.ndarray,
    starts: np.ndarray,
    ends: np.ndarray,
    n: int,
    max_line_bytes: int,
    pad_to_multiple: int,
    min_rows: int,
) -> EncodedLines:
    """``ops/encode.encode_lines`` bit-for-bit, from byte offsets instead
    of a list[str]: same width/rows geometry, one range-scatter fill, and
    per-line ``needs_host`` via segment reductions — no per-line Python.

    ``needs_host`` parity note: the scalar path checks non-ASCII/NUL over
    the first ``min(len, width)`` bytes only; here the reductions run over
    the FULL line. Equivalent: they differ only when ``len > width``, and
    those lines are flagged ``over_long`` regardless."""
    if n == 0:
        return EncodedLines(
            u8=np.zeros((min_rows, pad_to_multiple), dtype=np.uint8),
            lengths=np.zeros(min_rows, dtype=np.int32),
            needs_host=np.zeros(min_rows, dtype=bool),
            n_lines=0,
        )
    starts = starts[:n]
    ends = ends[:n]
    lengths64 = ends - starts
    lengths = lengths64.astype(np.int32)
    width = device_width(lengths, max_line_bytes, pad_to_multiple)
    rows = _pad_rows(n, min_rows)

    u8 = np.zeros((rows, width), dtype=np.uint8)
    clamped = np.minimum(lengths64, width)
    total = int(clamped.sum())
    if total:
        # one range-scatter: content byte p of the batch lands at output
        # cell dest[p] = row(p)*width + offset(p) and reads src[p] =
        # starts[row(p)] + offset(p). Both decompose into a per-LINE base
        # repeated over the line's byte count plus one shared arange — two
        # np.repeat + two adds, no per-byte row-id arithmetic. Indices stay
        # int32 (halves the memory traffic of these 8-45MB temporaries)
        # unless the blob or the padded batch overflows int32; chunked so a
        # 1M-line corpus doesn't hold GB-scale index temporaries at once.
        out = u8.reshape(-1)
        cs = np.cumsum(clamped)
        cum0 = cs - clamped  # exclusive prefix: content start per line
        idt = (
            np.int64
            if max(len(flat), rows * width) > np.iinfo(np.int32).max
            else np.int32
        )
        dest_base = (np.arange(n, dtype=np.int64) * width - cum0).astype(idt)
        reps = clamped.astype(np.int64)
        no_clamp = total == int(lengths64.sum())
        if no_clamp:
            # no line is truncated, so the content bytes are exactly the
            # blob minus its separators (and the dropped trailing-empty
            # region): ONE boolean compress replaces the per-byte source
            # index construction + gather — ~2× cheaper at 10MB scale
            keep = np.ones(len(flat), dtype=bool)
            seps = np.flatnonzero(flat == 0x0A)
            keep[seps] = False
            sep_pos = seps[seps > 0]
            crlf_r = sep_pos[flat[sep_pos - 1] == 0x0D] - 1
            keep[crlf_r] = False
            keep[int(ends[-1]) :] = False
            content = flat[keep]
        else:
            src_base = (starts - cum0).astype(idt)
        chunk_bytes = 16 << 20
        bounds = np.searchsorted(
            cs, np.arange(chunk_bytes, total + chunk_bytes, chunk_bytes)
        )
        lo = 0
        for hi in np.minimum(bounds + 1, n).tolist():
            if hi <= lo:
                continue
            base = int(cum0[lo])
            t = int(cs[hi - 1]) - base
            pos = np.arange(base, base + t, dtype=idt)
            dest = np.repeat(dest_base[lo:hi], reps[lo:hi])
            dest += pos
            if no_clamp:
                out[dest] = content[base : base + t]
            else:
                src = np.repeat(src_base[lo:hi], reps[lo:hi])
                src += pos
                out[dest] = flat[src]
            lo = hi

    host_flag = np.zeros(rows, dtype=bool)
    if len(flat):
        # per-line max/min over [start, end) in one reduceat each: the even
        # segments are line content, the odd ones separators (discarded).
        # A sentinel separator byte keeps every index < len and makes the
        # empty-segment result (flatx[start] — a separator) harmlessly
        # ASCII/non-NUL; empty lines are masked out anyway.
        flatx = np.concatenate([flat, np.asarray([0x0A], dtype=np.uint8)])
        inds = np.empty(2 * n, dtype=np.int64)
        inds[0::2] = starts
        inds[1::2] = ends
        maxs = np.maximum.reduceat(flatx, inds)[0::2]
        mins = np.minimum.reduceat(flatx, inds)[0::2]
        host_flag[:n] = (lengths64 > 0) & ((maxs >= 0x80) | (mins == 0))

    over_long = np.zeros(rows, dtype=bool)
    over_long[:n] = (lengths > width) | (lengths > max_line_bytes)

    full_lengths = np.zeros(rows, dtype=np.int32)
    full_lengths[:n] = np.minimum(lengths, width)
    return EncodedLines(
        u8=u8,
        lengths=full_lengths,
        needs_host=host_flag | over_long,
        n_lines=n,
    )


class Corpus:
    """Sequence-of-lines view over a log blob + its encoded device batch.

    Supports ``len``, integer indexing, and slicing (returns list[str]) so
    golden helpers (extract_context) accept it in place of list[str].

    Without the native library the fallback is the numpy-vectorized path
    above (split + fill + flags, zero per-line Python) — it keeps the same
    blob/starts/ends backing as the native path, so ``line()`` /
    ``line_key_bytes()`` stay O(1) slices either way. Only lone-surrogate
    input (which cannot strict-encode) drops to the per-line scalar path,
    exactly like the native branch does.
    """

    def __init__(
        self,
        logs: str,
        max_line_bytes: int = DEFAULT_MAX_LINE_BYTES,
        pad_to_multiple: int = DEFAULT_WIDTH_MULTIPLE,
        min_rows: int = 8,
    ):
        lib = get_lib()
        self._lines: list[str] | None = None
        if lib is None:
            try:
                blob = logs.encode("utf-8")
            except UnicodeEncodeError:
                self._scalar_init(logs, max_line_bytes, pad_to_multiple, min_rows)
                return
            self._blob = blob
            flat = np.frombuffer(blob, dtype=np.uint8)
            starts, ends, n = _split_offsets(flat)
            self._starts = starts
            self._ends = ends
            self.n_lines = n
            self.encoded = _vectorized_encode(
                flat, starts, ends, n, max_line_bytes, pad_to_multiple, min_rows
            )
            return

        import ctypes

        try:
            blob = logs.encode("utf-8")
        except UnicodeEncodeError:
            # lone surrogates (json.loads passes "\udXXX" escapes through
            # unpaired) cannot encode — take the pure-Python path, which
            # replaces per line and flags those lines for host re-match so
            # golden's str-level semantics still decide them
            self._scalar_init(logs, max_line_bytes, pad_to_multiple, min_rows)
            return
        self._blob = blob
        # zero-copy view of the bytes object (blob outlives the calls via self)
        bufp = ctypes.cast(
            ctypes.c_char_p(blob if blob else b"\0"),
            ctypes.POINTER(ctypes.c_uint8),
        )

        max_len = ctypes.c_int64(0)
        n = lib.lpn_split_scan(bufp, len(blob), ctypes.byref(max_len))
        self.n_lines = int(n)

        true_lengths = np.zeros(max(1, self.n_lines), dtype=np.int32)
        if self.n_lines:
            lib.lpn_split_lengths(
                bufp, len(blob), self.n_lines,
                true_lengths.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            )
        width = device_width(
            true_lengths[: self.n_lines], max_line_bytes, pad_to_multiple
        )
        rows = _pad_rows(self.n_lines, min_rows)

        u8 = np.zeros((rows, width), dtype=np.uint8)
        lengths = np.zeros(rows, dtype=np.int32)
        needs_host = np.zeros(rows, dtype=np.uint8)
        starts = np.zeros(rows, dtype=np.int64)
        ends = np.zeros(rows, dtype=np.int64)
        lib.lpn_split_fill(
            bufp,
            len(blob),
            self.n_lines,
            u8.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            width,
            lengths.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            needs_host.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            starts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            ends.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            max_line_bytes,
        )
        # the capped-width tail (width < len <= max_line_bytes) re-matches
        # on the host, exactly like non-ASCII lines (the C fill only flags
        # len > max_line_bytes)
        if self.n_lines:
            needs_host[: self.n_lines] |= (
                true_lengths[: self.n_lines] > width
            ).astype(np.uint8)
        self._starts = starts
        self._ends = ends
        self.encoded = EncodedLines(
            u8=u8,
            lengths=lengths,
            needs_host=needs_host.astype(bool),
            n_lines=self.n_lines,
        )

    def _scalar_init(
        self, logs: str, max_line_bytes: int, pad_to_multiple: int, min_rows: int
    ) -> None:
        """The per-line scalar path — only for input that cannot
        strict-encode (lone surrogates): ``line()`` must return the
        ORIGINAL str so golden re-matching sees the surrogate, not its
        replacement bytes."""
        lines = java_split_lines(logs)
        self._lines = lines
        self._blob = None
        self._starts = self._ends = None
        self.n_lines = len(lines)
        self.encoded = encode_lines(
            lines, max_line_bytes, pad_to_multiple, min_rows
        )

    # ------------------------------------------------------------- sequence

    def key_view(self) -> tuple[bytes, np.ndarray, np.ndarray] | None:
        """``(blob, starts, ends)`` backing byte-exact per-line access —
        the vectorized keying fast lane (runtime/linecache.py
        ``dedup_slots``) builds its per-line key material from these
        without materializing a bytes object per line. None on the
        scalar-lines path (lone surrogates), where callers must fall back
        to ``line_key_bytes`` per line."""
        if self._blob is None:
            return None
        return self._blob, self._starts, self._ends

    def __len__(self) -> int:
        return self.n_lines

    def line(self, i: int) -> str:
        if self._lines is not None:
            return self._lines[i]
        if not 0 <= i < self.n_lines:
            raise IndexError(i)
        # errors="replace" is defensive only: the blob encoded from a str,
        # so slices at line boundaries are valid UTF-8 — but a malformed
        # lazy read must never crash a request that already matched
        return self._blob[self._starts[i] : self._ends[i]].decode(
            "utf-8", errors="replace"
        )

    def line_key_bytes(self, i: int) -> bytes:
        """Ingest-normalized bytes of line ``i`` — the line-cache key
        material. Native and vectorized-fallback paths: a slice of the
        already-normalized blob (zero extra passes); scalar surrogate
        path: the same bytes via the per-line encode (``errors="replace"``
        matches :func:`normalize_blob` character-for-character)."""
        if self._lines is not None:
            return self._lines[i].encode("utf-8", errors="replace")
        if not 0 <= i < self.n_lines:
            raise IndexError(i)
        return self._blob[self._starts[i] : self._ends[i]]

    def __getitem__(self, key):
        if isinstance(key, slice):
            lo, hi, step = key.indices(self.n_lines)
            return [self.line(i) for i in range(lo, hi, step)]
        if key < 0:
            key += self.n_lines
        return self.line(key)

    def __iter__(self):
        for i in range(self.n_lines):
            yield self.line(i)

    def materialize(self) -> list[str]:
        """All lines as a list (only for paths that truly need every line)."""
        if self._lines is None:
            self._lines = [self.line(i) for i in range(self.n_lines)]
        return self._lines
