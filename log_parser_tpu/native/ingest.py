"""Corpus: fused Java-split + device encode with lazy line materialization.

The reference splits the whole log into a String[] up front
(AnalysisService.java:53). For a 1M-line corpus that is a million Python
string objects on the host hot path — so here the native library scans the
UTF-8 blob once, fills the padded uint8 device batch directly, and keeps
only byte offsets. Per-line ``str`` objects are decoded lazily (context
extraction touches a handful of window lines per matched event; host regex
verification touches only flagged lines).

Sequence semantics match ``java_split_lines`` exactly (trailing empty lines
dropped; no separator → the whole input, even empty) — property-tested
against the Python implementation in tests/test_native.py.
"""

from __future__ import annotations

import codecs

import numpy as np

from log_parser_tpu.golden.javacompat import java_split_lines
from log_parser_tpu.native import get_lib
from log_parser_tpu.ops.encode import (
    DEFAULT_MAX_LINE_BYTES,
    DEFAULT_WIDTH_MULTIPLE,
    EncodedLines,
    _pad_rows,
    device_width,
    encode_lines,
)


def normalize_blob(logs: str | None) -> bytes:
    """THE ingest normalization: the one byte-level view of a request's
    logs shared by every identity derived from content — the quarantine
    fingerprint (runtime/quarantine.py) and the line-cache keys
    (runtime/linecache.py). ``errors="replace"`` mirrors the per-line
    device encode, so a line's slice of this blob equals the bytes the
    match cube saw regardless of transport (HTTP / framed shim / gRPC all
    deliver the same ``str``)."""
    return (logs or "").encode("utf-8", errors="replace")


class StreamNormalizer:
    """Chunk-boundary-safe ingest normalization: the streaming analogue of
    :func:`normalize_blob` for byte tails that arrive in arbitrary splits.

    A multi-byte UTF-8 sequence split across two chunks must decode to the
    same characters as the joined blob — a naive per-chunk
    ``chunk.decode("utf-8", errors="replace")`` would replace the dangling
    prefix AND the orphaned continuation bytes, diverging from the blob
    path. ``codecs`` incremental decoding holds the incomplete tail
    sequence in the decoder and is split-invariant for ``errors="replace"``
    (pinned by tests/test_stream.py); only ``flush()`` at end-of-stream
    resolves a truncated trailing sequence, with the same replacement the
    blob path produces for it.
    """

    def __init__(self) -> None:
        self._decoder = codecs.getincrementaldecoder("utf-8")("replace")

    def feed(self, data: bytes) -> str:
        """Decode a chunk, carrying any incomplete trailing UTF-8 sequence
        into the next call. Returns the newly-completed text (may be
        empty while a sequence straddles the boundary)."""
        return self._decoder.decode(data, False)

    def flush(self) -> str:
        """End-of-stream: resolve a held incomplete sequence (truncated
        trailing multi-byte → U+FFFD, same as the blob path) and reset."""
        out = self._decoder.decode(b"", True)
        self._decoder.reset()
        return out


class Corpus:
    """Sequence-of-lines view over a log blob + its encoded device batch.

    Supports ``len``, integer indexing, and slicing (returns list[str]) so
    golden helpers (extract_context) accept it in place of list[str].
    """

    def __init__(
        self,
        logs: str,
        max_line_bytes: int = DEFAULT_MAX_LINE_BYTES,
        pad_to_multiple: int = DEFAULT_WIDTH_MULTIPLE,
        min_rows: int = 8,
    ):
        lib = get_lib()
        if lib is None:
            lines = java_split_lines(logs)
            self._lines: list[str] | None = lines
            self._blob = None
            self._starts = self._ends = None
            self.n_lines = len(lines)
            self.encoded = encode_lines(
                lines, max_line_bytes, pad_to_multiple, min_rows
            )
            return

        import ctypes

        self._lines = None
        try:
            blob = logs.encode("utf-8")
        except UnicodeEncodeError:
            # lone surrogates (json.loads passes "\udXXX" escapes through
            # unpaired) cannot encode — take the pure-Python path, which
            # replaces per line and flags those lines for host re-match so
            # golden's str-level semantics still decide them
            lines = java_split_lines(logs)
            self._lines = lines
            self._blob = None
            self._starts = self._ends = None
            self.n_lines = len(lines)
            self.encoded = encode_lines(
                lines, max_line_bytes, pad_to_multiple, min_rows
            )
            return
        self._blob = blob
        # zero-copy view of the bytes object (blob outlives the calls via self)
        bufp = ctypes.cast(
            ctypes.c_char_p(blob if blob else b"\0"),
            ctypes.POINTER(ctypes.c_uint8),
        )

        max_len = ctypes.c_int64(0)
        n = lib.lpn_split_scan(bufp, len(blob), ctypes.byref(max_len))
        self.n_lines = int(n)

        true_lengths = np.zeros(max(1, self.n_lines), dtype=np.int32)
        if self.n_lines:
            lib.lpn_split_lengths(
                bufp, len(blob), self.n_lines,
                true_lengths.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            )
        width = device_width(
            true_lengths[: self.n_lines], max_line_bytes, pad_to_multiple
        )
        rows = _pad_rows(self.n_lines, min_rows)

        u8 = np.zeros((rows, width), dtype=np.uint8)
        lengths = np.zeros(rows, dtype=np.int32)
        needs_host = np.zeros(rows, dtype=np.uint8)
        starts = np.zeros(rows, dtype=np.int64)
        ends = np.zeros(rows, dtype=np.int64)
        lib.lpn_split_fill(
            bufp,
            len(blob),
            self.n_lines,
            u8.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            width,
            lengths.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            needs_host.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            starts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            ends.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            max_line_bytes,
        )
        # the capped-width tail (width < len <= max_line_bytes) re-matches
        # on the host, exactly like non-ASCII lines (the C fill only flags
        # len > max_line_bytes)
        if self.n_lines:
            needs_host[: self.n_lines] |= (
                true_lengths[: self.n_lines] > width
            ).astype(np.uint8)
        self._starts = starts
        self._ends = ends
        self.encoded = EncodedLines(
            u8=u8,
            lengths=lengths,
            needs_host=needs_host.astype(bool),
            n_lines=self.n_lines,
        )

    # ------------------------------------------------------------- sequence

    def __len__(self) -> int:
        return self.n_lines

    def line(self, i: int) -> str:
        if self._lines is not None:
            return self._lines[i]
        if not 0 <= i < self.n_lines:
            raise IndexError(i)
        # errors="replace" is defensive only: the blob encoded from a str,
        # so slices at line boundaries are valid UTF-8 — but a malformed
        # lazy read must never crash a request that already matched
        return self._blob[self._starts[i] : self._ends[i]].decode(
            "utf-8", errors="replace"
        )

    def line_key_bytes(self, i: int) -> bytes:
        """Ingest-normalized bytes of line ``i`` — the line-cache key
        material. Native path: a slice of the already-normalized blob
        (zero extra passes); Python fallback: the same bytes via the
        per-line encode (``errors="replace"`` matches
        :func:`normalize_blob` character-for-character)."""
        if self._lines is not None:
            return self._lines[i].encode("utf-8", errors="replace")
        if not 0 <= i < self.n_lines:
            raise IndexError(i)
        return self._blob[self._starts[i] : self._ends[i]]

    def __getitem__(self, key):
        if isinstance(key, slice):
            lo, hi, step = key.indices(self.n_lines)
            return [self.line(i) for i in range(lo, hi, step)]
        if key < 0:
            key += self.n_lines
        return self.line(key)

    def __iter__(self):
        for i in range(self.n_lines):
            yield self.line(i)

    def materialize(self) -> list[str]:
        """All lines as a list (only for paths that truly need every line)."""
        if self._lines is None:
            self._lines = [self.line(i) for i in range(self.n_lines)]
        return self._lines
