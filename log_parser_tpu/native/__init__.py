"""ctypes bindings for the native runtime library (native/log_parser_native.cpp).

The shared object is compiled on demand with ``g++ -O3`` and cached next to
the source, keyed by source mtime. Every caller must tolerate
``get_lib() is None`` (no toolchain, compile failure) and fall back to the
pure-Python path — the native layer is an accelerator, never a requirement.
"""

from __future__ import annotations

import ctypes
import logging
import os
import re
import subprocess
import threading
from pathlib import Path

log = logging.getLogger(__name__)

_SRC = Path(__file__).resolve().parents[2] / "native" / "log_parser_native.cpp"
_SO = _SRC.parent / "build" / "log_parser_native.so"

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False
# WHY the fallback is running, recorded once at first get_lib() and
# surfaced at GET /trace/last "native" (docs/OPS.md) — a GLIBCXX mismatch
# on this host class used to require PERF.md archaeology to diagnose
_load_error: str | None = None
# the symbol-level diagnosis for the GLIBCXX case (see glibcxx_triage):
# stats() carries it so /trace/last and tools/check_native.py agree
_load_triage: dict | None = None

_GLIBCXX_RE = re.compile(rb"GLIBCXX_(\d+(?:\.\d+)+)")


def _glibcxx_versions(path) -> list[tuple[int, ...]]:
    """Every GLIBCXX_x.y.z version tag embedded in ``path``, sorted.
    Reading .dynstr as raw bytes needs no ELF tooling and matches what
    ``strings … | grep GLIBCXX`` shows an operator."""
    try:
        data = Path(path).read_bytes()
    except OSError:
        return []
    return sorted({
        tuple(int(part) for part in m.group(1).split(b"."))
        for m in _GLIBCXX_RE.finditer(data)
    })


def _fmt_glibcxx(v: tuple[int, ...]) -> str:
    return "GLIBCXX_" + ".".join(str(p) for p in v)


def find_libstdcxx() -> str | None:
    """The libstdc++ this process would dlopen against: the copy already
    mapped in (JAX links it) wins; otherwise scan the usual soname dirs."""
    try:
        with open("/proc/self/maps", encoding="utf-8", errors="replace") as f:
            for line in f:
                path = line.rsplit(None, 1)[-1]
                if "libstdc++" in os.path.basename(path):
                    return path
    except OSError:
        pass
    dirs = [d for d in os.environ.get("LD_LIBRARY_PATH", "").split(os.pathsep)
            if d]
    dirs += [
        "/usr/lib/x86_64-linux-gnu", "/lib/x86_64-linux-gnu",
        "/usr/lib/aarch64-linux-gnu", "/lib/aarch64-linux-gnu",
        "/usr/lib64", "/usr/lib", "/usr/local/lib",
    ]
    for d in dirs:
        p = os.path.join(d, "libstdc++.so.6")
        if os.path.exists(p):
            return p
    return None


def glibcxx_triage(so_path=None) -> dict:
    """Required-vs-provided GLIBCXX symbol versions: which versions the
    prebuilt .so asks for, which the host's libstdc++ actually exports,
    and the gap. This is the whole diagnosis for the classic 'built on a
    newer distro' failure — tools/check_native.py prints it, and a load
    failure records it into stats()."""
    so_path = str(so_path or _SO)
    provider = find_libstdcxx()
    required = _glibcxx_versions(so_path)
    provided = _glibcxx_versions(provider) if provider else []
    missing = [v for v in required if provided and v > max(provided)]
    return {
        "so": so_path,
        "libstdcxx": provider,
        "required": [_fmt_glibcxx(v) for v in required],
        "provided": [_fmt_glibcxx(v) for v in provided],
        "missing": [_fmt_glibcxx(v) for v in missing],
    }


def _compile() -> bool:
    global _load_error
    _SO.parent.mkdir(parents=True, exist_ok=True)
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC",
        str(_SRC), "-o", str(_SO),
    ]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
    except (OSError, subprocess.TimeoutExpired) as e:
        log.warning("native compile failed to launch: %s", e)
        _load_error = f"compile failed to launch: {e}"
        return False
    if proc.returncode != 0:
        log.warning("native compile failed:\n%s", proc.stderr)
        _load_error = f"compile failed: {proc.stderr.strip()[:500]}"
        return False
    return True


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    i8p = ctypes.POINTER(ctypes.c_int8)
    i32p = ctypes.POINTER(ctypes.c_int32)
    i64p = ctypes.POINTER(ctypes.c_int64)
    u8p = ctypes.POINTER(ctypes.c_uint8)

    lib.lpn_split_scan.argtypes = [u8p, ctypes.c_int64, i64p]
    lib.lpn_split_scan.restype = ctypes.c_int64
    lib.lpn_split_fill.argtypes = [
        u8p, ctypes.c_int64, ctypes.c_int64, u8p, ctypes.c_int64,
        i32p, u8p, i64p, i64p, ctypes.c_int64,
    ]
    lib.lpn_split_fill.restype = None
    lib.lpn_split_lengths.argtypes = [u8p, ctypes.c_int64, ctypes.c_int64, i32p]
    lib.lpn_split_lengths.restype = None

    lib.lpn_dfa_build.argtypes = [
        ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
        i64p, i8p, i32p,            # eps CSR
        i64p, i32p, i32p,           # trans CSR
        u8p, ctypes.c_int32, u8p,   # bytesets, n_bytesets, word mask
        ctypes.c_int32, ctypes.c_int32,  # max_states, do_minimize
        i32p, i32p, i32p, i32p,     # out n_states, n_classes, start, err
    ]
    lib.lpn_dfa_build.restype = ctypes.c_void_p
    lib.lpn_dfa_read.argtypes = [ctypes.c_void_p, i32p, i32p, u8p]
    lib.lpn_dfa_read.restype = None
    lib.lpn_dfa_free.argtypes = [ctypes.c_void_p]
    lib.lpn_dfa_free.restype = None

    u32p = ctypes.POINTER(ctypes.c_uint32)
    lib.lpn_multi_dfa_build.argtypes = [
        ctypes.c_int32, ctypes.c_int32,
        i64p, i8p, i32p,            # eps CSR
        i64p, i32p, i32p,           # trans CSR
        u8p, ctypes.c_int32, u8p,   # bytesets, n_bytesets, word mask
        i32p, ctypes.c_int32,       # finals, n_patterns
        ctypes.c_int32, ctypes.c_int32,  # max_states, do_minimize
        i32p, i32p, i32p, i32p, i32p,  # out n_states/n_classes/n_words/start/err
    ]
    lib.lpn_multi_dfa_build.restype = ctypes.c_void_p
    lib.lpn_multi_dfa_read.argtypes = [
        ctypes.c_void_p, i32p, i32p, i32p, u32p, u32p,
    ]
    lib.lpn_multi_dfa_read.restype = None
    lib.lpn_multi_dfa_free.argtypes = [ctypes.c_void_p]
    lib.lpn_multi_dfa_free.restype = None

    lib.lpn_regex_batch_build.argtypes = [
        u8p, i64p, u8p, ctypes.c_int32,      # blob, offs, ci flags, n
        u8p,                                  # word mask
        ctypes.c_int32, ctypes.c_int32,       # max_states, do_minimize
    ]
    lib.lpn_regex_batch_build.restype = ctypes.c_void_p
    lib.lpn_regex_batch_get.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, i32p, i32p, i32p,
    ]
    lib.lpn_regex_batch_get.restype = ctypes.c_int32
    lib.lpn_regex_batch_read.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, i32p, i32p, u8p,
    ]
    lib.lpn_regex_batch_read.restype = None
    lib.lpn_regex_batch_extract_totals.argtypes = [
        ctypes.c_void_p, i64p, i64p, i64p, i64p, i64p,
    ]
    lib.lpn_regex_batch_extract_totals.restype = None
    lib.lpn_regex_batch_extract_all.argtypes = [
        ctypes.c_void_p,
        i8p, i32p, i64p, u8p, u8p,   # lit status/counts/offs/ci/blob
        i8p, i32p, i32p, i32p, u8p,  # seq status/counts/lens/pos_counts/blob
    ]
    lib.lpn_regex_batch_extract_all.restype = None
    lib.lpn_regex_batch_free.argtypes = [ctypes.c_void_p]
    lib.lpn_regex_batch_free.restype = None

    lib.lpn_ac_build.argtypes = [
        u8p, i64p, i32p, ctypes.c_int32, ctypes.c_int32,  # blob, offs, groups, n, n_groups
        i32p, i32p, i32p,                                  # out nodes/classes/words
    ]
    lib.lpn_ac_build.restype = ctypes.c_void_p
    lib.lpn_ac_read.argtypes = [ctypes.c_void_p, i32p, i32p, u32p, u8p]
    lib.lpn_ac_read.restype = None
    lib.lpn_ac_free.argtypes = [ctypes.c_void_p]
    lib.lpn_ac_free.restype = None
    return lib


def get_lib() -> ctypes.CDLL | None:
    """The bound native library, or None when unavailable."""
    global _lib, _tried, _load_error
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("LOG_PARSER_TPU_NO_NATIVE"):
            _load_error = "disabled by LOG_PARSER_TPU_NO_NATIVE"
            return None
        try:
            # a prebuilt .so without source alongside (container runtime
            # stage, no toolchain) is loaded as-is; staleness only applies
            # when the source is present to rebuild from
            if _SRC.exists():
                stale = (
                    not _SO.exists() or _SO.stat().st_mtime < _SRC.stat().st_mtime
                )
                if stale and not _compile():
                    return None
            elif not _SO.exists():
                _load_error = f"no prebuilt library at {_SO} and no source to build"
                return None
            _lib = _bind(ctypes.CDLL(str(_SO)))
        except OSError as e:
            # the GLIBCXX case lands here: the .so links a newer
            # libstdc++ than the host ships (PERF.md §10)
            log.warning("native library unavailable: %s", e)
            global _load_triage
            if "GLIBCXX" in str(e):
                tri = glibcxx_triage()
                _load_triage = tri
                gap = (
                    f"needs {', '.join(tri['missing'])}; host "
                    f"{tri['libstdcxx'] or 'libstdc++ (not found)'} tops "
                    f"out at "
                    f"{tri['provided'][-1] if tri['provided'] else '?'}"
                    if tri["missing"]
                    else str(e)[:200]
                )
                _load_error = (
                    f"glibcxx mismatch: {gap} — rebuild on this host "
                    "(python tools/check_native.py --rebuild) or use the "
                    "Dockerfile native-rebuild stage"
                )
            else:
                _load_error = f"load failed: {e}"
            _lib = None
        except AttributeError as e:
            # a prebuilt .so from an older source revision lacks newly
            # added symbols — fall back to pure Python, never crash
            log.warning("native library is stale (missing symbol): %s", e)
            _load_error = f"stale library (missing symbol): {e}"
            _lib = None
    return _lib


def available() -> bool:
    return get_lib() is not None


def stats() -> dict:
    """GET /trace/last ``native`` block (docs/OPS.md): which ingest path
    this process is running, and — when the scalar fallback is active —
    the recorded reason the shared object refused to load."""
    lib = get_lib()
    doc = {
        "available": lib is not None,
        "loadError": _load_error,
    }
    if _load_triage is not None:
        doc["glibcxx"] = _load_triage
    return doc
