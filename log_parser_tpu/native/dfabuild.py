"""Native NFA→DFA subset construction (ctypes front-end).

Serializes the Python :class:`~log_parser_tpu.patterns.regex.nfa.Nfa` into
flat CSR arrays, runs the C++ builder (same algorithm as
patterns/regex/dfa.py — assertion-aware closure, sticky MATCHED sink), and
adds what the Python builder doesn't do: Moore minimization + byte-class
recompression, which shrink the packed device tables for large libraries.

Returns None when the native library is unavailable or the state cap is
exceeded (caller decides the fallback: Python builder or host regex).
"""

from __future__ import annotations

import ctypes

import numpy as np

from log_parser_tpu.native import get_lib
from log_parser_tpu.patterns.regex.nfa import Nfa
from log_parser_tpu.patterns.regex.parser import WORD_BYTES

_COND_CODE = {None: 0, "^": 1, "$": 2, "b": 3, "B": 4}

_WORD_MASK = np.zeros(32, dtype=np.uint8)
for _b in WORD_BYTES:
    _WORD_MASK[_b >> 3] |= 1 << (_b & 7)


def _byteset_mask(bs: frozenset[int]) -> np.ndarray:
    m = np.zeros(32, dtype=np.uint8)
    for b in bs:
        m[b >> 3] |= 1 << (b & 7)
    return m


class DfaLimitExceeded(Exception):
    pass


def _serialize_nfa(nfa: Nfa):
    """Flatten an Nfa into the CSR arrays the C ABI consumes."""
    n = nfa.n_states
    # epsilon CSR
    eps_off = np.zeros(n + 1, dtype=np.int64)
    eps_cond, eps_dst = [], []
    for s in range(n):
        for cond, dst in nfa.eps[s]:
            eps_cond.append(_COND_CODE[cond])
            eps_dst.append(dst)
        eps_off[s + 1] = len(eps_dst)
    eps_cond_a = np.asarray(eps_cond or [0], dtype=np.int8)
    eps_dst_a = np.asarray(eps_dst or [0], dtype=np.int32)

    # transition CSR with interned bytesets
    bs_ids: dict[frozenset[int], int] = {}
    masks: list[np.ndarray] = []
    t_off = np.zeros(n + 1, dtype=np.int64)
    t_bs, t_dst = [], []
    for s in range(n):
        for bs, dst in nfa.trans[s]:
            bid = bs_ids.get(bs)
            if bid is None:
                bid = len(masks)
                bs_ids[bs] = bid
                masks.append(_byteset_mask(bs))
            t_bs.append(bid)
            t_dst.append(dst)
        t_off[s + 1] = len(t_dst)
    t_bs_a = np.asarray(t_bs or [0], dtype=np.int32)
    t_dst_a = np.asarray(t_dst or [0], dtype=np.int32)
    bytesets = (
        np.concatenate(masks) if masks else np.zeros(32, dtype=np.uint8)
    ).astype(np.uint8)
    return eps_off, eps_cond_a, eps_dst_a, t_off, t_bs_a, t_dst_a, bytesets, len(masks)


def _p(arr, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


def build_dfa_native(nfa: Nfa, max_states: int = 4096, minimize: bool = True):
    """(trans, byte_class, accept_end, start) or None if lib unavailable.

    Raises :class:`DfaLimitExceeded` on state blowup.
    """
    lib = get_lib()
    if lib is None:
        return None

    n = nfa.n_states
    (
        eps_off, eps_cond_a, eps_dst_a, t_off, t_bs_a, t_dst_a, bytesets, n_bs
    ) = _serialize_nfa(nfa)

    p = _p

    out_ns = ctypes.c_int32(0)
    out_nc = ctypes.c_int32(0)
    out_start = ctypes.c_int32(0)
    err = ctypes.c_int32(0)
    handle = lib.lpn_dfa_build(
        n, nfa.start, nfa.final,
        p(eps_off, ctypes.c_int64), p(eps_cond_a, ctypes.c_int8),
        p(eps_dst_a, ctypes.c_int32),
        p(t_off, ctypes.c_int64), p(t_bs_a, ctypes.c_int32),
        p(t_dst_a, ctypes.c_int32),
        p(bytesets, ctypes.c_uint8), n_bs,
        p(_WORD_MASK, ctypes.c_uint8),
        max_states, int(minimize),
        ctypes.byref(out_ns), ctypes.byref(out_nc), ctypes.byref(out_start),
        ctypes.byref(err),
    )
    if not handle:
        if err.value == 1:
            raise DfaLimitExceeded(max_states)
        return None
    try:
        ns, nc = out_ns.value, out_nc.value
        trans = np.zeros((ns, nc), dtype=np.int32)
        byte_class = np.zeros(256, dtype=np.int32)
        accept = np.zeros(ns, dtype=np.uint8)
        lib.lpn_dfa_read(
            handle,
            p(trans, ctypes.c_int32),
            p(byte_class, ctypes.c_int32),
            p(accept, ctypes.c_uint8),
        )
    finally:
        lib.lpn_dfa_free(handle)
    return trans, byte_class, accept.astype(bool), out_start.value


def _read_extraction_all(lib, handle, n: int):
    """Per-regex (literals | None, exact_seqs | None) pairs — the native
    port of patterns/regex/literals.py, transferred for the WHOLE batch
    in one call (per-regex crossings measured ~0.6 s at 10k) and
    reconstructed into the same Literal frozensets / byteset-sequence
    tuples.  Position bytesets come as compact byte LISTS, so each
    frozenset builds straight off a bytes slice."""
    from log_parser_tpu.patterns.regex.literals import Literal

    t_lit = ctypes.c_int64(0)
    t_lit_b = ctypes.c_int64(0)
    t_seq = ctypes.c_int64(0)
    t_pos = ctypes.c_int64(0)
    t_seq_b = ctypes.c_int64(0)
    lib.lpn_regex_batch_extract_totals(
        handle, ctypes.byref(t_lit), ctypes.byref(t_lit_b),
        ctypes.byref(t_seq), ctypes.byref(t_pos), ctypes.byref(t_seq_b),
    )
    p = _p
    lit_status = np.zeros(n, dtype=np.int8)
    lit_counts = np.zeros(n, dtype=np.int32)
    lit_offs = np.zeros(t_lit.value + 1, dtype=np.int64)
    lit_ci = np.zeros(max(1, t_lit.value), dtype=np.uint8)
    lit_blob_a = np.zeros(max(1, t_lit_b.value), dtype=np.uint8)
    seq_status = np.zeros(n, dtype=np.int8)
    seq_counts = np.zeros(n, dtype=np.int32)
    seq_lens = np.zeros(max(1, t_seq.value), dtype=np.int32)
    pos_counts = np.zeros(max(1, t_pos.value), dtype=np.int32)
    seq_blob_a = np.zeros(max(1, t_seq_b.value), dtype=np.uint8)
    lib.lpn_regex_batch_extract_all(
        handle,
        p(lit_status, ctypes.c_int8), p(lit_counts, ctypes.c_int32),
        p(lit_offs, ctypes.c_int64), p(lit_ci, ctypes.c_uint8),
        p(lit_blob_a, ctypes.c_uint8),
        p(seq_status, ctypes.c_int8), p(seq_counts, ctypes.c_int32),
        p(seq_lens, ctypes.c_int32), p(pos_counts, ctypes.c_int32),
        p(seq_blob_a, ctypes.c_uint8),
    )
    lit_blob = lit_blob_a.tobytes()
    seq_blob = seq_blob_a.tobytes()
    loffs = lit_offs.tolist()
    lcis = lit_ci.tolist()
    slens = seq_lens.tolist()
    pcounts = pos_counts.tolist()
    out = []
    lk = 0
    sk = 0
    pk = 0
    sboff = 0
    for r in range(n):
        literals = None
        if lit_status[r] == 0:
            nl = int(lit_counts[r])
            literals = frozenset(
                Literal(lit_blob[loffs[lk + k]:loffs[lk + k + 1]],
                        bool(lcis[lk + k]))
                for k in range(nl)
            )
            lk += nl
        seqs = None
        if seq_status[r] == 0:
            built = []
            for s in range(int(seq_counts[r])):
                ln = slens[sk]
                sk += 1
                pos_sets = []
                for _ in range(ln):
                    cnt = pcounts[pk]
                    pk += 1
                    pos_sets.append(frozenset(seq_blob[sboff:sboff + cnt]))
                    sboff += cnt
                built.append(tuple(pos_sets))
            seqs = tuple(built) if built else None
        out.append((literals, seqs))
    return out


def build_dfas_batch(
    entries: list[tuple[str, bool]], max_states: int = 4096,
    minimize: bool = True, with_extraction: bool = False,
):
    """Compile ``entries`` (regex, case_insensitive) through the fully
    native parse → Thompson → subset pipeline in ONE call.

    Returns a list aligned with ``entries``: ``(trans, byte_class,
    accept, start)`` per success, ``None`` where the native port
    declined (unsupported construct or state cap) — the caller runs the
    Python pipeline for those, which reproduces the exact
    RegexUnsupportedError/DfaLimitError classification.  Returns None
    for the WHOLE batch when the native library is unavailable.
    With ``with_extraction`` each success becomes a 3-tuple
    ``(dfa_arrays, literals, exact_seqs)`` — the native port of
    literals.py computed on the same parse.
    """
    lib = get_lib()
    if lib is None:
        return None
    if not entries:
        return []
    pats = [r.encode("utf-8") for r, _ in entries]
    blob = np.frombuffer(b"".join(pats) or b"\0", dtype=np.uint8)
    offs = np.zeros(len(entries) + 1, dtype=np.int64)
    np.cumsum([len(b) for b in pats], out=offs[1:])
    ci = np.asarray([1 if c else 0 for _, c in entries], dtype=np.uint8)

    p = _p
    handle = lib.lpn_regex_batch_build(
        p(blob, ctypes.c_uint8), p(offs, ctypes.c_int64),
        p(ci, ctypes.c_uint8), len(entries),
        p(_WORD_MASK, ctypes.c_uint8), max_states, int(minimize),
    )
    if not handle:
        return None
    out = []
    try:
        extraction = (
            _read_extraction_all(lib, handle, len(entries))
            if with_extraction
            else None
        )
        ns = ctypes.c_int32(0)
        nc = ctypes.c_int32(0)
        start = ctypes.c_int32(0)
        for i in range(len(entries)):
            status = lib.lpn_regex_batch_get(
                handle, i, ctypes.byref(ns), ctypes.byref(nc),
                ctypes.byref(start),
            )
            if status != 0:
                out.append(None)
                continue
            trans = np.zeros((ns.value, nc.value), dtype=np.int32)
            byte_class = np.zeros(256, dtype=np.int32)
            accept = np.zeros(ns.value, dtype=np.uint8)
            lib.lpn_regex_batch_read(
                handle, i,
                p(trans, ctypes.c_int32), p(byte_class, ctypes.c_int32),
                p(accept, ctypes.c_uint8),
            )
            arrays = (trans, byte_class, accept.astype(bool), start.value)
            if extraction is not None:
                lits, seqs = extraction[i]
                out.append((arrays, lits, seqs))
            else:
                out.append(arrays)
    finally:
        lib.lpn_regex_batch_free(handle)
    return out


def build_multi_dfa_native(
    nfa: Nfa, finals: list[int], max_states: int = 8192, minimize: bool = True
):
    """Union multi-pattern subset construction (multidfa.py, native path).

    ``nfa`` is the MERGED union arena (multidfa._merge_nfas); ``finals[i]``
    is pattern i's final state. Returns (trans, byte_class, cls_word, out2,
    accept_words, start) or None if the lib is unavailable; raises
    :class:`DfaLimitExceeded` on state blowup.
    """
    lib = get_lib()
    if lib is None:
        return None

    (
        eps_off, eps_cond_a, eps_dst_a, t_off, t_bs_a, t_dst_a, bytesets, n_bs
    ) = _serialize_nfa(nfa)
    finals_a = np.asarray(finals, dtype=np.int32)
    n_patterns = len(finals)

    p = _p
    out_ns = ctypes.c_int32(0)
    out_nc = ctypes.c_int32(0)
    out_nw = ctypes.c_int32(0)
    out_start = ctypes.c_int32(0)
    err = ctypes.c_int32(0)
    handle = lib.lpn_multi_dfa_build(
        nfa.n_states, nfa.start,
        p(eps_off, ctypes.c_int64), p(eps_cond_a, ctypes.c_int8),
        p(eps_dst_a, ctypes.c_int32),
        p(t_off, ctypes.c_int64), p(t_bs_a, ctypes.c_int32),
        p(t_dst_a, ctypes.c_int32),
        p(bytesets, ctypes.c_uint8), n_bs,
        p(_WORD_MASK, ctypes.c_uint8),
        p(finals_a, ctypes.c_int32), n_patterns,
        max_states, int(minimize),
        ctypes.byref(out_ns), ctypes.byref(out_nc), ctypes.byref(out_nw),
        ctypes.byref(out_start), ctypes.byref(err),
    )
    if not handle:
        if err.value == 1:
            raise DfaLimitExceeded(max_states)
        return None
    try:
        ns, nc, nw = out_ns.value, out_nc.value, out_nw.value
        trans = np.zeros((ns, nc), dtype=np.int32)
        byte_class = np.zeros(256, dtype=np.int32)
        cls_word = np.zeros(nc, dtype=np.int32)
        out2 = np.zeros((ns * 2, nw), dtype=np.uint32)
        accept_words = np.zeros((ns, nw), dtype=np.uint32)
        lib.lpn_multi_dfa_read(
            handle,
            p(trans, ctypes.c_int32),
            p(byte_class, ctypes.c_int32),
            p(cls_word, ctypes.c_int32),
            p(out2, ctypes.c_uint32),
            p(accept_words, ctypes.c_uint32),
        )
    finally:
        lib.lpn_multi_dfa_free(handle)
    return trans, byte_class, cls_word, out2, accept_words, out_start.value
