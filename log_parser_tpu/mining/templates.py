"""Online template clusterer over the line-cache miss stream.

Logram (PAPERS.md) shows that token-position dictionaries make online
log-template discovery cheap: most log lines are a fixed token skeleton
with a few variable slots. This module groups ingest-normalized miss
lines into such templates — a list of fixed tokens and ``<*>`` wildcard
slots — with Drain-style position-wise similarity merging, and promotes
a cluster to candidate status only once it has both **support** (enough
distinct observations) and **stability** (the template stopped changing,
so later merges would not widen the synthesized regex).

Everything here is defensive by construction: lines are decoded with
``errors="replace"``, truncated at a byte ceiling, and tokenized to a
bounded token count, so hostile input (NULs, 1 MB lines, invalid UTF-8,
metacharacter soup — tools/fuzz_sweep.py --miner) can cost at most a
bounded amount of work and can never raise out of :meth:`observe`.
"""

from __future__ import annotations

import hashlib
import re
import threading

# hostile-input ceilings: a line longer than this is truncated before
# tokenizing (the template of a 1 MB line's head is as good as the whole),
# and a line with more tokens than the cap is ignored (no real log
# template has 48+ positions; unbounded positions would also blow the
# synthesized regex past the NFA repeat guard)
MAX_LINE_BYTES = 4096
MAX_TOKENS = 48

# a token carrying a digit is masked to a wildcard before clustering
# (Logram/Drain preprocessing): ids, counters, timestamps never belong
# to the fixed skeleton, and masking them early keeps one template from
# splintering into thousands of single-support clusters
_DIGIT_RE = re.compile(r"\d")

WILDCARD = None  # slot marker inside a template tuple


def tokenize(line_bytes: bytes) -> tuple:
    """Ingest-normalized line bytes -> bounded template-key token tuple.

    Tokens are whitespace-separated; digit-bearing tokens are masked to
    :data:`WILDCARD` immediately. Returns ``()`` for blank lines and for
    lines past the token cap (both unminable)."""
    text = line_bytes[:MAX_LINE_BYTES].decode("utf-8", errors="replace")
    toks = text.split()
    if not toks or len(toks) > MAX_TOKENS:
        return ()
    return tuple(
        WILDCARD if _DIGIT_RE.search(t) else t for t in toks
    )


def template_id(template: tuple) -> str:
    """Stable candidate id for one template: ``mined-<blake2b-12hex>`` of
    the rendered template text — deterministic across processes, so a
    re-mined template maps to the same pattern id (and the same pending
    file) every time."""
    return "mined-" + hashlib.blake2b(
        render(template).encode("utf-8", errors="replace"), digest_size=6
    ).hexdigest()


def render(template: tuple) -> str:
    """Human-readable template text (``<*>`` for wildcard slots)."""
    return " ".join("<*>" if t is WILDCARD else t for t in template)


class Cluster:
    """One template cluster: the merged token template plus its support
    and stability bookkeeping."""

    __slots__ = ("template", "support", "since_change", "promoted")

    def __init__(self, template: tuple):
        self.template = template
        self.support = 0  # lines observed (weighted by multiplicity)
        self.since_change = 0  # observations since the template last changed
        self.promoted = False  # handed to the synthesizer already

    def fixed_tokens(self) -> list[str]:
        return [t for t in self.template if t is not WILDCARD]

    def to_json(self) -> dict:
        return {
            "id": template_id(self.template),
            "template": render(self.template),
            "support": self.support,
            "sinceChange": self.since_change,
            "promoted": self.promoted,
        }


class TemplateClusterer:
    """Online, bounded, thread-compatible template clustering.

    ``observe`` buckets lines by token count, merges into the most
    similar existing cluster when at least ``sim_threshold`` of positions
    agree (wildcard positions count as agreeing — an established slot
    absorbs any token), else opens a new cluster. Differing positions
    become wildcards on merge, which resets the cluster's stability
    clock. Cluster count is bounded by ``max_clusters``; once full, novel
    templates are counted in ``discarded`` instead of evicting support
    the promoter is still accumulating.
    """

    def __init__(
        self,
        *,
        min_support: int = 8,
        sim_threshold: float = 0.55,
        stability: int = 4,
        max_clusters: int = 512,
    ):
        self.lock = threading.Lock()
        self.min_support = max(1, int(min_support))
        self.sim_threshold = float(sim_threshold)
        self.stability = max(0, int(stability))
        self.max_clusters = max(1, int(max_clusters))
        self._by_len: dict[int, list[Cluster]] = {}
        self._n = 0
        self.observed = 0
        self.skipped = 0  # blank / over-cap lines
        self.discarded = 0  # novel templates past max_clusters

    def observe(self, line_bytes: bytes, count: int = 1) -> None:
        template = tokenize(line_bytes)
        with self.lock:
            if not template:
                self.skipped += 1
                return
            self.observed += int(count)
            bucket = self._by_len.setdefault(len(template), [])
            best, best_sim = None, -1.0
            for c in bucket:
                same = sum(
                    1
                    for a, b in zip(c.template, template)
                    if a is WILDCARD or a == b
                )
                sim = same / len(template)
                if sim > best_sim:
                    best, best_sim = c, sim
            if best is not None and best_sim >= self.sim_threshold:
                merged = tuple(
                    a if (a is WILDCARD or a == b) else WILDCARD
                    for a, b in zip(best.template, template)
                )
                if merged != best.template:
                    best.template = merged
                    best.since_change = 0
                    best.promoted = False  # widened: re-earn stability
                else:
                    best.since_change += 1
                best.support += int(count)
                return
            if self._n >= self.max_clusters:
                self.discarded += 1
                return
            c = Cluster(template)
            c.support = int(count)
            bucket.append(c)
            self._n += 1

    def promotable(self) -> list[Cluster]:
        """Clusters ready for synthesis: supported, stable, not yet
        promoted, and carrying at least one fixed token long enough to
        seed a literal probe. Marks them promoted so one stable template
        is synthesized exactly once (until a merge widens it again)."""
        out: list[Cluster] = []
        with self.lock:
            for bucket in self._by_len.values():
                for c in bucket:
                    if c.promoted or c.support < self.min_support:
                        continue
                    if c.since_change < self.stability:
                        continue
                    if not any(len(t) >= 4 for t in c.fixed_tokens()):
                        continue
                    c.promoted = True
                    out.append(c)
        return out

    def snapshot(self) -> list[dict]:
        with self.lock:
            return [
                c.to_json()
                for bucket in self._by_len.values()
                for c in bucket
            ]

    def stats(self) -> dict:
        with self.lock:
            return {
                "clusters": self._n,
                "observed": self.observed,
                "skipped": self.skipped,
                "discarded": self.discarded,
            }
