"""Self-growing pattern library: online template mining from the
line-cache miss stream.

The loop (docs/ARCHITECTURE.md "Self-growing pattern library"):

    miss tap (runtime/linecache.MissTap)
      → online clusterer (templates.TemplateClusterer)
      → synthesizer (synthesize.synthesize)
      → admission (admit.vet_candidate / admit.admit_candidate)
      → review parking or canary + quiesced swap

Enabled per engine via ``AnalysisEngine.enable_miner`` (serve flag
``--miner``); per-tenant state lives beside the tenant WAL.
"""

from log_parser_tpu.mining.admit import (
    REJECT_REASONS,
    Rejection,
    admit_candidate,
    vet_candidate,
)
from log_parser_tpu.mining.miner import FAULT_SITES, MODES, TemplateMiner
from log_parser_tpu.mining.synthesize import candidate_yaml, synthesize
from log_parser_tpu.mining.templates import TemplateClusterer, tokenize

__all__ = [
    "REJECT_REASONS",
    "Rejection",
    "admit_candidate",
    "vet_candidate",
    "FAULT_SITES",
    "MODES",
    "TemplateMiner",
    "candidate_yaml",
    "synthesize",
    "TemplateClusterer",
    "tokenize",
]
