"""The template miner: background consumer of the line-cache miss tap.

One :class:`TemplateMiner` per engine (the default engine and every
resident tenant engine own one — state namespaced beside the tenant's
WAL under ``state_dir/mined/``). The worker thread drains the
:class:`~log_parser_tpu.runtime.linecache.MissTap`, feeds the online
clusterer, and pushes each newly-stable template through synthesis and
the admission pipeline:

- ``review`` (default): candidates that pass the vet gates (compile,
  subsumption, lint) are parked as YAML in ``state_dir/mined/pending/``
  and surfaced on ``GET /patterns/mined``; an operator approves or
  rejects via ``POST /patterns/mined`` — approval runs the full canary
  ladder and the quiesced swap.
- ``auto``: vetted candidates go straight through canary + swap, and
  shadow verification is forced on (``DEFAULT_SHADOW_RATE`` when the
  operator has not enabled it) so every admitted mined id is
  continuously re-verified against the golden host path — the
  "Lost in Translation" guard rail (docs/PATTERNS.md).
- ``off``: the miner clusters and reports but never synthesizes.

The worker is fully contained: admission rejections are counters, any
other exception (including the injected ``miner`` fault site) bumps
``errors`` and the loop continues — a miner defect can degrade mining,
never parsing.
"""

from __future__ import annotations

import logging
import os
import threading
from collections import Counter, deque

import yaml

from log_parser_tpu.mining.admit import (
    RETRYABLE_REASONS,
    Rejection,
    admit_candidate,
    vet_candidate,
)
from log_parser_tpu.mining.synthesize import candidate_yaml, synthesize
from log_parser_tpu.mining.templates import TemplateClusterer
from log_parser_tpu.models.pattern import PatternSet
from log_parser_tpu.runtime import faults, pressure
from log_parser_tpu.runtime.linecache import DEFAULT_TAP_CAPACITY, MissTap

log = logging.getLogger(__name__)

MODES = ("off", "review", "auto")
DEFAULT_SHADOW_RATE = 0.05
_MAX_SWAP_RETRIES = 5
_DRAIN_BATCH = 512

# chaos vocabulary — tools/hygiene.py check 14 pins every key here to a
# docs/OPS.md row AND a live faults.fire call site, exactly like check 13
# does for the tenancy sites
FAULT_SITES: dict[str, str] = {
    "miner": "miner worker loop, once per pump — a hang wedges the "
    "worker (the tap fills and drops; the hot path never notices), a "
    "raise bumps miner.errors and the loop continues",
    "miner_admit": "candidate admission, before the vet gates — raise "
    "becomes a structured mined-fault rejection, the bank untouched",
}


class TemplateMiner:
    """Owns the tap, the clusterer, the pending-candidate store, and the
    worker thread for ONE engine."""

    def __init__(
        self,
        engine,
        *,
        mode: str = "review",
        sample: float = 1.0,
        min_support: int = 8,
        state_dir: str | None = None,
        capacity: int = DEFAULT_TAP_CAPACITY,
        poll_s: float = 0.25,
        shadow_rate: float = DEFAULT_SHADOW_RATE,
        stability: int = 4,
    ):
        if mode not in MODES:
            raise ValueError(f"miner mode must be one of {MODES}, got {mode!r}")
        self.engine = engine
        self.mode = mode
        self.poll_s = float(poll_s)
        self.shadow_rate = float(shadow_rate)
        self.tap = MissTap(capacity=capacity, sample=sample)
        self.clusterer = TemplateClusterer(
            min_support=min_support, stability=stability
        )
        self.pending_dir = (
            os.path.join(state_dir, "mined", "pending") if state_dir else None
        )
        self.lock = threading.Lock()
        self._pending: dict[str, dict] = {}  # id -> {yaml, template, support, tier}
        self._retry: deque[tuple[PatternSet, int]] = deque()
        self.promoted = 0
        self.admitted = 0
        self.errors = 0
        self.park_skipped = 0  # pending-YAML persists paused/refused by disk pressure
        self._rejected: Counter[str] = Counter()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._load_pending()

    # ------------------------------------------------------------ lifecycle

    def start(self) -> "TemplateMiner":
        self._thread = threading.Thread(
            target=self._run, name="template-miner", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, timeout: float = 2.0) -> None:
        self._stop.set()
        self.tap.close()
        t = self._thread
        if t is not None:
            t.join(timeout)  # a fault-wedged worker is daemon; don't hang shutdown
            self._thread = None

    def _run(self) -> None:
        while not self._stop.is_set():
            self.pump(timeout=self.poll_s)

    # ------------------------------------------------------------ pipeline

    def pump(self, timeout: float = 0.0) -> int:
        """One synchronous mining cycle: drain → cluster → promote →
        synthesize → admit/park. The worker thread calls this in a loop;
        tests and tools/mine_report.py call it directly for determinism.
        Returns the number of miss lines consumed. Never raises."""
        try:
            faults.fire("miner")
            items = self.tap.drain(max_items=_DRAIN_BATCH, timeout=timeout)
            for line_bytes, count in items:
                self.clusterer.observe(line_bytes, count)
            if self.mode != "off":
                self._retry_swaps()
                for cluster in self.clusterer.promotable():
                    with self.lock:
                        self.promoted += 1
                    self._handle_candidate(synthesize(cluster))
            return len(items)
        except Exception:  # noqa: BLE001 — the miner must never take the
            # process (or the serving path) down; the fault site above
            # and any real defect land here as a counter
            with self.lock:
                self.errors += 1
            log.exception("miner pump failed")
            return 0

    def _handle_candidate(self, candidate: PatternSet) -> None:
        pid = (candidate.patterns or [None])[0].id
        try:
            if self.mode == "auto":
                result = admit_candidate(self.engine, candidate)
                self._note_admitted(result)
            else:  # review: vet, then park for the operator
                vet = vet_candidate(self.engine, candidate)
                self._park(candidate, vet)
        except Rejection as exc:
            self._note_rejected(exc, candidate)
        except Exception:  # noqa: BLE001 — same containment as pump
            with self.lock:
                self.errors += 1
            log.exception("candidate %s failed out of band", pid)

    def _retry_swaps(self) -> None:
        """Transient (mined-swap) rejections re-enter admission on later
        pumps, bounded by _MAX_SWAP_RETRIES attempts each."""
        for _ in range(len(self._retry)):
            candidate, attempts = self._retry.popleft()
            try:
                self._note_admitted(admit_candidate(self.engine, candidate))
            except Rejection as exc:
                if exc.reason in RETRYABLE_REASONS and attempts + 1 < _MAX_SWAP_RETRIES:
                    self._retry.append((candidate, attempts + 1))
                else:
                    self._note_rejected(exc, candidate, retryable=False)

    def _note_admitted(self, result: dict) -> None:
        with self.lock:
            self.admitted += 1
        if self.mode == "auto" and self.engine.shadow is None:
            # forced-on shadow verification for mined ids: every admitted
            # generated pattern keeps being re-checked against the golden
            # host path; a divergence trips its breaker and the pattern
            # serves from host truth while the operator triages
            self.engine.enable_shadow(self.shadow_rate)
        log.info("miner admitted %s (epoch %s)", result.get("id"), result.get("epoch"))

    def _note_rejected(
        self, exc: Rejection, candidate: PatternSet, retryable: bool = True
    ) -> None:
        if retryable and exc.reason in RETRYABLE_REASONS:
            self._retry.append((candidate, 1))
            return
        with self.lock:
            self._rejected[exc.reason] += 1
        log.info("miner rejected candidate: %s", exc)

    # ------------------------------------------------------- review surface

    def _park(self, candidate: PatternSet, vet: dict) -> None:
        pid = (candidate.patterns or [None])[0].id
        text = candidate_yaml(candidate)
        entry = {
            "id": pid,
            "yaml": text,
            "template": (candidate.patterns[0].remediation or {}).get("template", ""),
            "support": (candidate.patterns[0].remediation or {}).get("support", 0),
            **vet,
        }
        with self.lock:
            self._pending[pid] = entry
        self._persist_pending(pid, text)

    def _persist_pending(self, pid: str, text: str) -> None:
        """Write one parked candidate's YAML beside the WAL. Under disk
        pressure (soft or hard) parking pauses: the candidate stays
        reviewable in memory — losing a mined *suggestion* across a
        crash is the cheapest possible shed, so this is the first
        writer the ladder turns off."""
        if not self.pending_dir:
            return
        if pressure.miner_park_paused():
            with self.lock:
                self.park_skipped += 1
            return
        try:
            os.makedirs(self.pending_dir, exist_ok=True)
            path = os.path.join(self.pending_dir, f"{pid}.yaml")
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as fh:
                fh.write(text)
            os.replace(tmp, path)
        except OSError as exc:
            # organic full disk on the same writer: contained — mining
            # must never take the serving path (or the worker) down
            with self.lock:
                self.park_skipped += 1
            pressure.note_write_error(exc, "miner_park")
            log.warning("parking candidate %s failed: %s", pid, exc)

    def adopt_pending(self, entries) -> int:
        """Re-park candidate entries exported by a tenant migration
        (runtime/migrate.py): insert each parked candidate and persist
        its yaml under this miner's pending dir so the review workflow
        continues on the new owner. Entries without an id or yaml are
        skipped; an existing id is left alone (the local copy already
        survived a restart). Returns how many were adopted."""
        adopted = 0
        for entry in entries or ():
            pid = str(entry.get("id") or "")
            text = entry.get("yaml")
            if not pid or not text:
                continue
            with self.lock:
                if pid in self._pending:
                    continue
                self._pending[pid] = dict(entry)
            adopted += 1
            self._persist_pending(pid, str(text))
        return adopted

    def _load_pending(self) -> None:
        """Rehydrate parked candidates across restarts (review workflow:
        a pending candidate survives like the WAL beside it does)."""
        if not self.pending_dir or not os.path.isdir(self.pending_dir):
            return
        for name in sorted(os.listdir(self.pending_dir)):
            if not name.endswith(".yaml"):
                continue
            path = os.path.join(self.pending_dir, name)
            try:
                with open(path, "r", encoding="utf-8") as fh:
                    text = fh.read()
                ps = PatternSet.from_dict(yaml.safe_load(text))
                pat = (ps.patterns or [None])[0]
                if pat is None or not pat.id:
                    continue
                self._pending[pat.id] = {
                    "id": pat.id,
                    "yaml": text,
                    "template": (pat.remediation or {}).get("template", ""),
                    "support": (pat.remediation or {}).get("support", 0),
                }
            except Exception:  # noqa: BLE001 — a corrupt pending file is
                # skipped, not fatal (same posture as the pattern loader)
                log.exception("skipping unreadable pending candidate %s", path)

    def pending_list(self) -> list[dict]:
        with self.lock:
            return [
                {k: v for k, v in e.items() if k != "yaml"}
                for e in self._pending.values()
            ]

    def pending_yaml(self, candidate_id: str) -> str | None:
        with self.lock:
            e = self._pending.get(candidate_id)
            return e["yaml"] if e else None

    def approve(self, candidate_id: str, timeout_s: float = 30.0) -> dict:
        """Operator approval: the parked candidate runs the FULL ladder
        (vet again against the current library — it may have changed
        since parking — then canary + quiesced swap). Raises KeyError for
        an unknown id, :class:`Rejection` with the structured reason on
        any gate failure (the HTTP surface maps it to a 409)."""
        text = self.pending_yaml(candidate_id)
        if text is None:
            raise KeyError(candidate_id)
        candidate = PatternSet.from_dict(yaml.safe_load(text))
        result = admit_candidate(self.engine, candidate, timeout_s=timeout_s)
        self._note_admitted(result)
        self.discard(candidate_id)
        return result

    def discard(self, candidate_id: str) -> bool:
        with self.lock:
            found = self._pending.pop(candidate_id, None) is not None
        if self.pending_dir:
            try:
                os.unlink(os.path.join(self.pending_dir, f"{candidate_id}.yaml"))
            except FileNotFoundError:
                pass
        return found

    # ------------------------------------------------------- observability

    def stats(self) -> dict:
        tap = self.tap.stats()
        cl = self.clusterer.stats()
        with self.lock:
            return {
                "mode": self.mode,
                **tap,
                **cl,
                "promoted": self.promoted,
                "admitted": self.admitted,
                "rejected": dict(self._rejected),
                "pending": len(self._pending),
                "retrying": len(self._retry),
                "errors": self.errors,
                "parkSkipped": self.park_skipped,
            }
