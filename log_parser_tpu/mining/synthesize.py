"""Candidate PatternSet synthesis from a stable template cluster.

"Lost in Translation" (PAPERS.md) is the standing caution: machine-
generated regexes must be *narrow by construction* and semantically
verified before anything serves them. The synthesizer therefore emits
only a restricted dialect:

- fixed tokens are emitted as escaped literals (metacharacters
  backslash-escaped; a token carrying non-printable or non-ASCII bytes
  is demoted to a wildcard slot rather than risk an escape outside the
  automaton dialect);
- wildcard slots are **bounded** character classes (``\\S{1,64}``),
  never ``.*`` — a mined pattern can never match across token
  boundaries it did not see;
- token separators are bounded whitespace runs (``\\s{1,8}``).

The bounds keep every synthesized regex inside the byte-class DFA
tier's NFA budget (analysis/tiers.py), which the admission pipeline
*requires*: no DFA means no exact subsumption check against the curated
library, and an unverifiable candidate is rejected, not admitted.

The emitted :class:`PatternSet` is flagged ``generated: true`` on the
pattern (provenance — docs/PATTERNS.md "Generated patterns"), carries
the template and support in ``remediation`` for reviewers, and defaults
to ``severity: INFO`` / ``confidence: 0.5`` — a mined pattern states
"this template exists", not "this template is critical"; an operator
promotes severity by editing the YAML like any hand-authored pattern.
"""

from __future__ import annotations

import yaml

from log_parser_tpu.mining.templates import (
    WILDCARD,
    Cluster,
    render,
    template_id,
)
from log_parser_tpu.models.pattern import (
    Pattern,
    PatternSet,
    PatternSetMetadata,
    PrimaryPattern,
)

# bounded wildcard/separator fragments — never unbounded, never `.*`
WILDCARD_RE = r"\S{1,64}"
SEPARATOR_RE = r"\s{1,8}"

DEFAULT_SEVERITY = "INFO"
DEFAULT_CONFIDENCE = 0.5

# escaped inside literal tokens; every other printable-ASCII char is
# literal in the Java dialect outside a class
_META = set("\\^$.|?*+()[]{}")


def _escape_token(token: str) -> str | None:
    """Escaped-literal regex for one fixed token, or None when the token
    carries bytes outside printable ASCII (demoted to a wildcard by the
    caller — an exotic escape is exactly the kind of generated regex
    that fails semantic review)."""
    out: list[str] = []
    for ch in token:
        if not (0x21 <= ord(ch) <= 0x7E):
            return None
        out.append("\\" + ch if ch in _META else ch)
    return "".join(out)


def template_regex(template: tuple) -> str:
    """Bounded-dialect regex for one token template."""
    parts: list[str] = []
    for tok in template:
        frag = None if tok is WILDCARD else _escape_token(tok)
        parts.append(WILDCARD_RE if frag is None else frag)
    return SEPARATOR_RE.join(parts)


def synthesize(cluster: Cluster) -> PatternSet:
    """One candidate PatternSet for one stable cluster."""
    pid = template_id(cluster.template)
    text = render(cluster.template)
    regex = template_regex(cluster.template)
    pattern = Pattern(
        id=pid,
        name=f"Mined template: {text[:80]}",
        severity=DEFAULT_SEVERITY,
        primary_pattern=PrimaryPattern(
            regex=regex, confidence=DEFAULT_CONFIDENCE
        ),
        remediation={
            "source": "template-miner",
            "template": text,
            "support": cluster.support,
        },
        generated=True,
    )
    return PatternSet(
        metadata=PatternSetMetadata(
            library_id=f"mined.{pid}",
            name="Mined candidate",
            version="1",
            description=f"mined from {cluster.support} cache-miss lines",
        ),
        patterns=[pattern],
    )


def candidate_yaml(candidate: PatternSet) -> str:
    """Round-trippable YAML for one candidate — the exact bytes the
    review workflow parks in ``state_dir/<tenant>/mined/pending/`` and
    the loader reads back on approval."""
    return yaml.safe_dump(
        candidate.to_dict(drop_none=True), sort_keys=False
    )
