"""Admission pipeline for mined candidate patterns.

A synthesized candidate is never trusted: it runs the SAME gates a
hand-authored hot reload runs, plus one the reload ladder does not —
an explicit exact subsumption check against every curated primary.
Stages, in order (cheapest first):

1. **compile/tier** — the candidate's regex must compile through the
   bank's own entry points and land on a device tier with a byte-class
   DFA (``classify_regex``); no DFA means the subsumption gate cannot
   verify it, and an unverifiable candidate is rejected, not admitted;
2. **subsumption** — product-DFA comparison (analysis/subsumption.py)
   against every curated primary: a mined pattern whose language
   equals, strictly contains, or is strictly contained by a curated one
   is rejected with a structured reason — shadowing a curated pattern
   silently is the one failure mode this subsystem must never have;
3. **lint** — the full static-analysis pass (ReDoS heuristics, schema)
   over the candidate set; any gating finding rejects;
4. **canary + swap** (auto mode / review approval only) — the reload
   ladder's candidate build and device-vs-golden canary over the merged
   library, then the atomic quiesced ``apply_library`` swap.

Every rejection carries a stable reason code from :data:`REJECT_REASONS`
(tools/hygiene.py check 14 pins each code to a docs/PATTERNS.md row),
surfaces on ``/trace/last`` under ``miner.rejected``, and leaves the
serving bank object-identical — pinned by tests/test_mining.py and the
``tools/chaos_sweep.py --group miner`` drill.
"""

from __future__ import annotations

from log_parser_tpu.analysis import subsumption
from log_parser_tpu.analysis.lint import lint_pattern_sets
from log_parser_tpu.analysis.tiers import classify_regex
from log_parser_tpu.models.pattern import PatternSet
from log_parser_tpu.runtime import faults

# rejection-reason vocabulary (stable codes; check 14 pins each to a
# docs/PATTERNS.md row the same way check 13 pins tenancy FAULT_SITES)
REJECT_REASONS: dict[str, str] = {
    "mined-compile": "candidate regex failed the bank's compile entry points",
    "mined-tier": "candidate regex landed off the DFA-capable device tiers, "
    "so exact subsumption verification is impossible",
    "mined-duplicate-id": "a pattern with the candidate's id is already in "
    "the serving library",
    "mined-duplicate": "candidate language equals a curated pattern's "
    "(product-DFA EQUAL)",
    "mined-shadows-curated": "candidate language strictly contains a curated "
    "pattern's — admitting it would shadow the curated pattern",
    "mined-shadowed": "candidate language is strictly contained in a curated "
    "pattern's — every mined match already fires the curated pattern",
    "mined-undecided": "product-DFA budget exceeded before the relation was "
    "decided; undecidable candidates are rejected, never admitted",
    "mined-lint": "the static-analysis pass raised a gating finding",
    "mined-canary": "candidate build or device-vs-golden canary failed",
    "mined-swap": "the quiesced library swap failed or timed out (for "
    "example racing a concurrent curated reload); retried, not admitted",
    "mined-fault": "admission raised unexpectedly (injected miner_admit "
    "fault or a real defect); the candidate is rejected, the bank "
    "untouched",
}

# transient rejections the miner may retry on a later pump; everything
# else is a terminal verdict for that template
RETRYABLE_REASONS = frozenset({"mined-swap"})


class Rejection(Exception):
    """Structured admission rejection — reason ∈ :data:`REJECT_REASONS`."""

    def __init__(self, reason: str, detail: str, findings: list | None = None):
        assert reason in REJECT_REASONS, reason
        super().__init__(f"{reason}: {detail}")
        self.reason = reason
        self.detail = detail
        self.findings = findings or []

    def to_json(self) -> dict:
        out = {"reason": self.reason, "detail": self.detail}
        if self.findings:
            out["findings"] = self.findings
        return out


def _candidate_pattern(candidate: PatternSet):
    pats = candidate.patterns or []
    if len(pats) != 1 or pats[0].primary_pattern is None:
        raise Rejection(
            "mined-compile",
            "candidate set must carry exactly one primary-bearing pattern",
        )
    return pats[0]


def vet_candidate(
    engine,
    candidate: PatternSet,
    *,
    max_product_states: int = subsumption.DEFAULT_MAX_PRODUCT_STATES,
) -> dict:
    """Stages 1-3 (compile/tier, subsumption, lint) — everything short of
    touching the serving library. Raises :class:`Rejection`; returns the
    candidate's tier prediction summary on success. ``review`` mode runs
    exactly this before parking a candidate."""
    try:
        faults.fire("miner_admit")
        pat = _candidate_pattern(candidate)
        regex = pat.primary_pattern.regex

        # ---- stage 1: the bank's own compile entry points -------------
        pred = classify_regex(regex)
        if pred.tier == "skipped":
            raise Rejection(
                "mined-compile",
                f"{pred.reason_code}: {pred.detail}",
            )
        if pred.dfa is None:
            raise Rejection(
                "mined-tier",
                f"tier {pred.tier} ({pred.reason_code}): no byte-class DFA "
                "to verify subsumption against",
            )

        # ---- stage 2: exact subsumption vs every curated primary ------
        live_ids = {
            p.id
            for ps in engine.bank.pattern_sets
            for p in ps.patterns or []
        }
        if pat.id in live_ids:
            raise Rejection(
                "mined-duplicate-id", f"pattern id {pat.id!r} already serves"
            )
        for ps in engine.bank.pattern_sets:
            for cur in ps.patterns or []:
                if cur.primary_pattern is None or not cur.primary_pattern.regex:
                    continue
                cur_rx = cur.primary_pattern.regex
                if cur_rx == regex:
                    raise Rejection(
                        "mined-duplicate",
                        f"regex is byte-identical to curated {cur.id!r}",
                    )
                cur_pred = classify_regex(cur_rx)
                if cur_pred.dfa is None:
                    # a host-tier curated pattern has no DFA to compare;
                    # the byte-identity check above is the only exact
                    # statement available (documented limitation)
                    continue
                rel = subsumption.compare_dfas(
                    pred.dfa,
                    cur_pred.dfa,
                    max_product_states=max_product_states,
                )
                if rel == subsumption.EQUAL:
                    raise Rejection(
                        "mined-duplicate",
                        f"language equals curated {cur.id!r}",
                    )
                if rel == subsumption.B_IN_A:
                    raise Rejection(
                        "mined-shadows-curated",
                        f"language strictly contains curated {cur.id!r}",
                    )
                if rel == subsumption.A_IN_B:
                    raise Rejection(
                        "mined-shadowed",
                        f"language strictly contained in curated {cur.id!r}",
                    )
                if rel == subsumption.UNDECIDED:
                    raise Rejection(
                        "mined-undecided",
                        f"budget exceeded comparing against {cur.id!r}",
                    )

        # ---- stage 3: the lint gate (ReDoS + schema) ------------------
        # subsumption is off here: stage 2 just answered it exactly for
        # the only new pattern, and re-walking every curated pair per
        # candidate would be O(library²) for nothing
        report = lint_pattern_sets([candidate], check_subsumption=False)
        if report.gating:
            raise Rejection(
                "mined-lint",
                "; ".join(
                    f"{f.rule}: {f.detail}" for f in report.gating_findings
                ),
                findings=[f.to_json() for f in report.gating_findings],
            )
        return {"tier": pred.tier, "bitCapable": pred.bit_capable}
    except Rejection:
        raise
    except Exception as exc:  # noqa: BLE001 — injected miner_admit fault or a
        # real admission defect: either way the verdict is a structured
        # rejection, never an escaped exception (the miner thread and the
        # HTTP review surface both rely on this containment)
        raise Rejection("mined-fault", repr(exc)[:300]) from exc


def admit_candidate(
    engine,
    candidate: PatternSet,
    *,
    timeout_s: float = 30.0,
    max_product_states: int = subsumption.DEFAULT_MAX_PRODUCT_STATES,
) -> dict:
    """The full ladder: vet, then candidate build + canary over the
    merged library, then the atomic quiesced swap. Raises
    :class:`Rejection`; returns the admission envelope on success."""
    from log_parser_tpu.runtime.reload import (
        ReloadError,
        build_candidate,
        canary_validate,
    )

    vet = vet_candidate(
        engine, candidate, max_product_states=max_product_states
    )
    merged = list(engine.bank.pattern_sets) + [candidate]
    try:
        source = build_candidate(
            merged, engine.config, engine_clock=engine.frequency.clock
        )
        canary_events = canary_validate(source)
    except ReloadError as exc:
        raise Rejection(
            "mined-canary", f"{exc.stage}: {exc.reason}"
        ) from exc
    except Rejection:
        raise
    except Exception as exc:  # noqa: BLE001 — same containment as vet
        raise Rejection("mined-fault", repr(exc)[:300]) from exc
    try:
        epoch = engine.apply_library(source, timeout_s=timeout_s)
    except (TimeoutError, RuntimeError) as exc:
        raise Rejection("mined-swap", str(exc)) from exc
    pat = _candidate_pattern(candidate)
    return {
        "status": "admitted",
        "id": pat.id,
        "epoch": epoch,
        "canaryEvents": canary_events,
        **vet,
    }
