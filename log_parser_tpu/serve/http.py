"""``POST /parse`` HTTP endpoint — the reference's REST contract.

Contract parity with Parse.java:41-61:

- ``POST /parse`` consumes/produces JSON;
- a null body or null ``pod`` returns 400 with exactly
  ``{"error":"Invalid PodFailureData provided"}`` (Parse.java:45-49);
- success returns the full ``AnalysisResult`` (camelCase keys, Jackson bean
  convention) with 200;
- request/response logging mirrors Parse.java:51,55-58.

Additions over the reference (SURVEY.md §5.3 — it has no health endpoints
and no REST surface for the frequency admin API that exists only
programmatically at FrequencyTrackingService.java:101-134):

- ``GET /health`` (+ ``/health/live``, ``/health/ready``);
- ``GET /frequency/stats`` — current windowed counts per pattern id;
- ``POST /frequency/reset`` and ``POST /frequency/reset/{patternId}``.

Concurrency: requests run PIPELINED — ingest and device execution of one
request overlap the host finalize of another; only the frequency-coupled
finish phase serializes, on the engine's own ``state_lock`` (shared with
the shim transports and the admin routes). The reference's concurrency
story was an unsynchronized data race on shared pattern objects
(SURVEY.md §5.2) — not a behavior to reproduce.

Overload: ``POST /parse`` admits through the engine-wide
:class:`~log_parser_tpu.serve.admission.AdmissionController` (one gate
shared with the shim transports — docs/OPS.md "Overload & degradation").
A request may carry ``X-Request-Deadline-Ms``; one that would start past
its deadline, or that finds the bounded queue full, is refused with 429 +
``Retry-After``. During drain ``/health/ready`` answers 503 and new parses
get 503.
"""

from __future__ import annotations

import json
import logging
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from log_parser_tpu import _clock as pclock
from log_parser_tpu import native
from log_parser_tpu.models.pod import PodFailureData
from log_parser_tpu.obs import SPANS
from log_parser_tpu.obs.profiler import ProfilerBusy, ProfilerUnavailable
from log_parser_tpu.runtime import faults, pressure
from log_parser_tpu.utils import xlacache
from log_parser_tpu.runtime.engine import AnalysisEngine
from log_parser_tpu.runtime.quarantine import QuarantineRejected
from log_parser_tpu.runtime.tenancy import (
    TenantError,
    TenantForwarded,
    TenantRegistry,
)
from log_parser_tpu.serve.admission import AdmissionRejected, shared_gate

log = logging.getLogger(__name__)

_INVALID = b'{"error":"Invalid PodFailureData provided"}'
# admin bodies (/patterns/reload, /frequency/restore) are operator input,
# not parse traffic — bound them so a runaway payload cannot balloon the
# process before validation even starts
_ADMIN_MAX_BODY = 4 << 20
# a migration bundle carries a whole tenant's folded state (frequency
# ages + parked candidates + session windows) — bounded by the same cap
# the frequency WAL puts on one record
_MIGRATE_MAX_BODY = 64 << 20
_TOO_LARGE = b'{"error":"payload too large"}'


class ParseServer(ThreadingHTTPServer):
    daemon_threads = True
    # socketserver's default listen backlog is 5; a synchronized burst
    # (the micro-batching client pattern) can overflow it and get
    # connection-refused before admission control ever sees the request
    request_queue_size = 128

    def __init__(
        self,
        address: tuple[str, int],
        engine: AnalysisEngine,
        tenants: TenantRegistry | None = None,
    ):
        super().__init__(address, _Handler)
        self.engine = engine
        # the engine's own state lock: admin routes and the analyze finish
        # phase serialize on ONE lock across every transport (HTTP + shim)
        self.analyze_lock = engine.state_lock
        # ... and the engine's one admission gate, shared the same way
        self.admission = shared_gate(engine)
        # tenant resolution (X-Tenant header → TenantContext). Always
        # present: without --tenant-root only the default tenant resolves
        # and non-default ids answer 404, so single-tenant deployments
        # keep their exact pre-tenancy behavior.
        self.tenants = (
            tenants
            if tenants is not None
            else TenantRegistry(engine, gate=self.admission)
        )
        # observability plane (log_parser_tpu/obs): one bundle, rooted at
        # the engine, shared by every transport and tenant engine
        self.obs = engine.obs
        # hot pattern reload (runtime/reload.py): set by serve/__main__.py
        # (or lazily on the first POST /patterns/reload); the watcher is
        # the optional --watch-patterns poller, stopped with the server
        self.reloader = None
        self.watcher = None
        # streaming follow-mode sessions (runtime/stream.py): lazily
        # created on the first POST /parse/stream; serve/__main__.py
        # flips stream_enabled off for sharded/distributed engines (the
        # session layer's residual program is the single-device cube,
        # same gate as --batching / --line-cache-mb)
        self.stream_manager = None
        self.stream_enabled = True
        self._stream_lock = threading.Lock()
        # tenant migration + drain (runtime/migrate.py): wired by
        # serve/__main__.py when --state-dir is set; None answers the
        # admin routes with 501
        self.migrator = None
        self.drain_supervisor = None
        # warm-standby replication (runtime/replicate.py): wired by
        # serve/__main__.py when --replica-target/--replica-of is set;
        # None answers /admin/replica/feed and /admin/promote with 501
        self.replicator = None

    @property
    def dropped_responses(self) -> int:
        """Responses we failed to write because the client had already
        gone away (GET /trace/last "droppedResponses") — a view over the
        registry's cross-transport drop counter, not a second tally."""
        return self.obs.dropped_responses

    def get_reloader(self):
        from log_parser_tpu.runtime.reload import PatternReloader

        if self.reloader is None:
            self.reloader = PatternReloader(self.engine)
        return self.reloader

    def get_stream_manager(self, ctx=None):
        """The stream manager for ``ctx``'s engine (default engine when
        ``ctx`` is None). ONE manager per engine across transports — a
        gRPC StreamParse session and an HTTP one share the registry, the
        admission budget, and the /trace/last counters; each tenant gets
        its own manager so sessions pin to that tenant's bank epoch."""
        if not self.stream_enabled:
            return None
        engine = self.engine if ctx is None else ctx.engine
        with self._stream_lock:
            from log_parser_tpu.runtime.stream import shared_manager

            mgr = shared_manager(engine)
            if engine is self.engine:
                self.stream_manager = mgr
            return mgr


class _Handler(BaseHTTPRequestHandler):
    server: ParseServer

    # ------------------------------------------------------------- plumbing

    def log_message(self, fmt: str, *args) -> None:  # route to logging, not stderr
        log.debug("%s " + fmt, self.address_string(), *args)

    def _send_json(
        self, status: int, payload: bytes, headers: dict[str, str] | None = None
    ) -> None:
        self._send_body(status, payload, "application/json", headers)

    def _send_body(
        self,
        status: int,
        payload: bytes,
        content_type: str,
        headers: dict[str, str] | None = None,
    ) -> None:
        try:
            self.send_response(status)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(payload)))
            for key, value in (headers or {}).items():
                self.send_header(key, value)
            self.end_headers()
            self.wfile.write(payload)
        except (BrokenPipeError, ConnectionResetError) as exc:
            # the client hung up first (its own timeout, or a shed it did
            # not wait for). Not a server fault: count it in the shared
            # cross-transport drop counter, keep the worker thread's
            # stderr free of ThreadingHTTPServer's default traceback spew.
            self.server.obs.note_dropped("http")
            log.debug(
                "client %s disconnected before the response: %s",
                self.address_string(),
                exc,
            )
            self.close_connection = True

    def _tenant(self):
        """Resolve this request's ``X-Tenant`` header to its context, or
        answer the error (400 malformed / 404 unknown / 500 on an
        injected resolve fault) and return None. Requests without the
        header run as the default tenant — the engine the server booted
        with — so pre-tenancy clients are untouched.

        The context comes back pinned (eviction-proof); the do_GET /
        do_POST wrappers unpin it when the handler returns."""
        try:
            ctx = self.server.tenants.resolve(self.headers.get("X-Tenant"))
            self._leases.append(ctx)
            return ctx
        except TenantForwarded as exc:
            # post-cutover forward (runtime/migrate.py): the tenant lives
            # elsewhere now. 307 preserves the method+body; Retry-After
            # paces callers that re-resolve through a stale balancer.
            self._send_json(
                exc.status,
                json.dumps(
                    {"error": exc.reason, "location": exc.location}
                ).encode(),
                headers={
                    "Location": exc.location,
                    "Retry-After": str(exc.retry_after_s),
                },
            )
            return None
        except TenantError as exc:
            self._send_json(
                exc.status,
                json.dumps({"error": exc.reason}).encode(),
            )
            return None
        except Exception:
            log.exception("tenant resolution failed")
            self._send_json(
                500, b'{"error":"Internal tenant resolution failure"}'
            )
            return None

    # --------------------------------------------------------------- routes

    def do_POST(self) -> None:
        self._leases: list = []
        try:
            self._route_post()
        finally:
            # the request is answered: release the tenant lease so the
            # context becomes evictable again
            for ctx in self._leases:
                ctx.unpin()

    def do_GET(self) -> None:
        self._leases = []
        try:
            self._route_get()
        finally:
            for ctx in self._leases:
                ctx.unpin()

    def _route_post(self) -> None:
        if self.path == "/parse":
            return self._parse()
        if self.path == "/parse/stream":
            return self._parse_stream()
        if self.path == "/patterns/reload":
            return self._patterns_reload()
        if self.path == "/patterns/mined":
            return self._mined_post()
        if self.path == "/debug/profile":
            return self._debug_profile()
        if self.path == "/admin/migrate":
            return self._admin_migrate()
        if self.path == "/admin/migrate/import":
            return self._admin_migrate_import()
        if self.path == "/admin/migrate/activate":
            return self._admin_migrate_activate()
        if self.path == "/admin/drain":
            return self._admin_drain()
        if self.path == "/admin/replica/feed":
            return self._admin_replica_feed()
        if self.path == "/admin/promote":
            return self._admin_promote()
        if self.path == "/admin/budget":
            return self._admin_budget()
        if self.path == "/frequency/restore":
            bad = b'{"error":"expected {patternId: [ageSeconds >= 0]}"}'
            try:
                length = int(self.headers.get("Content-Length", 0))
                if length > _ADMIN_MAX_BODY:
                    return self._send_json(413, _TOO_LARGE)
                ages = json.loads(self.rfile.read(length) if length else b"{}")
            except ValueError:
                return self._send_json(400, bad)
            # versioned envelope (the GET /frequency/snapshot shape) and
            # the legacy bare mapping both restore; the envelope's epoch
            # is informational — restore is state, not history
            if (
                isinstance(ages, dict)
                and isinstance(ages.get("ages"), dict)
                and set(ages) <= {"ages", "epoch"}
            ):
                ages = ages["ages"]
            # validate the FULL shape before touching state: restore must be
            # all-or-nothing, never partial. Negative ages are future
            # timestamps that never prune — rejected.
            if not isinstance(ages, dict) or not all(
                isinstance(v, list)
                and all(isinstance(a, (int, float)) and a >= 0 for a in v)
                for v in ages.values()
            ):
                return self._send_json(400, bad)
            ctx = self._tenant()
            if ctx is None:
                return
            eng = ctx.engine
            with eng.state_lock:
                # a journal-backed tracker writes a barrier record here: a
                # crash right after this response still recovers the
                # restored state, not the pre-restore tail
                eng.frequency.restore(ages)
            journal = eng.journal
            epoch = 0 if journal is None else journal.epoch
            return self._send_json(
                200,
                json.dumps({"status": "restored", "epoch": epoch}).encode(),
            )
        if self.path == "/frequency/reset":
            ctx = self._tenant()
            if ctx is None:
                return
            with ctx.engine.state_lock:
                ctx.engine.frequency.reset_all_frequencies()
            return self._send_json(200, b'{"status":"reset"}')
        if self.path.startswith("/frequency/reset/"):
            pattern_id = self.path[len("/frequency/reset/") :]
            ctx = self._tenant()
            if ctx is None:
                return
            with ctx.engine.state_lock:
                ctx.engine.frequency.reset_pattern_frequency(pattern_id)
            return self._send_json(200, b'{"status":"reset"}')
        self._send_json(404, b'{"error":"not found"}')

    def _patterns_reload(self) -> None:
        """Canary-gated hot reload (runtime/reload.py). Empty body: re-read
        the configured pattern directory. Non-empty body: inline YAML
        pattern sets. Any build/canary failure is a structured 409 and the
        live engine is untouched — in-flight requests never notice.

        Tenant-scoped: ``X-Tenant`` picks whose library swaps. The quiesce
        runs on that tenant's engine alone, so every other tenant's
        traffic proceeds uninterrupted through the whole ladder."""
        from log_parser_tpu.runtime.reload import ReloadError

        try:
            length = int(self.headers.get("Content-Length", 0))
            if length > _ADMIN_MAX_BODY:
                return self._send_json(413, _TOO_LARGE)
            body = self.rfile.read(length) if length else b""
        except ValueError:
            return self._send_json(400, b'{"error":"bad request body"}')
        try:
            yaml_text = body.decode("utf-8") if body.strip() else None
        except UnicodeDecodeError:
            return self._send_json(400, b'{"error":"body is not UTF-8"}')
        ctx = self._tenant()
        if ctx is None:
            return
        default = ctx.engine is self.server.engine
        reloader = self.server.get_reloader() if default else ctx.reloader()
        try:
            envelope = reloader.reload(yaml_text=yaml_text)
        except ReloadError as exc:
            return self._send_json(409, json.dumps(exc.to_json()).encode())
        except Exception:
            log.exception("pattern reload failed")
            return self._send_json(
                500, b'{"error":"Internal reload failure"}'
            )
        ctx.note_reloaded()
        return self._send_json(200, json.dumps(envelope).encode())

    def _mined_get(self) -> None:
        """``GET /patterns/mined``: the review queue — parked candidates
        (id, template, support, tier; the YAML itself stays on disk) plus
        the miner's live counters. Tenant-scoped: ``X-Tenant`` picks whose
        miner answers; 404 when mining is off for that engine."""
        ctx = self._tenant()
        if ctx is None:
            return
        miner = getattr(ctx.engine, "miner", None)
        if miner is None:
            return self._send_json(404, b'{"error":"miner disabled"}')
        return self._send_json(
            200,
            json.dumps(
                {"pending": miner.pending_list(), "stats": miner.stats()}
            ).encode(),
        )

    def _mined_post(self) -> None:
        """``POST /patterns/mined`` with ``{"id": ..., "action":
        "approve"|"reject"}``. Approve re-runs the FULL admission ladder
        (the curated library may have changed since parking) — a gate
        failure is a structured 409 carrying the rejection reason, and the
        candidate stays parked for triage. Reject discards the parked
        candidate."""
        from log_parser_tpu.mining.admit import Rejection

        bad = b'{"error":"expected {id, action: approve|reject}"}'
        try:
            length = int(self.headers.get("Content-Length", 0))
            if length > _ADMIN_MAX_BODY:
                return self._send_json(413, _TOO_LARGE)
            body = json.loads(self.rfile.read(length) if length else b"{}")
        except ValueError:
            return self._send_json(400, bad)
        if (
            not isinstance(body, dict)
            or not isinstance(body.get("id"), str)
            or body.get("action") not in ("approve", "reject")
        ):
            return self._send_json(400, bad)
        ctx = self._tenant()
        if ctx is None:
            return
        miner = getattr(ctx.engine, "miner", None)
        if miner is None:
            return self._send_json(404, b'{"error":"miner disabled"}')
        if body["action"] == "reject":
            found = miner.discard(body["id"])
            if not found:
                return self._send_json(404, b'{"error":"unknown candidate"}')
            return self._send_json(200, b'{"status":"rejected"}')
        try:
            result = miner.approve(body["id"])
        except KeyError:
            return self._send_json(404, b'{"error":"unknown candidate"}')
        except Rejection as exc:
            return self._send_json(409, json.dumps(exc.to_json()).encode())
        except Exception:
            log.exception("mined-candidate approval failed")
            return self._send_json(
                500, b'{"error":"Internal approval failure"}'
            )
        return self._send_json(200, json.dumps(result).encode())

    # ---------------------------------------------------- migration admin

    def _admin_body(self, max_body: int = _ADMIN_MAX_BODY):
        """Parsed JSON object body for an admin route, or None after
        answering the error."""
        try:
            length = int(self.headers.get("Content-Length", 0))
            if length > max_body:
                self._send_json(413, _TOO_LARGE)
                return None
            body = json.loads(self.rfile.read(length) if length else b"{}")
        except ValueError:
            self._send_json(400, b'{"error":"bad request body"}')
            return None
        if not isinstance(body, dict):
            self._send_json(400, b'{"error":"expected a JSON object"}')
            return None
        return body

    def _admin_budget(self) -> None:
        """``POST /admin/budget`` ``{"lineCacheMb": x, "tenantBudgetMb":
        y}``: apply a fleet-arbitrated budget share live — the router's
        arbiter (fleet/budget.py) replaces the process-local
        ``--line-cache-mb`` / ``--tenant-budget-mb`` constants with
        these pushes. Shrinking evicts down immediately."""
        body = self._admin_body()
        if body is None:
            return
        line_mb = body.get("lineCacheMb")
        tenant_mb = body.get("tenantBudgetMb")
        if line_mb is None and tenant_mb is None:
            return self._send_json(
                400,
                b'{"error":"expected {lineCacheMb and/or tenantBudgetMb}"}',
            )
        applied = {}
        try:
            if line_mb is not None:
                line_mb = max(0.0, float(line_mb))
                self.server.tenants.set_line_cache_budget(
                    int(line_mb * 1024 * 1024)
                )
                applied["lineCacheMb"] = line_mb
            if tenant_mb is not None:
                tenant_mb = max(0.0, float(tenant_mb))
                self.server.tenants.set_budget_mb(tenant_mb)
                applied["tenantBudgetMb"] = tenant_mb
        except (TypeError, ValueError):
            return self._send_json(
                400, b'{"error":"budgets must be numbers"}'
            )
        return self._send_json(200, json.dumps(applied).encode())

    def _require_migrator(self):
        mig = self.server.migrator
        if mig is None:
            self._send_json(
                501,
                b'{"error":"migration is not enabled (serve with '
                b'--state-dir)"}',
            )
        return mig

    def _require_replication(self):
        rep = self.server.replicator
        if rep is None:
            self._send_json(
                501,
                b'{"error":"replication is not enabled (serve with '
                b'--state-dir and --replica-target/--replica-of)"}',
            )
        return rep

    def _admin_replica_feed(self) -> None:
        """``POST /admin/replica/feed``: one shipped WAL batch from the
        primary — a snapshot barrier, or base64 CRC-framed records at
        the tenant's acked offset. Verified and applied whole, or
        refused with the receiver's position so the sender re-syncs;
        a refused batch never moves the acked offset."""
        from log_parser_tpu.runtime.replicate import ReplicationError

        rep = self._require_replication()
        if rep is None:
            return
        body = self._admin_body(max_body=_MIGRATE_MAX_BODY)
        if body is None:
            return
        try:
            ack = rep.feed(body)
        except ReplicationError as exc:
            return self._send_json(
                exc.status if exc.status else 503,
                json.dumps(exc.to_json()).encode(),
            )
        except Exception:
            log.exception("replica feed failed")
            return self._send_json(
                500, b'{"error":"Internal replication failure"}'
            )
        return self._send_json(200, json.dumps(ack).encode())

    def _admin_promote(self) -> None:
        """``POST /admin/promote`` ``{["reason": text]}``: manual
        failover — journal PROMOTE(epoch+1), activate every replicated
        tenant, lift the fence. Idempotent on an already-primary
        process; the abandoned primary demotes itself the moment it
        sees the higher epoch."""
        from log_parser_tpu.runtime.replicate import ReplicationError

        rep = self._require_replication()
        if rep is None:
            return
        body = self._admin_body()
        if body is None:
            return
        reason = body.get("reason")
        try:
            summary = rep.promote(
                reason=str(reason) if isinstance(reason, str) and reason
                else "admin"
            )
        except ReplicationError as exc:
            return self._send_json(
                exc.status if exc.status else 503,
                json.dumps(exc.to_json()).encode(),
            )
        except Exception:
            log.exception("promotion failed")
            return self._send_json(
                500, b'{"error":"Internal replication failure"}'
            )
        return self._send_json(200, json.dumps(summary).encode())

    def _admin_migrate(self) -> None:
        """``POST /admin/migrate`` ``{"tenant": id, "target": url[,
        "retryAfterS": n]}``: run the full source side of the migration
        protocol against the target process's import endpoints. Blocks
        until CUTOVER+COMPLETE (or a pre-cutover abort, answered as a
        structured 4xx/5xx with the tenant still owned here)."""
        from log_parser_tpu.runtime.migrate import HttpTarget, MigrationError

        mig = self._require_migrator()
        if mig is None:
            return
        body = self._admin_body()
        if body is None:
            return
        tenant = body.get("tenant")
        target = body.get("target")
        if not isinstance(tenant, str) or not isinstance(target, str):
            return self._send_json(
                400, b'{"error":"expected {tenant, target}"}'
            )
        try:
            retry_after = int(body.get("retryAfterS", 5))
        except (TypeError, ValueError):
            return self._send_json(400, b'{"error":"bad retryAfterS"}')
        try:
            summary = mig.migrate(
                tenant, HttpTarget(target), retry_after_s=retry_after
            )
        except MigrationError as exc:
            return self._send_json(
                exc.status, json.dumps({"error": exc.reason}).encode()
            )
        except Exception:
            log.exception("migration of %r failed", tenant)
            return self._send_json(
                500, b'{"error":"Internal migration failure"}'
            )
        return self._send_json(200, json.dumps(summary).encode())

    def _admin_migrate_import(self) -> None:
        """``POST /admin/migrate/import`` ``{"bundle": {...}, "sha":
        hex}``: the target half's STAGE step — verify + warm-build +
        persist, ack with the sha. Nothing is applied until activate."""
        from log_parser_tpu.runtime.migrate import MigrationError

        mig = self._require_migrator()
        if mig is None:
            return
        body = self._admin_body(max_body=_MIGRATE_MAX_BODY)
        if body is None:
            return
        bundle = body.get("bundle")
        sha = body.get("sha")
        if not isinstance(bundle, dict) or not isinstance(sha, str):
            return self._send_json(
                400, b'{"error":"expected {bundle, sha}"}'
            )
        try:
            ack = mig.stage_import(bundle, sha)
        except MigrationError as exc:
            return self._send_json(
                exc.status, json.dumps({"error": exc.reason}).encode()
            )
        except Exception:
            log.exception("migration import failed")
            return self._send_json(
                500, b'{"error":"Internal import failure"}'
            )
        return self._send_json(200, json.dumps(ack).encode())

    def _admin_migrate_activate(self) -> None:
        """``POST /admin/migrate/activate`` ``{"mid": id}``: apply a
        staged import (the source's CUTOVER is durable by the time it
        calls this)."""
        from log_parser_tpu.runtime.migrate import MigrationError

        mig = self._require_migrator()
        if mig is None:
            return
        body = self._admin_body()
        if body is None:
            return
        mid = body.get("mid")
        if not isinstance(mid, str) or not mid:
            return self._send_json(400, b'{"error":"expected {mid}"}')
        try:
            summary = mig.activate(mid)
        except MigrationError as exc:
            return self._send_json(
                exc.status, json.dumps({"error": exc.reason}).encode()
            )
        except Exception:
            log.exception("migration activate failed")
            return self._send_json(
                500, b'{"error":"Internal activate failure"}'
            )
        return self._send_json(200, json.dumps(summary).encode())

    def _admin_drain(self) -> None:
        """``POST /admin/drain``: run one drain-supervisor pass — flip
        admission (readiness 503), migrate every resident tenant to the
        configured ``--drain-target`` under ``--drain-deadline-s``
        (bounded local close when there is no target), finalize every
        engine. Blocks until the pass completes and returns its summary;
        the process keeps running (SIGTERM drains AND exits)."""
        sup = self.server.drain_supervisor
        if sup is None:
            return self._send_json(
                501, b'{"error":"drain supervisor is not enabled"}'
            )
        try:
            summary = sup.drain(reason="admin")
        except Exception:
            log.exception("drain failed")
            return self._send_json(500, b'{"error":"Internal drain failure"}')
        return self._send_json(200, json.dumps(summary).encode())

    def _route_get(self) -> None:
        if self.path in ("/health", "/health/live", "/health/ready", "/q/health"):
            # draining: readiness fails (load balancers stop sending) but
            # liveness holds — in-flight work is still finishing
            if self.path == "/health/ready" and self.server.admission.draining:
                return self._send_json(
                    503,
                    b'{"status":"DOWN","checks":[{"name":"draining",'
                    b'"status":"DOWN"}]}',
                )
            # still UP while degraded — requests serve from the host path
            # (circuit open) or the coordinator's local devices (follower
            # group dead) — but the degradation is visible to probes
            checks = []
            sup = self.server.drain_supervisor
            if (sup is not None and sup.draining) or (
                self.server.admission.draining
            ):
                # the drain supervisor is evacuating this process: the
                # aggregated probe reports a DRAINING check, and answers
                # ready-503 so load balancers stop routing here while
                # in-flight migrations finish. Liveness (/health,
                # /health/live) holds throughout — killing a draining
                # process forfeits the handoff.
                checks.append({"name": "drain", "status": "DRAINING"})
                if self.path == "/q/health":
                    return self._send_json(
                        503,
                        json.dumps(
                            {"status": "DRAINING", "checks": checks}
                        ).encode(),
                    )
            if self.server.engine.watchdog.circuit_open:
                checks.append({"name": "device", "status": "DEGRADED"})
            mesh = getattr(self.server.engine, "mesh_health", None)
            if mesh is not None and mesh.degraded:
                checks.append({"name": "mesh", "status": "DEGRADED"})
            journal = self.server.engine.journal
            if journal is not None and not journal.healthy:
                # requests still serve, but frequency durability is gone:
                # a crash now loses the un-journaled tail
                checks.append({"name": "journal", "status": "DEGRADED"})
            if self.server.engine.breakers.any_active():
                # shadow verification caught a device-vs-golden divergence:
                # the divergent pattern(s) serve from the host regex until
                # a clean half-open probe (docs/OPS.md "Shadow divergence")
                checks.append({"name": "shadow", "status": "DEGRADED"})
            rep = self.server.replicator
            if rep is not None and rep.role == "standby":
                # informational, not DOWN: a standby is healthy but fenced
                # — client traffic 307s to the owner while feeds apply.
                # The failover supervisor on the OTHER side probes this
                # same endpoint, which must stay 200 while we are alive.
                checks.append({
                    "name": "replication", "status": "STANDBY",
                    "epoch": rep.epoch,
                })
            ctl = pressure.current()
            if ctl is not None:
                pc = ctl.health_check()
                if pc["status"] != "UP":
                    # resource pressure (disk/memory ladder off ``ok``):
                    # still a 200 — the ladder's whole contract is that
                    # the serving path keeps answering while degraded
                    # (docs/OPS.md "Resource exhaustion")
                    checks.append(pc)
            slo = self.server.obs.slo.health()
            if slo is not None and slo["status"] != "UP":
                # SLO burn: an objective is spending its error budget
                # faster than the threshold on every configured window
                # (docs/OPS.md "Observability" — SLO runbook)
                checks.append(slo)
            if checks:
                return self._send_json(
                    200, json.dumps({"status": "UP", "checks": checks}).encode()
                )
            return self._send_json(200, b'{"status":"UP"}')
        if self.path == "/frequency/stats":
            ctx = self._tenant()
            if ctx is None:
                return
            with ctx.engine.state_lock:
                stats = ctx.engine.frequency.get_frequency_statistics()
            return self._send_json(200, json.dumps(stats).encode())
        if self.path == "/frequency/snapshot":
            ctx = self._tenant()
            if ctx is None:
                return
            with ctx.engine.state_lock:
                snap = ctx.engine.frequency.snapshot()
            journal = ctx.engine.journal
            epoch = 0 if journal is None else journal.epoch
            # versioned envelope; POST /frequency/restore accepts it as-is
            return self._send_json(
                200, json.dumps({"epoch": epoch, "ages": snap}).encode()
            )
        if self.path == "/patterns/mined":
            return self._mined_get()
        if self.path == "/trace/last":
            trace = self.server.engine.last_trace
            payload = {"phasesMs": {}, "totalMs": 0.0} if trace is None else {
                "phasesMs": {k: v * 1e3 for k, v in trace.as_dict().items()},
                "totalMs": trace.total * 1e3,
            }
            payload["fallbackCount"] = self.server.engine.fallback_count
            payload["hostRoutedCount"] = self.server.engine.host_routed_count
            payload["deviceCircuitOpen"] = (
                self.server.engine.watchdog.circuit_open
            )
            # a view over the registry's cross-transport drop counter
            payload["droppedResponses"] = self.server.dropped_responses
            payload["admission"] = self.server.admission.stats()
            # trace-ring occupancy (GET /trace/recent reads the entries)
            payload["traceRing"] = self.server.obs.ring.stats()
            # causal span store occupancy (GET /trace/spans reads the
            # trees; docs/OPS.md "Span tracing & utilization accounting")
            payload["spans"] = self.server.obs.spans.stats()
            batcher = getattr(self.server.engine, "batcher", None)
            if batcher is not None:
                # queue depth, batch sizes, flush reasons (docs/OPS.md
                # "Micro-batching")
                payload["batcher"] = batcher.stats()
            line_cache = getattr(self.server.engine, "line_cache", None)
            if line_cache is not None:
                # routing-tier hit/residual/eviction counters (docs/OPS.md
                # "Line cache (routing tier)")
                payload["lineCache"] = line_cache.stats()
            interner = getattr(self.server.engine, "key_interner", None)
            if interner is not None:
                # two-level keying: probe hits are digests served without
                # blake2b (docs/OPS.md "Line cache (routing tier)")
                payload["interner"] = interner.stats()
            kernel_stats = getattr(self.server.engine, "kernel_stats", None)
            if kernel_stats is not None:
                # Pallas union-DFA kernel tier: admission reason +
                # per-dispatch routing counters (docs/OPS.md "Kernel tier")
                payload["kernel"] = kernel_stats.stats()
            mesh = getattr(self.server.engine, "mesh_health", None)
            if mesh is not None:
                # follower liveness + degrade-to-local counters
                # (docs/OPS.md "Distributed failure modes")
                payload["distributed"] = mesh.stats()
            journal = self.server.engine.journal
            if journal is not None:
                # WAL/snapshot counters (docs/OPS.md "State durability")
                payload["journal"] = journal.stats()
            stream_mgr = self.server.stream_manager
            if stream_mgr is not None:
                # follow-mode session counters (docs/OPS.md "Streaming
                # follow-mode")
                payload["stream"] = stream_mgr.stats()
            # which ingest path this process runs, and why the native
            # scanner refused to load when it did (docs/OPS.md "Which
            # ingest am I running?")
            payload["native"] = native.stats()
            # persistent XLA compile cache wiring + hit/miss tally
            # (docs/OPS.md "Compile cache")
            payload["compileCache"] = xlacache.stats()
            # poison-request ledger (docs/OPS.md "Poison-request triage")
            payload["quarantine"] = self.server.engine.quarantine.stats()
            miner = getattr(self.server.engine, "miner", None)
            if miner is not None:
                # template-miner loop: tap/cluster/admission counters
                # (docs/OPS.md "Template miner")
                payload["miner"] = miner.stats()
            shadow = getattr(self.server.engine, "shadow", None)
            if shadow is not None:
                # online device-vs-golden verification + per-pattern
                # breakers (docs/OPS.md "Shadow divergence")
                payload["shadow"] = shadow.stats()
            payload["reload"] = {
                "epoch": self.server.engine.reload_epoch,
                "count": self.server.engine.reload_count,
                "failures": self.server.engine.reload_failures,
                "lastError": self.server.engine.last_reload_error,
            }
            last_lint = getattr(self.server.engine, "last_lint", None)
            if last_lint is not None:
                # static-analysis summary of the most recent reload
                # candidate (docs/OPS.md "Lint-blocked reload")
                payload["lint"] = last_lint
            # tenant residency/quota counters (docs/OPS.md "Multi-tenant
            # serving")
            payload["tenants"] = self.server.tenants.stats()
            migrator = self.server.migrator
            if migrator is not None:
                # migration protocol + drain counters (docs/OPS.md
                # "Tenant migration & drain")
                mig_stats = migrator.stats()
                sup = self.server.drain_supervisor
                if sup is not None:
                    mig_stats["drain"] = sup.stats()
                payload["migration"] = mig_stats
            replicator = self.server.replicator
            if replicator is not None:
                # replication channel + failover position (docs/OPS.md
                # "Warm-standby replication")
                payload["replication"] = replicator.stats()
            ctl = pressure.current()
            if ctl is not None:
                # resource-pressure ladder, levers and retry budget
                # (docs/OPS.md "Resource exhaustion")
                payload["pressure"] = ctl.stats()
            fault_stats = faults.stats()
            if fault_stats is not None:
                payload["faults"] = fault_stats
            return self._send_json(200, json.dumps(payload).encode())
        if self.path == "/metrics":
            # Prometheus text exposition: owned hot-path instruments plus
            # scrape-time collectors over every subsystem's stats() — the
            # same variables /trace/last reads (docs/OPS.md
            # "Observability")
            return self._send_body(
                200,
                self.server.obs.registry.render().encode(),
                "text/plain; version=0.0.4; charset=utf-8",
            )
        if self.path.startswith("/trace/recent"):
            query = urllib.parse.urlparse(self.path).query
            params = urllib.parse.parse_qs(query)
            try:
                n = int(params.get("n", ["50"])[0])
            except ValueError:
                return self._send_json(400, b'{"error":"n must be an integer"}')
            ring = self.server.obs.ring
            return self._send_json(200, json.dumps({
                "requests": ring.recent(n),
                "slow": ring.slow_recent(n),
                "ring": ring.stats(),
            }).encode())
        if self.path.startswith("/trace/spans"):
            # self-contained causal trees: request -> flush(link) ->
            # dispatch -> finalize, plus session/tenancy lifecycles
            # (docs/OPS.md "Span tracing & utilization accounting")
            query = urllib.parse.urlparse(self.path).query
            params = urllib.parse.parse_qs(query)
            try:
                n = int(params.get("n", ["50"])[0])
            except ValueError:
                return self._send_json(400, b'{"error":"n must be an integer"}')
            spans = self.server.obs.spans
            return self._send_json(200, json.dumps({
                "traces": spans.traces(n),
                "store": spans.stats(),
                "vocabulary": sorted(SPANS),
            }).encode())
        if self.path == "/debug/factors":
            fin = self.server.engine.last_finalized
            rows = [] if fin is None else fin.factor_rows(self.server.engine.bank)
            return self._send_json(200, json.dumps(rows).encode())
        self._send_json(404, b'{"error":"not found"}')

    def _parse_stream(self) -> None:
        """``POST /parse/stream``: chunked follow-mode ingestion. Each HTTP
        request chunk (``Transfer-Encoding: chunked``, hand-decoded — the
        stdlib handler never decodes request bodies) is one session chunk;
        the response is NDJSON frames (``emit`` / ``revised`` / ``final`` /
        ``error``, runtime/stream.py FRAME_TYPES) written full-duplex as
        chunks arrive, so time-to-first-detection is one chunk deep, not
        one blob deep. The zero-size chunk closes the session; the final
        frame's result is bit-identical to one-shot ``POST /parse`` on the
        concatenated body. A fixed-length body is treated as a single
        chunk + close."""
        try:
            faults.fire("http")
        except Exception:
            log.exception("injected HTTP-transport fault")
            return self._send_json(500, b'{"error":"Internal analysis failure"}')
        ctx = self._tenant()
        if ctx is None:
            return
        mgr = self.server.get_stream_manager(ctx)
        if mgr is None:
            return self._send_json(
                501, b'{"error":"streaming is not supported on this engine"}'
            )
        deadline_ms = None
        header = self.headers.get("X-Request-Deadline-Ms")
        if header is not None:
            try:
                deadline_ms = float(header)
            except ValueError:
                return self._send_json(
                    400, b'{"error":"invalid X-Request-Deadline-Ms"}'
                )
        try:
            sess = mgr.open(deadline_ms)
        except AdmissionRejected as exc:
            return self._send_json(
                exc.status,
                json.dumps({"error": "overloaded", "reason": exc.reason}).encode(),
                headers={"Retry-After": str(exc.retry_after_s)},
            )

        def _write(frames: list[dict]) -> None:
            for frame in frames:
                self.wfile.write(json.dumps(frame).encode() + b"\n")
            self.wfile.flush()

        chunked = "chunked" in (
            self.headers.get("Transfer-Encoding") or ""
        ).lower()
        try:
            self.send_response(200)
            self.send_header("Content-Type", "application/x-ndjson")
            self.send_header("Connection", "close")
            self.end_headers()
            if chunked:
                while not sess.closed:
                    size_line = self.rfile.readline(130)
                    try:
                        size = int(size_line.split(b";")[0].strip() or b"x", 16)
                    except ValueError:
                        # garbage framing: a structured error frame, never
                        # a wedged session or a half-open connection
                        _write(
                            [
                                {
                                    "type": "error",
                                    "session": sess.session_id,
                                    "reason": "bad-frame",
                                    "message": "malformed chunk size line",
                                }
                            ]
                        )
                        sess.kill("bad-frame")
                        break
                    if size == 0:
                        while self.rfile.readline(130).strip():
                            pass  # discard trailers
                        _write(sess.close())
                        break
                    data = self.rfile.read(size)
                    self.rfile.read(2)  # chunk CRLF
                    _write(sess.feed(data))
            else:
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length) if length else b""
                _write(sess.feed(body))
                if not sess.closed:
                    _write(sess.close())
        except (BrokenPipeError, ConnectionResetError) as exc:
            self.server.obs.note_dropped("http")
            log.debug(
                "stream client %s disconnected: %s", self.address_string(), exc
            )
        except Exception:
            log.exception("stream session %s failed", sess.session_id)
        finally:
            if not sess.closed:
                sess.kill("transport")
            self.close_connection = True

    def _debug_profile(self) -> None:
        # on-demand jax.profiler capture: {"seconds": N} -> 202 with the
        # capture directory; single-flight, so a concurrent start is a 409
        try:
            length = int(self.headers.get("Content-Length", 0))
            if length > _ADMIN_MAX_BODY:
                return self._send_json(413, _TOO_LARGE)
            payload = json.loads(self.rfile.read(length) if length else b"{}")
            seconds = float(payload.get("seconds", 5)) if isinstance(
                payload, dict
            ) else None
        except (ValueError, TypeError):
            seconds = None
        if seconds is None:
            return self._send_json(
                400, b'{"error":"expected {\\"seconds\\": N}"}'
            )
        try:
            capture_dir = self.server.obs.profiler.start(seconds)
        except ProfilerBusy as exc:
            return self._send_json(
                409, json.dumps({"error": str(exc)}).encode()
            )
        except ProfilerUnavailable as exc:
            return self._send_json(
                503, json.dumps({"error": str(exc)}).encode()
            )
        except ValueError as exc:
            return self._send_json(
                400, json.dumps({"error": str(exc)}).encode()
            )
        return self._send_json(
            202,
            json.dumps(
                {"status": "capturing", "seconds": seconds, "dir": capture_dir}
            ).encode(),
        )

    def _parse(self) -> None:
        obs = self.server.obs
        # honor a caller-supplied correlation id, mint one otherwise; the
        # same id is echoed back and threaded through admission -> batcher
        # flush -> device dispatch so /trace/recent can stitch the hops
        rid = obs.clean_request_id(self.headers.get("X-Request-Id"))
        if rid is None:
            rid = obs.new_request_id()
        started = pclock.mono()
        tenant = "default"
        route = "device"

        def reply(status, body, *, detail=None, headers=None):
            hdrs = dict(headers) if headers else {}
            hdrs["X-Request-Id"] = rid
            obs.note_request(
                "http",
                route,
                status,
                tenant,
                pclock.mono() - started,
                request_id=rid,
                detail=detail,
            )
            return self._send_json(status, body, headers=hdrs)

        try:
            faults.fire("http")
        except Exception:
            log.exception("injected HTTP-transport fault")
            return reply(
                500, b'{"error":"Internal analysis failure"}', detail="fault"
            )
        try:
            length = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(length) if length else b""
            payload = json.loads(body) if body else None
        except (ValueError, json.JSONDecodeError):
            return reply(400, _INVALID, detail="invalid body")

        data = PodFailureData.from_dict(payload) if isinstance(payload, dict) else None
        # Parse.java:45-49 — null data or null pod is a 400
        if data is None or data.pod is None:
            return reply(400, _INVALID, detail="invalid body")

        deadline_ms = None  # None -> the gate's configured default
        header = self.headers.get("X-Request-Deadline-Ms")
        if header is not None:
            try:
                deadline_ms = float(header)
            except ValueError:
                return reply(
                    400,
                    b'{"error":"invalid X-Request-Deadline-Ms"}',
                    detail="invalid deadline",
                )

        ctx = self._tenant()
        if ctx is None:
            return
        tenant = ctx.tenant_id
        engine = ctx.engine
        batcher = getattr(engine, "batcher", None)
        n_lines = (data.logs.count("\n") + 1) if data.logs else 0
        arrival = pclock.mono()
        try:
            route = self.server.admission.acquire(
                deadline_ms,
                batchable=batcher is not None,
                tenant=ctx.quota,
                lines=n_lines,
            )
        except AdmissionRejected as exc:
            # shed (429) or draining (503): tell the client when it is
            # worth coming back. A futile shed (413 `tenant burst` — the
            # request exceeds the bucket's whole capacity) carries NO
            # Retry-After: the same request can never be admitted.
            # the staged admission child attaches when reply()'s
            # note_request commits this shed request's trace
            obs.spans.annotate(
                rid, "admission", pclock.mono() - arrival,
                attrs={"verdict": exc.reason, "tenant": tenant},
            )
            route = "admission"
            return reply(
                exc.status,
                json.dumps({"error": "overloaded", "reason": exc.reason}).encode(),
                detail=exc.reason,
                headers=(
                    {"Retry-After": str(exc.retry_after_s)}
                    if exc.retry_after_s > 0
                    else None
                ),
            )
        obs.spans.annotate(
            rid, "admission", pclock.mono() - arrival,
            attrs={"verdict": route, "tenant": tenant},
        )
        try:
            log.info("Received analysis request for pod: %s", data.pod_name)
            try:
                if route == "host":
                    # ladder rung 2: device slots saturated, this request
                    # queued — serve it from the cheaper golden host path
                    result = engine.analyze_host_routed(data, request_id=rid)
                elif batcher is not None:
                    # micro-batching on: this request ("device" or
                    # queued-then-"batched") coalesces with concurrent
                    # arrivals into one shared device batch. Pass the
                    # REMAINING deadline budget — time already burned
                    # waiting for admission must pull the flush earlier.
                    route = "batched"  # the metrics label matches the ring
                    effective = (
                        deadline_ms
                        if deadline_ms is not None
                        else (self.server.admission.default_deadline_ms or None)
                    )
                    if effective is not None:
                        effective -= (pclock.mono() - arrival) * 1e3
                    result = engine.analyze_batched(
                        data, effective, request_id=rid
                    )
                else:
                    # pipelined: ingest + device work of this request
                    # overlaps the host finalize of in-flight ones; only
                    # the frequency-coupled finish phase serializes (on
                    # engine.state_lock)
                    result = engine.analyze_pipelined(data, request_id=rid)
            except QuarantineRejected as exc:
                # a quarantined fingerprint the golden host path could not
                # serve either — structured 429, try again after the TTL
                return reply(
                    exc.status,
                    json.dumps(
                        {
                            "error": "quarantined",
                            "reason": exc.reason,
                            "fingerprint": exc.fingerprint,
                        }
                    ).encode(),
                    detail="quarantined",
                    headers={"Retry-After": str(exc.retry_after_s)},
                )
            except Exception:
                # non-device bugs propagate out of analyze() by design
                # (runtime/engine.py is_device_error) — answer with a JSON
                # 500 instead of dropping the connection mid-request
                log.exception("Analysis failed for pod: %s", data.pod_name)
                return reply(
                    500, b'{"error":"Internal analysis failure"}', detail="error"
                )
        finally:
            self.server.admission.release(tenant=ctx.quota)
        log.info(
            "Analysis complete for pod: %s. Found %d significant events.",
            data.pod_name,
            result.summary.significant_events if result.summary else 0,
        )
        # pressure.stamp marks the envelope ``durability: degraded``
        # while the disk ladder is hard — its absence is a promise that
        # this response's frequency updates ride an fsync'd journal
        reply(200, json.dumps(
            pressure.stamp(result.to_dict(drop_none=True))
        ).encode())


def make_server(
    engine: AnalysisEngine,
    host: str = "0.0.0.0",
    port: int = 8080,
    tenants: TenantRegistry | None = None,
) -> ParseServer:
    return ParseServer((host, port), engine, tenants=tenants)
