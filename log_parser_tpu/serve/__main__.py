"""CLI entry: ``python -m log_parser_tpu.serve --pattern-dir /shared/patterns``.

Mirrors the reference's boot sequence: load the pattern directory at startup
(PatternService @PostConstruct, PatternService.java:45-69), then serve
``POST /parse`` on :8080 (Dockerfile.native:28). Config comes from a Java
``.properties`` file (``--config``), environment variables (MicroProfile
convention), or flags — flags win.
"""

from __future__ import annotations

import argparse
import dataclasses
import logging
import os
import sys

from log_parser_tpu.config import ScoringConfig
from log_parser_tpu.patterns import load_pattern_directory
from log_parser_tpu.runtime import AnalysisEngine
from log_parser_tpu.serve.admission import install_drain_handlers
from log_parser_tpu.serve.http import make_server


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="log_parser_tpu.serve")
    parser.add_argument("--pattern-dir", help="pattern YAML directory (pattern.directory)")
    parser.add_argument("--config", help="Java .properties config file")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=8080)
    parser.add_argument("--log-level", default="INFO")
    # fleet router front-door (docs/OPS.md "Fleet routing & placement")
    parser.add_argument(
        "--role", default="serve", choices=("serve", "router"),
        help="'serve' boots the engine process (default); 'router' boots "
        "the fleet front-door instead: no engine, no patterns — requests "
        "are proxied to --backends by consistent hashing on the tenant id "
        "(log_parser_tpu/fleet/)",
    )
    parser.add_argument(
        "--backends", default=None, metavar="HOST:PORT,...",
        help="router mode: comma-separated backend serving processes "
        "(HTTP base addresses) forming the consistent-hash ring",
    )
    parser.add_argument(
        "--backends-shim", default=None, metavar="HOST:PORT,...",
        help="router mode: the framed-shim address of each --backends "
        "entry (same order); enables the router's framed front on "
        "--shim-port",
    )
    parser.add_argument(
        "--shim-port", type=int, default=None, metavar="PORT",
        help="router mode: listen port for the framed Envelope front-door "
        "(requires --backends-shim)",
    )
    parser.add_argument(
        "--grpc-port", type=int, default=None, metavar="PORT",
        help="router mode: listen port for the gRPC front-door, proxied "
        "over the framed back-channel (requires --backends-shim; "
        "disabled when grpcio is absent)",
    )
    parser.add_argument(
        "--fleet-vnodes", type=int, default=64,
        help="virtual nodes per backend on the consistent-hash ring "
        "(router mode; default 64)",
    )
    parser.add_argument(
        "--fleet-down-after", type=int, default=2,
        help="consecutive probe/proxy failures before a backend leaves "
        "the ring; it re-joins on the first healthy probe (router mode)",
    )
    parser.add_argument(
        "--fleet-poll-s", type=float, default=2.0, metavar="SECONDS",
        help="placement control-loop poll interval over backend "
        "/q/health + /metrics (router mode; fleet/placement.py)",
    )
    parser.add_argument(
        "--fleet-burn-polls", type=int, default=3,
        help="consecutive polls with SLO burn rate > 1 before the placer "
        "moves the backend's hottest tenant (router mode)",
    )
    parser.add_argument(
        "--fleet-shed-rate", type=float, default=1.0, metavar="PER_S",
        help="per-tenant 429/503 rate that triggers a live move of that "
        "tenant; 0 is never reached in practice (router mode)",
    )
    parser.add_argument(
        "--fleet-thrash-rebuilds", type=int, default=3,
        help="tenant-engine rebuilds within one poll window that count "
        "as residency thrash and trigger a move (router mode)",
    )
    parser.add_argument(
        "--fleet-move-cooldown-s", type=float, default=30.0,
        metavar="SECONDS",
        help="minimum seconds between placer-initiated moves of the SAME "
        "tenant, so a flapping signal cannot ping-pong it (router mode)",
    )
    parser.add_argument(
        "--fleet-cache-mb", type=float, default=0.0, metavar="MB",
        help="fleet-wide line-cache budget arbitrated across backends "
        "from observed traffic, pushed via POST /admin/budget — replaces "
        "per-process --line-cache-mb; 0 disables (router mode)",
    )
    parser.add_argument(
        "--fleet-tenant-budget-mb", type=float, default=0.0, metavar="MB",
        help="fleet-wide tenant-residency budget arbitrated across "
        "backends from observed traffic — replaces per-process "
        "--tenant-budget-mb; 0 disables (router mode)",
    )
    parser.add_argument(
        "--sharded",
        action="store_true",
        help="shard the line batch over every visible device (jax mesh)",
    )
    # multi-process (DCN) scale-out: one mesh spanning processes. Process 0
    # serves HTTP and broadcasts each request; the rest follow
    # (parallel/distributed.py; SURVEY.md §5.8).
    parser.add_argument(
        "--coordinator",
        help="host:port of the jax.distributed coordinator (enables "
        "multi-process mode; implies --sharded)",
    )
    parser.add_argument("--num-processes", type=int, default=None)
    parser.add_argument("--process-id", type=int, default=None)
    # distributed resilience (docs/OPS.md "Distributed failure modes")
    parser.add_argument(
        "--broadcast-timeout", type=float, default=None, metavar="SECONDS",
        help="deadline per coordinator→follower dispatch attempt; 0 = "
        "unbounded (LOG_PARSER_TPU_BROADCAST_TIMEOUT_S)",
    )
    parser.add_argument(
        "--broadcast-retries", type=int, default=None,
        help="extra dispatch attempts after a pre-collective timeout "
        "(LOG_PARSER_TPU_BROADCAST_RETRIES)",
    )
    parser.add_argument(
        "--heartbeat-s", type=float, default=None, metavar="SECONDS",
        help="follower heartbeat interval on the coordinator; 0 disables "
        "(LOG_PARSER_TPU_HEARTBEAT_S)",
    )
    parser.add_argument(
        "--dead-after", type=int, default=None,
        help="consecutive dispatch failures before the follower group is "
        "declared dead and serving degrades to local "
        "(LOG_PARSER_TPU_DEAD_AFTER)",
    )
    parser.add_argument(
        "--device-timeout",
        type=float,
        default=None,
        metavar="SECONDS",
        help="watchdog deadline for the device step: a wedged backend "
        "trips the circuit and requests serve from the host path until "
        "it responds (default: off; also LOG_PARSER_TPU_DEVICE_TIMEOUT_S)",
    )
    # overload controls (docs/OPS.md "Overload & degradation") — flags win
    # over the LOG_PARSER_TPU_* env vars they mirror
    parser.add_argument(
        "--max-inflight", type=int, default=None,
        help="bound on concurrently-executing parses; 0 = unbounded "
        "(LOG_PARSER_TPU_MAX_INFLIGHT)",
    )
    parser.add_argument(
        "--max-queue", type=int, default=None,
        help="bound on parses waiting for a slot before the gate sheds "
        "with 429 (LOG_PARSER_TPU_MAX_QUEUE)",
    )
    parser.add_argument(
        "--deadline-ms", type=float, default=None,
        help="default per-request deadline; X-Request-Deadline-Ms "
        "overrides per request (LOG_PARSER_TPU_DEADLINE_MS)",
    )
    parser.add_argument(
        "--drain-s", type=float, default=None,
        help="SIGTERM drain deadline: finish in-flight work up to this "
        "many seconds before exiting (LOG_PARSER_TPU_DRAIN_S)",
    )
    # tenant evacuation (docs/OPS.md "Tenant migration & drain")
    parser.add_argument(
        "--drain-deadline-s", type=float, default=None, metavar="SECONDS",
        help="bound on the drain supervisor's tenant evacuation "
        "(/admin/drain + SIGTERM): past it, remaining tenants close "
        "locally — open stream sessions get an explicit error frame, "
        "never an indefinite hang (default 30; "
        "LOG_PARSER_TPU_DRAIN_DEADLINE_S)",
    )
    parser.add_argument(
        "--drain-target", default=None, metavar="URL",
        help="peer base URL (http://host:port) that drained tenants "
        "migrate to via the crash-safe migration protocol "
        "(runtime/migrate.py); unset = tenants close locally on drain "
        "(LOG_PARSER_TPU_DRAIN_TARGET)",
    )
    parser.add_argument(
        "--drain-on-burn", type=float, default=None, metavar="SECONDS",
        help="poll interval for the health-driven drain trigger: when "
        "/q/health SLO burn goes DEGRADED or the device breaker sticks "
        "open, the supervisor evacuates this process; 0 disables "
        "(default 0; LOG_PARSER_TPU_DRAIN_ON_BURN)",
    )
    # warm-standby replication (docs/OPS.md "Warm-standby replication")
    parser.add_argument(
        "--replica-target", default=None, metavar="URL",
        help="standby base URL (http://host:port) every tenant's "
        "frequency WAL continuously ships to as it is fsynced "
        "(runtime/replicate.py; requires --state-dir; "
        "LOG_PARSER_TPU_REPLICA_TARGET)",
    )
    parser.add_argument(
        "--replica-of", default=None, metavar="URL",
        help="primary base URL this process is the warm standby of: "
        "boot fenced (every client resolve 307s to the primary), "
        "accept /admin/replica/feed, arm the failover supervisor "
        "(requires --state-dir; LOG_PARSER_TPU_REPLICA_OF)",
    )
    parser.add_argument(
        "--failover-after-s", type=float, default=None, metavar="SECONDS",
        help="consecutive seconds the primary's /q/health must fail "
        "before the standby journals PROMOTE(epoch+1) and takes "
        "ownership; 0 = manual POST /admin/promote only (default 0; "
        "LOG_PARSER_TPU_FAILOVER_AFTER_S)",
    )
    # cross-request micro-batching (docs/OPS.md "Micro-batching")
    parser.add_argument(
        "--batching", choices=("on", "off"), default=None,
        help="coalesce concurrent parses into shared device batches "
        "(runtime/batcher.py; single-device engine only; "
        "LOG_PARSER_TPU_BATCHING)",
    )
    parser.add_argument(
        "--batch-wait-ms", type=float, default=None, metavar="MS",
        help="max time a request waits for batchmates before its bucket "
        "flushes (LOG_PARSER_TPU_BATCH_WAIT_MS)",
    )
    parser.add_argument(
        "--batch-max", type=int, default=None,
        help="requests per coalesced device batch; a full bucket flushes "
        "immediately (LOG_PARSER_TPU_BATCH_MAX)",
    )
    # exact-match line cache (docs/OPS.md "Line cache (routing tier)")
    parser.add_argument(
        "--line-cache-mb", type=float, default=None, metavar="MB",
        help="resident-byte budget of the exact-match line cache: repeat "
        "lines skip the match cube, novel lines run as a compacted "
        "residual batch (runtime/linecache.py; single-device engine "
        "only; 0 disables; default 64; LOG_PARSER_TPU_LINE_CACHE_MB)",
    )
    # template miner (docs/OPS.md "Template miner")
    parser.add_argument(
        "--miner", choices=("on", "off"), default=None,
        help="mine templates from the line-cache miss stream "
        "(log_parser_tpu/mining/; requires --line-cache-mb > 0; "
        "single-device engine only; default off; LOG_PARSER_TPU_MINER)",
    )
    parser.add_argument(
        "--miner-sample", type=float, default=None, metavar="RATE",
        help="fraction of unique cache-miss lines offered to the miner "
        "tap; deterministic stride sampling, never blocks the hot path "
        "(default 1.0; LOG_PARSER_TPU_MINER_SAMPLE)",
    )
    parser.add_argument(
        "--miner-min-support", type=int, default=None,
        help="miss lines a template cluster must absorb before it is "
        "synthesized into a candidate (default 8; "
        "LOG_PARSER_TPU_MINER_MIN_SUPPORT)",
    )
    parser.add_argument(
        "--mined-patterns", default=None, choices=("off", "review", "auto"),
        help="what happens to lint-clean mined candidates: 'review' parks "
        "them for GET/POST /patterns/mined, 'auto' admits through canary "
        "+ quiesced swap with shadow verification forced on, 'off' "
        "clusters without synthesizing; default review "
        "(LOG_PARSER_TPU_MINED_PATTERNS)",
    )
    # streaming follow-mode (docs/OPS.md "Streaming follow-mode")
    parser.add_argument(
        "--stream-emit-threshold", type=float, default=None, metavar="SCORE",
        help="minimum provisional score before a streaming session emits "
        "an event frame early (monotone-refinement contract: emitted "
        "scores may firm up, retractions are explicit 'revised' frames; "
        "default 0 emits everything; "
        "LOG_PARSER_TPU_STREAM_EMIT_THRESHOLD)",
    )
    parser.add_argument(
        "--stream-ttl-s", type=float, default=None, metavar="SECONDS",
        help="idle streaming sessions are reaped (and their admission "
        "slot released) after this long without a chunk; 0 disables "
        "the reaper (default 300; LOG_PARSER_TPU_STREAM_TTL_S)",
    )
    # poison-request quarantine + online shadow verification
    # (docs/OPS.md "Poison-request triage" / "Shadow divergence")
    parser.add_argument(
        "--quarantine-strikes", type=int, default=None,
        help="organic device-failure strikes before a request fingerprint "
        "is quarantined to the golden host path "
        "(LOG_PARSER_TPU_QUARANTINE_STRIKES)",
    )
    parser.add_argument(
        "--quarantine-ttl-s", type=float, default=None, metavar="SECONDS",
        help="how long a quarantined fingerprint stays off the device "
        "step before re-admission (LOG_PARSER_TPU_QUARANTINE_TTL_S)",
    )
    parser.add_argument(
        "--shadow-rate", type=float, default=None, metavar="RATE",
        help="fraction of served requests re-run on the golden host path "
        "off the hot path and compared at 1e-9; divergence trips a "
        "per-pattern breaker (0 disables; LOG_PARSER_TPU_SHADOW_RATE)",
    )
    # observability plane (docs/OPS.md "Observability")
    parser.add_argument(
        "--trace-ring", type=int, default=None, metavar="N",
        help="capacity of the bounded request-trace ring behind "
        "GET /trace/recent (default 256; LOG_PARSER_TPU_TRACE_RING)",
    )
    parser.add_argument(
        "--trace-slow-ms", type=float, default=None, metavar="MS",
        help="requests at or above this total latency are also captured "
        "in the slow-request ring (default 500; "
        "LOG_PARSER_TPU_TRACE_SLOW_MS)",
    )
    parser.add_argument(
        "--trace-sample", type=float, default=None, metavar="FRACTION",
        help="head-sampling rate for the causal span store behind "
        "GET /trace/spans: deterministic on the trace id; slow requests "
        "(--trace-slow-ms) and flush/session/tenancy spans are always "
        "kept (default 1.0; LOG_PARSER_TPU_TRACE_SAMPLE)",
    )
    parser.add_argument(
        "--trace-spans", type=int, default=None, metavar="N",
        help="capacity of the bounded causal span store "
        "(default 256; LOG_PARSER_TPU_TRACE_SPANS)",
    )
    parser.add_argument(
        "--slo-p99-ms", type=float, default=None, metavar="MS",
        help="latency objective: p99 of served requests should stay "
        "under this; burn-rate over the multi-window accounting flips "
        "/q/health DEGRADED (0 disables; LOG_PARSER_TPU_SLO_P99_MS)",
    )
    parser.add_argument(
        "--slo-availability", type=float, default=None, metavar="FRACTION",
        help="availability objective, e.g. 0.999: non-5xx fraction of "
        "requests; burn-rate over budget flips /q/health DEGRADED "
        "(0 disables; LOG_PARSER_TPU_SLO_AVAILABILITY)",
    )
    parser.add_argument(
        "--faults", default=None, metavar="SPEC",
        help="fault-injection DSL, e.g. 'device_hang:2@after=3' "
        "(LOG_PARSER_TPU_FAULTS; see runtime/faults.py)",
    )
    parser.add_argument(
        "--fault-seed", type=int, default=None,
        help="PRNG seed for probabilistic fault specs "
        "(LOG_PARSER_TPU_FAULT_SEED)",
    )
    # durable state + hot reload (docs/OPS.md "State durability & recovery")
    parser.add_argument(
        "--state-dir", default=None, metavar="DIR",
        help="directory for the frequency WAL + snapshots; enables crash "
        "recovery across restarts (LOG_PARSER_TPU_STATE_DIR)",
    )
    parser.add_argument(
        "--journal-fsync-ms", type=float, default=None, metavar="MS",
        help="group-fsync interval for the frequency journal "
        "(LOG_PARSER_TPU_JOURNAL_FSYNC_MS)",
    )
    parser.add_argument(
        "--snapshot-every", type=int, default=None,
        help="journal records between background snapshots; a snapshot "
        "truncates the WAL (LOG_PARSER_TPU_SNAPSHOT_EVERY)",
    )
    # resource-pressure plane (docs/OPS.md "Resource exhaustion")
    parser.add_argument(
        "--disk-soft-mb", type=float, default=None, metavar="MB",
        help="free-byte soft watermark over --state-dir: below it every "
        "journal snapshots+truncates and the migration/epoch journals "
        "compact (runtime/pressure.py; 0 disables; "
        "LOG_PARSER_TPU_DISK_SOFT_MB)",
    )
    parser.add_argument(
        "--disk-hard-mb", type=float, default=None, metavar="MB",
        help="free-byte hard watermark: below it journals degrade to a "
        "bounded in-memory ring and responses carry 'durability: "
        "degraded' — the serving path keeps answering 200s (0 disables; "
        "LOG_PARSER_TPU_DISK_HARD_MB)",
    )
    parser.add_argument(
        "--mem-soft-mb", type=float, default=None, metavar="MB",
        help="RSS soft watermark: over it the memory levers apply one "
        "per poll in severity order (line-cache shrink, interner evict, "
        "tenant eviction, span staging trim, miner tap close), released "
        "in reverse with hysteresis (0 disables; "
        "LOG_PARSER_TPU_MEM_SOFT_MB)",
    )
    parser.add_argument(
        "--retry-budget", type=float, default=None, metavar="RATIO",
        help="retry-budget ratio shared per destination: sustained "
        "retries (shim reconnects, router re-routes, replica sender "
        "backoff) are capped at this fraction of recent first attempts; "
        "exhausted budgets shed 'retry budget exhausted'; 0 disables "
        "(default 0.1; LOG_PARSER_TPU_RETRY_BUDGET)",
    )
    parser.add_argument(
        "--watch-patterns", type=float, default=None, metavar="SECONDS",
        help="poll the pattern directory at this interval and hot-reload "
        "on change (canary-gated, runtime/reload.py); 0 disables "
        "(LOG_PARSER_TPU_WATCH_PATTERNS)",
    )
    parser.add_argument(
        "--lint-patterns", default=None, choices=("off", "warn", "block"),
        help="static-analysis lint stage of the reload ladder "
        "(log_parser_tpu/analysis/): 'warn' records findings on "
        "/trace/last, 'block' rejects a reload with gating findings as "
        "a structured 409; default warn (LOG_PARSER_TPU_LINT_PATTERNS)",
    )
    parser.add_argument(
        "--compile-cache-dir", default=None, metavar="DIR",
        help="persistent XLA compilation cache directory: warm restarts "
        "replay compiles from disk instead of re-running XLA "
        "(utils/xlacache.py; default on at "
        "~/.cache/log_parser_tpu/xla-cache, '0' disables; "
        "LOG_PARSER_TPU_XLA_CACHE)",
    )
    parser.add_argument(
        "--pallas-dfa", default=None, choices=("on", "off"),
        help="route the union multi-DFA tier through the Pallas scan "
        "kernel (ops/matchdfa_pallas.py); bit-identical to the XLA scan, "
        "falls back per batch on admission or fault; default off "
        "(LOG_PARSER_TPU_PALLAS_DFA)",
    )
    # multi-tenant serving (docs/OPS.md "Multi-tenant serving")
    parser.add_argument(
        "--tenant-root", default=None, metavar="DIR",
        help="root of per-tenant pattern libraries: DIR/<tenant>/ holds "
        "tenant <tenant>'s YAML sets, built lazily on first X-Tenant "
        "request (runtime/tenancy.py; single-device engine only; "
        "LOG_PARSER_TPU_TENANT_ROOT)",
    )
    parser.add_argument(
        "--tenant-budget-mb", type=float, default=None, metavar="MB",
        help="resident byte budget across non-default tenant banks; over "
        "budget the least-recently-used idle tenant is evicted (its "
        "journal snapshots, its next request rebuilds warm from the "
        "library snapshot cache); 0 = unbounded "
        "(LOG_PARSER_TPU_TENANT_BUDGET_MB)",
    )
    parser.add_argument(
        "--tenant-max-inflight", type=int, default=None,
        help="per-tenant cap on concurrently-executing parses inside the "
        "shared gate; 0 = unbounded (LOG_PARSER_TPU_TENANT_MAX_INFLIGHT)",
    )
    parser.add_argument(
        "--tenant-max-queued", type=int, default=None,
        help="per-tenant share of the shared wait queue; 0 = unbounded "
        "(LOG_PARSER_TPU_TENANT_MAX_QUEUED)",
    )
    parser.add_argument(
        "--tenant-lines-per-s", type=float, default=None,
        help="per-tenant sustained log-line rate (token bucket, 2s "
        "burst); a request over budget sheds 429 'tenant rate' with "
        "Retry-After; 0 = unbounded (LOG_PARSER_TPU_TENANT_LINES_PER_S)",
    )
    args = parser.parse_args(argv)
    if args.device_timeout is not None:
        os.environ["LOG_PARSER_TPU_DEVICE_TIMEOUT_S"] = str(args.device_timeout)
    if args.pallas_dfa is not None:
        os.environ["LOG_PARSER_TPU_PALLAS_DFA"] = (
            "1" if args.pallas_dfa == "on" else "0"
        )
    for flag, env_key in (
        (args.max_inflight, "LOG_PARSER_TPU_MAX_INFLIGHT"),
        (args.max_queue, "LOG_PARSER_TPU_MAX_QUEUE"),
        (args.deadline_ms, "LOG_PARSER_TPU_DEADLINE_MS"),
        (args.drain_s, "LOG_PARSER_TPU_DRAIN_S"),
        (args.batching, "LOG_PARSER_TPU_BATCHING"),
        (args.batch_wait_ms, "LOG_PARSER_TPU_BATCH_WAIT_MS"),
        (args.batch_max, "LOG_PARSER_TPU_BATCH_MAX"),
        (args.line_cache_mb, "LOG_PARSER_TPU_LINE_CACHE_MB"),
        (args.miner, "LOG_PARSER_TPU_MINER"),
        (args.miner_sample, "LOG_PARSER_TPU_MINER_SAMPLE"),
        (args.miner_min_support, "LOG_PARSER_TPU_MINER_MIN_SUPPORT"),
        (args.mined_patterns, "LOG_PARSER_TPU_MINED_PATTERNS"),
        (args.stream_emit_threshold, "LOG_PARSER_TPU_STREAM_EMIT_THRESHOLD"),
        (args.stream_ttl_s, "LOG_PARSER_TPU_STREAM_TTL_S"),
        (args.quarantine_strikes, "LOG_PARSER_TPU_QUARANTINE_STRIKES"),
        (args.quarantine_ttl_s, "LOG_PARSER_TPU_QUARANTINE_TTL_S"),
        (args.shadow_rate, "LOG_PARSER_TPU_SHADOW_RATE"),
        (args.trace_ring, "LOG_PARSER_TPU_TRACE_RING"),
        (args.trace_slow_ms, "LOG_PARSER_TPU_TRACE_SLOW_MS"),
        (args.trace_sample, "LOG_PARSER_TPU_TRACE_SAMPLE"),
        (args.trace_spans, "LOG_PARSER_TPU_TRACE_SPANS"),
        (args.slo_p99_ms, "LOG_PARSER_TPU_SLO_P99_MS"),
        (args.slo_availability, "LOG_PARSER_TPU_SLO_AVAILABILITY"),
        (args.faults, "LOG_PARSER_TPU_FAULTS"),
        (args.fault_seed, "LOG_PARSER_TPU_FAULT_SEED"),
        (args.broadcast_timeout, "LOG_PARSER_TPU_BROADCAST_TIMEOUT_S"),
        (args.broadcast_retries, "LOG_PARSER_TPU_BROADCAST_RETRIES"),
        (args.heartbeat_s, "LOG_PARSER_TPU_HEARTBEAT_S"),
        (args.dead_after, "LOG_PARSER_TPU_DEAD_AFTER"),
        (args.state_dir, "LOG_PARSER_TPU_STATE_DIR"),
        (args.journal_fsync_ms, "LOG_PARSER_TPU_JOURNAL_FSYNC_MS"),
        (args.snapshot_every, "LOG_PARSER_TPU_SNAPSHOT_EVERY"),
        (args.disk_soft_mb, "LOG_PARSER_TPU_DISK_SOFT_MB"),
        (args.disk_hard_mb, "LOG_PARSER_TPU_DISK_HARD_MB"),
        (args.mem_soft_mb, "LOG_PARSER_TPU_MEM_SOFT_MB"),
        (args.retry_budget, "LOG_PARSER_TPU_RETRY_BUDGET"),
        (args.watch_patterns, "LOG_PARSER_TPU_WATCH_PATTERNS"),
        (args.lint_patterns, "LOG_PARSER_TPU_LINT_PATTERNS"),
        (args.compile_cache_dir, "LOG_PARSER_TPU_XLA_CACHE"),
        (args.tenant_root, "LOG_PARSER_TPU_TENANT_ROOT"),
        (args.tenant_budget_mb, "LOG_PARSER_TPU_TENANT_BUDGET_MB"),
        (args.tenant_max_inflight, "LOG_PARSER_TPU_TENANT_MAX_INFLIGHT"),
        (args.tenant_max_queued, "LOG_PARSER_TPU_TENANT_MAX_QUEUED"),
        (args.tenant_lines_per_s, "LOG_PARSER_TPU_TENANT_LINES_PER_S"),
        (args.drain_deadline_s, "LOG_PARSER_TPU_DRAIN_DEADLINE_S"),
        (args.drain_target, "LOG_PARSER_TPU_DRAIN_TARGET"),
        (args.drain_on_burn, "LOG_PARSER_TPU_DRAIN_ON_BURN"),
        (args.replica_target, "LOG_PARSER_TPU_REPLICA_TARGET"),
        (args.replica_of, "LOG_PARSER_TPU_REPLICA_OF"),
        (args.failover_after_s, "LOG_PARSER_TPU_FAILOVER_AFTER_S"),
    ):
        if flag is not None:
            os.environ[env_key] = str(flag)

    logging.basicConfig(
        level=args.log_level.upper(),
        format="%(asctime)s %(levelname)s [%(name)s] %(message)s",
    )
    log = logging.getLogger("log_parser_tpu.serve")

    if args.role == "router":
        # the router holds no engine: no pattern directory, no jax —
        # branch before any of the engine boot requirements below
        return _run_router(args, log)

    config = (
        ScoringConfig.from_properties_file(args.config)
        if args.config
        else ScoringConfig.from_env()
    )
    if args.pattern_dir:
        config = dataclasses.replace(config, pattern_directory=args.pattern_dir)
    if not config.pattern_directory:
        log.error("pattern.directory is required (--pattern-dir / config / env)")
        return 2

    if args.coordinator:
        if args.num_processes is None or args.process_id is None:
            log.error("--coordinator requires --num-processes and --process-id")
            return 2
        from log_parser_tpu.parallel.distributed import init_distributed

        init_distributed(args.coordinator, args.num_processes, args.process_id)

    pattern_sets = load_pattern_directory(config.pattern_directory)
    if args.coordinator:
        from log_parser_tpu.parallel import make_mesh
        from log_parser_tpu.parallel.distributed import DistributedShardedEngine

        mesh = make_mesh()
        engine = DistributedShardedEngine(pattern_sets, config, mesh=mesh)
        log.info(
            "Multi-process mesh: %d devices across %d processes",
            mesh.devices.size,
            args.num_processes,
        )
    elif args.sharded:
        from log_parser_tpu.parallel import ShardedEngine, make_mesh

        mesh = make_mesh()
        engine = ShardedEngine(pattern_sets, config, mesh=mesh)
        log.info("Sharding line batches over %d devices", mesh.devices.size)
    else:
        engine = AnalysisEngine(pattern_sets, config)
    if engine.skipped_patterns:
        for pid, reason in engine.skipped_patterns:
            log.warning("pattern %r disabled: %s", pid, reason)
    log.info(
        "Loaded %d pattern sets (%d patterns, %d matcher columns; %d on-device DFAs)",
        len(pattern_sets),
        engine.bank.n_patterns,
        engine.bank.n_columns,
        sum(1 for c in engine.bank.columns if c.dfa is not None),
    )

    if os.environ.get("LOG_PARSER_TPU_BATCHING", "off").strip().lower() == "on":
        if args.coordinator or args.sharded:
            # the vmapped batch program has no shard_map counterpart yet —
            # the request axis and the line/pattern mesh axes would need a
            # combined layout (ROADMAP)
            log.warning(
                "--batching is only supported on the single-device "
                "engine; serving unbatched"
            )
        else:
            wait_ms = float(os.environ.get("LOG_PARSER_TPU_BATCH_WAIT_MS", "2"))
            batch_max = int(os.environ.get("LOG_PARSER_TPU_BATCH_MAX", "8"))
            engine.enable_batching(wait_ms=wait_ms, batch_max=batch_max)
            log.info(
                "Micro-batching on: wait %.1f ms, batch max %d",
                wait_ms,
                batch_max,
            )

    line_cache_mb = float(
        os.environ.get("LOG_PARSER_TPU_LINE_CACHE_MB", "64") or 0
    )
    if line_cache_mb > 0:
        if args.coordinator or args.sharded:
            # the residual program is the full-bank single-device cube;
            # sharded engines split patterns/lines across devices and
            # keep the uncached path (same gate as --batching)
            log.warning(
                "--line-cache-mb is only supported on the single-device "
                "engine; serving uncached"
            )
        else:
            engine.enable_line_cache(line_cache_mb)
            log.info("Line cache on: %.0f MB budget", line_cache_mb)

    if args.coordinator and args.process_id != 0:
        # followers own no network surface: they replay the coordinator's
        # broadcast requests so every process enters each SPMD dispatch.
        # SIGTERM/SIGINT must NOT kill a follower mid-collective — orderly
        # exit is the coordinator's shutdown sentinel, which arrives after
        # the coordinator finishes draining. A second signal forces out.
        import signal

        signals_seen = {"n": 0}

        def _follower_signal(signum, frame):
            signals_seen["n"] += 1
            if signals_seen["n"] > 1:
                log.warning(
                    "Follower %d: second signal, exiting immediately",
                    args.process_id,
                )
                raise SystemExit(1)
            log.info(
                "Follower %d: signal %d ignored — waiting for the "
                "coordinator's drain sentinel (signal again to force exit)",
                args.process_id,
                signum,
            )

        signal.signal(signal.SIGTERM, _follower_signal)
        signal.signal(signal.SIGINT, _follower_signal)
        log.info("Follower %d ready", args.process_id)
        engine.follower_loop()
        return 0

    # resource-pressure plane: one controller per process, installed
    # BEFORE the journal opens so the very first append is already
    # guarded; journals/levers/compactors attach below as their
    # subsystems come up (runtime/pressure.py, docs/OPS.md "Resource
    # exhaustion")
    from log_parser_tpu.runtime import pressure

    pressure_ctl = pressure.PressureController(
        os.environ.get("LOG_PARSER_TPU_STATE_DIR") or None,
        disk_soft_mb=float(
            os.environ.get("LOG_PARSER_TPU_DISK_SOFT_MB", "0") or 0
        ),
        disk_hard_mb=float(
            os.environ.get("LOG_PARSER_TPU_DISK_HARD_MB", "0") or 0
        ),
        mem_soft_mb=float(
            os.environ.get("LOG_PARSER_TPU_MEM_SOFT_MB", "0") or 0
        ),
        retry_ratio=float(
            os.environ.get("LOG_PARSER_TPU_RETRY_BUDGET", "0.1") or 0
        ),
    )
    pressure.install(pressure_ctl)

    # durable frequency state: recover + journal under --state-dir.
    # Followers never reach this point (follower_loop above), so in
    # distributed mode only the coordinator journals — its tracker is the
    # canonical one; followers converge from the broadcast replay.
    journal = None
    state_dir = os.environ.get("LOG_PARSER_TPU_STATE_DIR")
    if state_dir:
        journal = engine.attach_journal(
            state_dir,
            fsync_ms=float(
                os.environ.get("LOG_PARSER_TPU_JOURNAL_FSYNC_MS", "50")
            ),
            snapshot_every=int(
                os.environ.get("LOG_PARSER_TPU_SNAPSHOT_EVERY", "512")
            ),
        )
        log.info(
            "Frequency journal at %s: epoch %d, %d record(s) replayed%s",
            state_dir,
            journal.epoch,
            journal.replayed,
            ", torn tail quarantined" if journal.torn_tails else "",
        )
        pressure_ctl.register_journal(journal)
        # on-demand device profiling (POST /debug/profile) captures into a
        # state-dir subdirectory; without --state-dir the route answers 503
        engine.obs.profiler.configure(os.path.join(state_dir, "profiles"))
        # shutdown writes the span store as OTLP/JSON here, so the last
        # window of causal trees survives the process
        engine.obs.span_dump_path = os.path.join(state_dir, "spans.otlp.json")

    # template miner: background consumer of the line-cache miss stream
    # (log_parser_tpu/mining/); per-tenant miners are wired below in
    # tenant_engine_setup with the SAME env-carried knobs
    miner_on = (
        os.environ.get("LOG_PARSER_TPU_MINER", "off").strip().lower() == "on"
    )
    miner_sample = float(os.environ.get("LOG_PARSER_TPU_MINER_SAMPLE", "1.0"))
    miner_support = int(
        os.environ.get("LOG_PARSER_TPU_MINER_MIN_SUPPORT", "8")
    )
    miner_mode = (
        os.environ.get("LOG_PARSER_TPU_MINED_PATTERNS", "review")
        .strip()
        .lower()
    )
    if miner_on:
        if args.coordinator or args.sharded:
            log.warning(
                "--miner rides the line cache and is only supported on "
                "the single-device engine; mining disabled"
            )
            miner_on = False
        elif engine.line_cache is None:
            log.warning(
                "--miner requires --line-cache-mb > 0 (the miss stream "
                "IS the cache miss stream); mining disabled"
            )
            miner_on = False
        else:
            engine.enable_miner(
                mode=miner_mode,
                sample=miner_sample,
                min_support=miner_support,
                state_dir=state_dir,
            )
            log.info(
                "Template miner on: mode %s, sample %.3g, min support %d",
                miner_mode,
                miner_sample,
                miner_support,
            )
            pressure_ctl.register_miner(engine.miner)

    # tenant registry: X-Tenant (HTTP) / x-tenant (gRPC) / method@tenant
    # (framed shim) resolve through one registry; each non-default tenant
    # gets a dedicated engine mirroring this one's serving features, all
    # admitting through the ONE shared gate
    from log_parser_tpu.runtime.tenancy import TenantQuota, TenantRegistry
    from log_parser_tpu.serve.admission import shared_gate

    tenant_root = os.environ.get("LOG_PARSER_TPU_TENANT_ROOT") or None
    if tenant_root and (args.coordinator or args.sharded):
        # tenant engines are single-device AnalysisEngines; placing tenant
        # banks across a mesh is parallel/pattern_sharded.py's
        # tenant-placement mode, not the serve path
        log.warning(
            "--tenant-root is only supported on the single-device engine; "
            "serving single-tenant"
        )
        tenant_root = None

    # filled after the replicator is built below; tenant engines that come
    # up later (lazy first-touch builds) attach their WAL senders here
    replication_holder: dict = {"rep": None}

    def tenant_engine_setup(eng, tenant_id: str) -> None:
        # mirror the default engine's serving features; env carries the
        # flag values (the flag→env loop above ran before boot)
        if os.environ.get(
            "LOG_PARSER_TPU_BATCHING", "off"
        ).strip().lower() == "on":
            eng.enable_batching(
                wait_ms=float(
                    os.environ.get("LOG_PARSER_TPU_BATCH_WAIT_MS", "2")
                ),
                batch_max=int(os.environ.get("LOG_PARSER_TPU_BATCH_MAX", "8")),
            )
        mb = float(os.environ.get("LOG_PARSER_TPU_LINE_CACHE_MB", "64") or 0)
        if mb > 0:
            eng.enable_line_cache(mb)
            if miner_on:
                # per-tenant miner: own tap/clusterer/pending store, state
                # namespaced beside the tenant WAL (tenants/<id>/mined/)
                eng.enable_miner(
                    mode=miner_mode,
                    sample=miner_sample,
                    min_support=miner_support,
                    state_dir=(
                        os.path.join(state_dir, "tenants", tenant_id)
                        if state_dir
                        else None
                    ),
                )
        if state_dir:
            # namespaced WAL/snapshot dir: tenants/<id> under the default
            # tenant's state dir, so recovery is per-tenant and a tenant
            # eviction's final snapshot lands where its rebuild looks
            tenant_journal = eng.attach_journal(
                os.path.join(state_dir, "tenants", tenant_id),
                fsync_ms=float(
                    os.environ.get("LOG_PARSER_TPU_JOURNAL_FSYNC_MS", "50")
                ),
                snapshot_every=int(
                    os.environ.get("LOG_PARSER_TPU_SNAPSHOT_EVERY", "512")
                ),
            )
            if tenant_journal is not None:
                # rides the same ladder as the default WAL: soft
                # snapshots it, hard degrades it to its ring
                pressure_ctl.register_journal(tenant_journal)
            rep = replication_holder["rep"]
            if rep is not None:
                # primary side: this tenant's WAL starts shipping to the
                # standby as soon as the engine is up (no-op on standbys)
                rep.attach_sender(tenant_id, eng)

    t_inflight = int(os.environ.get("LOG_PARSER_TPU_TENANT_MAX_INFLIGHT", "0") or 0)
    t_queued = int(os.environ.get("LOG_PARSER_TPU_TENANT_MAX_QUEUED", "0") or 0)
    t_lps = float(os.environ.get("LOG_PARSER_TPU_TENANT_LINES_PER_S", "0") or 0)
    tenants = TenantRegistry(
        engine,
        root=tenant_root,
        budget_mb=float(
            os.environ.get("LOG_PARSER_TPU_TENANT_BUDGET_MB", "0") or 0
        ),
        gate=shared_gate(engine),
        engine_setup=tenant_engine_setup,
        quota_factory=lambda tid: TenantQuota(t_inflight, t_queued, t_lps),
        lint_mode=os.environ.get("LOG_PARSER_TPU_LINT_PATTERNS", "warn"),
    )
    if tenant_root:
        log.info(
            "Multi-tenant serving: root %s, bank budget %s, quota "
            "inflight=%d queued=%d lines/s=%.0f",
            tenant_root,
            "unbounded" if tenants.budget_bytes <= 0
            else "%.0f MB" % (tenants.budget_bytes / 2**20),
            t_inflight, t_queued, t_lps,
        )

    try:
        server = make_server(engine, args.host, args.port, tenants=tenants)
    except OSError:
        # followers are already blocked waiting for a broadcast; a
        # coordinator that dies without the shutdown sentinel would hang
        # the whole group
        if args.coordinator:
            engine.shutdown_followers()
        raise
    # SIGTERM/SIGINT drain instead of killing in-flight work: readiness
    # flips to 503, the gate refuses new parses, in-flight ones finish (up
    # to --drain-s), then serve_forever returns and the normal shutdown
    # sequence below runs — including the follower sentinel in distributed
    # mode, which therefore always lands AFTER the drain, never
    # mid-broadcast (the analyze lock covers the straggler case).
    # streaming follow-mode sessions: same single-device gate as
    # --batching / --line-cache-mb (the session residual program is the
    # full-bank cube). The manager is created eagerly so the TTL reaper
    # runs from boot, not from the first streaming request.
    if args.coordinator or args.sharded:
        server.stream_enabled = False
        log.warning(
            "streaming sessions are only supported on the single-device "
            "engine; POST /parse/stream disabled"
        )
    else:
        mgr = server.get_stream_manager()
        log.info(
            "Streaming on: emit threshold %.3g, session TTL %.0fs",
            mgr.emit_threshold,
            mgr.ttl_s,
        )
    # crash-safe tenant migration + health-driven drain (runtime/migrate.py,
    # docs/OPS.md "Tenant migration & drain"). The Migrator needs --state-dir
    # for its per-migration journals; the DrainSupervisor is wired
    # unconditionally so /admin/drain and SIGTERM finalize EVERY resident
    # tenant (fold WALs, flush batchers, dump spans) even on stateless nodes.
    from log_parser_tpu.runtime.migrate import (
        DrainSupervisor,
        HttpTarget,
        Migrator,
    )

    drain_deadline = float(
        os.environ.get("LOG_PARSER_TPU_DRAIN_DEADLINE_S", "30") or 30
    )
    drain_target_url = (
        os.environ.get("LOG_PARSER_TPU_DRAIN_TARGET", "").strip() or None
    )
    migrator = None
    if state_dir:
        migrator = Migrator(
            tenants,
            state_root=state_dir,
            node_url=f"http://{args.host}:{args.port}",
        )
        server.migrator = migrator
        # boot-time recovery: exactly-one-owner after any crash — re-install
        # forwards for cut-over migrations, resume the ones whose target we
        # still know, discard half-staged imports
        recovered = migrator.recover(
            {drain_target_url: HttpTarget(drain_target_url)}
            if drain_target_url
            else None
        )
        if any(v for v in recovered.values()):
            log.info(
                "Migration recovery: %d forward(s) re-installed, "
                "%d resumed, %d staged import(s) discarded, %d pending",
                len(recovered["forwards"]),
                len(recovered["resumed"]),
                len(recovered["discarded"]),
                len(recovered["pending"]),
            )
        # bounded growth: terminal migration journals compact at boot
        # and on every entry into soft disk pressure
        pressure_ctl.register_compactor("migration", migrator.compact)
    drain_supervisor = DrainSupervisor(
        tenants,
        migrator,
        gate=server.admission,
        target=(
            HttpTarget(drain_target_url, timeout_s=max(5.0, drain_deadline))
            if drain_target_url
            else None
        ),
        deadline_s=drain_deadline,
        span_dump_path=engine.obs.span_dump_path,
    )
    server.drain_supervisor = drain_supervisor
    drain_on_burn = float(
        os.environ.get("LOG_PARSER_TPU_DRAIN_ON_BURN", "0") or 0
    )
    if drain_on_burn > 0:

        def _evacuation_check() -> str | None:
            slo = engine.obs.slo.health()
            if slo is not None and slo.get("status") != "UP":
                return "slo-burn"
            if engine.watchdog.circuit_open:
                return "device-breaker"
            return None

        drain_supervisor.watch_health(_evacuation_check, poll_s=drain_on_burn)
        log.info(
            "Health-driven drain armed: poll %.1fs, target %s",
            drain_on_burn,
            drain_target_url or "<close locally>",
        )
    # warm-standby replication + fenced failover (runtime/replicate.py,
    # docs/OPS.md "Warm-standby replication"). A primary (--replica-target)
    # ships every tenant WAL to the standby; a standby (--replica-of) boots
    # fenced, applies feeds, and promotes on sustained primary death.
    replica_target_url = (
        os.environ.get("LOG_PARSER_TPU_REPLICA_TARGET", "").strip() or None
    )
    replica_of_url = (
        os.environ.get("LOG_PARSER_TPU_REPLICA_OF", "").strip() or None
    )
    failover_after = float(
        os.environ.get("LOG_PARSER_TPU_FAILOVER_AFTER_S", "0") or 0
    )
    if (replica_target_url or replica_of_url) and not state_dir:
        log.warning(
            "replication needs --state-dir for the WAL + epoch journal; "
            "--replica-target/--replica-of ignored"
        )
    elif replica_target_url or replica_of_url:
        from log_parser_tpu.runtime.replicate import (
            HttpReplicaTarget,
            Replicator,
        )
        from log_parser_tpu.runtime.tenancy import DEFAULT_TENANT

        replicator = Replicator(
            tenants,
            state_root=state_dir,
            node_url=f"http://{args.host}:{args.port}",
            peer_url=replica_of_url,
            target=(
                HttpReplicaTarget(replica_target_url)
                if replica_target_url
                else None
            ),
        )
        server.replicator = replicator
        # before recover(): tenants the recovery walk activates must come
        # up with their WAL senders attached
        replication_holder["rep"] = replicator
        rep_summary = replicator.recover()
        # the default engine's sender (tenant engines attach via
        # tenant_engine_setup as they build)
        if journal is not None:
            replicator.attach_sender(DEFAULT_TENANT, engine)
        if replica_of_url and failover_after > 0:
            replicator.arm_failover(replica_of_url, after_s=failover_after)
        # epoch WAL compaction: a long promote/demote history folds to
        # one terminal record at boot and on soft disk pressure
        pressure_ctl.register_compactor(
            "epoch", replicator.compact_epoch_journal
        )
        if migrator is not None:
            # cross-plane wiring: a tenant cut over to another node must
            # stop shipping here AND be released on the standby, or a later
            # promotion resurrects the departed tenant's stale replica; a
            # tenant migrated back durably voids its release. Replay the
            # boot-recovered ownership verdicts through the same hooks
            # (migrator.recover() ran before the replicator existed).
            migrator.on_release = replicator.release_tenant
            migrator.on_adopt = replicator.adopt_tenant
            migrator.on_primacy_check = replicator.verify_primacy
            for tid in recovered.get("forwards", ()):
                fwd = tenants.forward_for(tid)
                if fwd:
                    replicator.release_tenant(tid, fwd[0], ship=False)
            for tid in recovered.get("owned", ()):
                replicator.adopt_tenant(tid, ship=False)
        replicator.start()
        log.info(
            "Replication role %s at epoch %d (%d protocol record(s) "
            "replayed); target %s, failover %s",
            replicator.role, replicator.epoch, rep_summary["records"],
            replica_target_url or "<none>",
            "%.1fs" % failover_after if failover_after > 0 else "manual",
        )
    install_drain_handlers(
        server,
        server.admission,
        log,
        # SIGTERM evacuates: migrate every resident tenant to the drain
        # target (or close it with a final WAL fold) under the bounded
        # deadline, then finalize the default engine's journal/batcher and
        # dump the span file — the satellite guarantee that shutdown folds
        # EVERY tenant, not just the default WAL
        on_drained=lambda: drain_supervisor.drain(reason="signal"),
    )
    # canary-gated hot reload: POST /patterns/reload re-reads this
    # directory (or takes inline YAML); --watch-patterns polls it
    from log_parser_tpu.runtime.reload import PatternReloader, PatternWatcher

    server.reloader = PatternReloader(
        engine,
        config.pattern_directory,
        lint_mode=os.environ.get("LOG_PARSER_TPU_LINT_PATTERNS", "warn"),
    )
    watch_s = float(os.environ.get("LOG_PARSER_TPU_WATCH_PATTERNS", "0"))
    if watch_s > 0:
        server.watcher = PatternWatcher(
            server.reloader, config.pattern_directory, interval_s=watch_s
        )
        server.watcher.start()
        log.info("Watching %s every %.1fs", config.pattern_directory, watch_s)
    if args.coordinator:
        # follower liveness probe + degraded-mesh readmission; serializes
        # with request broadcasts on the engine's state_lock
        engine.start_health_loop()

    # memory levers in severity order: cheapest/least-visible reclaim
    # first, each applied one poll apart while RSS stays over the
    # watermark, released in reverse once it clears (hysteresis)
    saved_knobs: dict = {}

    def _lever_line_cache() -> None:
        cache = getattr(engine, "line_cache", None)
        if cache is None:
            return
        saved_knobs["line_cache_bytes"] = cache.budget_bytes
        tenants.set_line_cache_budget(cache.budget_bytes // 2)

    def _release_line_cache() -> None:
        if "line_cache_bytes" in saved_knobs:
            tenants.set_line_cache_budget(
                saved_knobs.pop("line_cache_bytes")
            )

    def _lever_interner() -> None:
        interner = getattr(engine, "key_interner", None)
        if interner is not None:
            interner.evict_half()

    def _lever_span_staging() -> None:
        spans = engine.obs.spans
        saved_knobs["staging_capacity"] = spans.staging_capacity
        spans.trim_staging(spans.staging_capacity // 2)

    def _release_span_staging() -> None:
        if "staging_capacity" in saved_knobs:
            engine.obs.spans.staging_capacity = saved_knobs.pop(
                "staging_capacity"
            )

    def _lever_miner_tap() -> None:
        m = getattr(engine, "miner", None)
        if m is not None:
            # the tap is the miner's only feed; closing it stops new
            # miss buffering (parked candidates stay reviewable)
            m.tap.close()

    pressure_ctl.add_lever(
        "line_cache", _lever_line_cache, _release_line_cache
    )
    pressure_ctl.add_lever("interner", _lever_interner)
    pressure_ctl.add_lever("tenants", lambda: tenants.shed_idle(0.5))
    pressure_ctl.add_lever(
        "span_staging", _lever_span_staging, _release_span_staging
    )
    pressure_ctl.add_lever("miner_tap", _lever_miner_tap)
    pressure_ctl.bind_obs(engine.obs)
    pressure_ctl.bootstrap()
    pressure_ctl.start()
    if pressure_ctl.disk_soft_bytes or pressure_ctl.disk_hard_bytes or (
        pressure_ctl.mem_soft_bytes
    ):
        log.info(
            "Pressure plane armed: disk soft/hard %.0f/%.0f MB free, "
            "mem soft %.0f MB, retry budget %s",
            pressure_ctl.disk_soft_bytes / 2**20,
            pressure_ctl.disk_hard_bytes / 2**20,
            pressure_ctl.mem_soft_bytes / 2**20,
            "%.0f%%" % (pressure_ctl.retry.ratio * 100)
            if pressure_ctl.retry.enabled else "off",
        )
    log.info("Serving POST /parse on %s:%d", args.host, args.port)
    try:
        server.serve_forever()
        log.info("Drained; shutting down")
    except KeyboardInterrupt:  # pre-handler-install window only
        log.info("Shutting down")
    finally:
        server.server_close()
        drain_supervisor.stop_watch()
        if server.replicator is not None:
            # stop the pump + failover watch; the epoch journal closes
            # with its last fsynced record as the durable role
            server.replicator.stop()
        if server.watcher is not None:
            server.watcher.stop()
        # tenant engines first: closes their batchers/stream sessions and
        # folds each tenant WAL into a final snapshot, releasing any
        # shared-gate slots their sessions held
        server.tenants.shutdown()
        if server.stream_manager is not None:
            # kill open sessions so their admission slots release before
            # the gate's drain accounting is torn down
            server.stream_manager.shutdown()
        if engine.batcher is not None:
            # flush anything still queued before the process exits
            engine.batcher.close()
        if getattr(engine, "miner", None) is not None:
            # parked candidates are already durable on disk; this just
            # stops the worker and closes the tap
            engine.miner.stop()
        if engine.shadow is not None:
            engine.shadow.close()
        if journal is not None:
            # fold the WAL tail into one final durable snapshot — a clean
            # shutdown must never need replay on the next boot
            journal.snapshot_now()
            journal.close()
        if engine.obs.span_dump_path:
            try:
                if engine.obs.spans.dump(engine.obs.span_dump_path):
                    log.info(
                        "Span store dumped to %s", engine.obs.span_dump_path
                    )
                else:
                    # hard disk pressure: the dump skipped atomically —
                    # the least valuable bytes lose first, the drain
                    # completes either way
                    log.warning("span dump skipped: durability degraded")
            except OSError:
                log.exception("span dump failed")
        pressure_ctl.stop()
        pressure.install(None)
        if args.coordinator:
            # under the analyze lock: a daemon handler thread may still be
            # mid-broadcast inside analyze(); interleaving the shutdown
            # sentinel with a request broadcast would desync the followers
            with server.analyze_lock:
                engine.shutdown_followers()
    return 0


def _run_router(args, log) -> int:
    """Boot the fleet front-door (``--role router``): the HTTP proxy,
    the optional framed/gRPC fronts, and the placement control loop.
    No engine is constructed — the router is deliberately thin."""
    import threading

    from log_parser_tpu.fleet.budget import FleetBudget
    from log_parser_tpu.fleet.placement import FleetController
    from log_parser_tpu.fleet.router import (
        FramedRouterFront,
        make_grpc_front,
        make_router,
        parse_backends,
    )

    try:
        backends = parse_backends(args.backends or "")
    except ValueError as exc:
        log.error("%s", exc)
        return 2

    # the router rides the same pressure plane as a backend: the retry
    # budget bounds its re-route storms, and a --state-dir gives its
    # override journal a home plus disk watermarks over it
    from log_parser_tpu.runtime import faults, pressure

    faults.ensure_env()
    state_dir = os.environ.get("LOG_PARSER_TPU_STATE_DIR") or None
    pressure_ctl = pressure.PressureController(
        state_dir,
        disk_soft_mb=float(
            os.environ.get("LOG_PARSER_TPU_DISK_SOFT_MB", "0") or 0
        ),
        disk_hard_mb=float(
            os.environ.get("LOG_PARSER_TPU_DISK_HARD_MB", "0") or 0
        ),
        mem_soft_mb=float(
            os.environ.get("LOG_PARSER_TPU_MEM_SOFT_MB", "0") or 0
        ),
        retry_ratio=float(
            os.environ.get("LOG_PARSER_TPU_RETRY_BUDGET", "0.1") or 0
        ),
    )
    pressure.install(pressure_ctl)

    router = make_router(
        args.host, args.port, backends,
        vnodes=args.fleet_vnodes, down_after=args.fleet_down_after,
        state_dir=state_dir,
    )
    pressure_ctl.bind_obs(router.obs)
    pressure_ctl.bootstrap()
    pressure_ctl.start()

    budget = None
    if args.fleet_cache_mb > 0 or args.fleet_tenant_budget_mb > 0:
        budget = FleetBudget(args.fleet_cache_mb, args.fleet_tenant_budget_mb)
    controller = FleetController(
        router,
        poll_s=args.fleet_poll_s,
        burn_polls=args.fleet_burn_polls,
        shed_rate=args.fleet_shed_rate,
        thrash_rebuilds=args.fleet_thrash_rebuilds,
        move_cooldown_s=args.fleet_move_cooldown_s,
        budget=budget,
    )
    router.controller = controller

    framed = None
    grpc_front = None
    if args.backends_shim:
        shim_specs = [s.strip() for s in args.backends_shim.split(",")
                      if s.strip()]
        if len(shim_specs) != len(backends):
            log.error(
                "--backends-shim must list one host:port per --backends entry"
            )
            return 2
        if args.shim_port is None:
            log.error("--backends-shim requires --shim-port")
            return 2
        shim_addrs = {}
        for base, spec in zip(backends, shim_specs):
            host, _, port = spec.rpartition(":")
            try:
                shim_addrs[base] = (host or "127.0.0.1", int(port))
            except ValueError:
                log.error("bad --backends-shim entry %r: need host:port",
                          spec)
                return 2
        framed = FramedRouterFront(
            (args.host, args.shim_port), router, shim_addrs
        )
        router.framed_front = framed
        threading.Thread(
            target=framed.serve_forever, name="fleet-framed", daemon=True
        ).start()
        log.info("Framed front on %s:%d", args.host, args.shim_port)
        if args.grpc_port:
            grpc_front = make_grpc_front(
                router, framed, args.host, args.grpc_port
            )
            router.grpc_front = grpc_front
            if grpc_front is not None:
                log.info("gRPC front on %s:%d", args.host, args.grpc_port)
    elif args.grpc_port:
        log.error("--grpc-port on the router requires --backends-shim")
        return 2

    controller.start()
    log.info(
        "Fleet router on %s:%d -> %d backends (%d vnodes each)",
        args.host, args.port, len(backends), args.fleet_vnodes,
    )
    try:
        router.serve_forever()
    except KeyboardInterrupt:
        log.info("Shutting down router")
    finally:
        controller.stop()
        if grpc_front is not None:
            grpc_front.stop(grace=1.0)
        if framed is not None:
            framed.shutdown()
            framed.server_close()
        if router.override_journal is not None:
            router.override_journal.close()
        router.server_close()
        pressure_ctl.stop()
        pressure.install(None)
    return 0


if __name__ == "__main__":
    sys.exit(main())
