"""HTTP serving: the reference's ``POST /parse`` contract plus operational
endpoints the reference lacked (health, frequency admin), guarded by the
engine-wide admission gate (admission.py)."""

from log_parser_tpu.serve.admission import (
    AdmissionController,
    AdmissionRejected,
    shared_gate,
)
from log_parser_tpu.serve.http import ParseServer, make_server

__all__ = [
    "AdmissionController",
    "AdmissionRejected",
    "ParseServer",
    "make_server",
    "shared_gate",
]
