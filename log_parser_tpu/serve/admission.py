"""Admission control + the degradation ladder — ONE gate for every
transport.

The reference accepts unbounded concurrent work (a thread per connection,
no deadline, no shedding — its overload story is "the JVM falls over",
SURVEY.md §5.2). This module is the serving-side half of robustness,
pairing the device-side half (DeviceWatchdog + golden fallback,
runtime/engine.py):

ladder (evaluated per request at admission):

1. **device path** — an in-flight slot is free: full service.
2. **queued** — slots saturated but the bounded wait queue has room: the
   request waits for a slot. What the wait buys depends on the engine:
   with micro-batching on (runtime/batcher.py) the request coalesces
   into the next shared device batch (route ``"batched"`` — a
   first-class outcome with FULL device service, not a degradation);
   otherwise it is served from the cheaper golden host path
   (``engine.analyze_host_routed``), relieving device pressure before
   anything is refused. Both counted separately from error-fallbacks
   (CelerLog-style dynamic fast/slow routing, PAPERS.md).
3. **shed** — queue full, or the request would start past its deadline
   (checked while queued, so a doomed request never does dead work):
   reject with 429 + ``Retry-After``.
4. **drain** — SIGTERM: ``/health/ready`` flips to 503, new work is
   refused (503), in-flight work finishes up to a drain deadline, then
   the process exits.

One rung sits BELOW this ladder, inside the engine: a request whose
fingerprint is quarantined (runtime/quarantine.py — repeated organic
device failures) is still admitted here and spends its slot, but the
engine routes it straight to the golden host path without touching the
device step; only if golden also fails does the caller see 429 +
Retry-After (``QuarantineRejected`` — same wire shape as a shed, but
scoped to ONE poison fingerprint rather than global load).

Deadlines come from ``LOG_PARSER_TPU_DEADLINE_MS`` (0 = none) or the
per-request ``X-Request-Deadline-Ms`` header (header wins). Concurrency
bounds: ``LOG_PARSER_TPU_MAX_INFLIGHT`` (0 = unbounded) and
``LOG_PARSER_TPU_MAX_QUEUE``; drain: ``LOG_PARSER_TPU_DRAIN_S``.

Sharing: :func:`shared_gate` attaches one controller to the engine, so the
HTTP front-end and both shim transports (which each hold the same engine)
admit through the same semaphore — saturating one transport sheds on the
others, exactly like the shared ``state_lock``.
"""

from __future__ import annotations

import os
import threading
import time
from log_parser_tpu import _clock as pclock

ENV_MAX_INFLIGHT = "LOG_PARSER_TPU_MAX_INFLIGHT"
ENV_MAX_QUEUE = "LOG_PARSER_TPU_MAX_QUEUE"
ENV_DEADLINE_MS = "LOG_PARSER_TPU_DEADLINE_MS"
ENV_DRAIN_S = "LOG_PARSER_TPU_DRAIN_S"


class AdmissionRejected(Exception):
    """The gate refused this request (shed or draining). Transports map it
    onto their wire: HTTP 429/503 + Retry-After, shim error envelope, gRPC
    RESOURCE_EXHAUSTED/UNAVAILABLE."""

    def __init__(self, reason: str, retry_after_s: int, status: int):
        hint = (
            f"retry after {retry_after_s}s"
            if retry_after_s > 0
            # 413-style futile shed: the same request can never fit, so
            # promising a retry window would send the client into a loop
            else "retrying will not help"
        )
        super().__init__(f"overloaded: {reason}; {hint}")
        self.reason = reason
        self.retry_after_s = retry_after_s
        self.status = status  # HTTP mapping: 429 shed, 503 draining,
        # 413 request exceeds the tenant's burst capacity outright


class AdmissionController:
    """Bounded in-flight semaphore + bounded wait queue + drain latch."""

    def __init__(
        self,
        max_inflight: int = 0,
        max_queue: int = 0,
        default_deadline_ms: float = 0.0,
        drain_deadline_s: float = 10.0,
        clock=pclock.mono,
    ):
        self.max_inflight = int(max_inflight)
        self.max_queue = int(max_queue)
        self.default_deadline_ms = float(default_deadline_ms)
        self.drain_deadline_s = float(drain_deadline_s)
        self.clock = clock
        self._cv = threading.Condition()
        self._inflight = 0
        self._waiting = 0
        self._draining = False
        # ladder counters (GET /trace/last)
        self.admitted_device = 0
        self.admitted_host = 0
        self.admitted_batched = 0
        self.shed_queue_full = 0
        self.shed_deadline = 0
        self.shed_draining = 0
        self.shed_tenant = 0

    @classmethod
    def from_env(cls, env=None) -> "AdmissionController":
        env = os.environ if env is None else env
        return cls(
            max_inflight=int(env.get(ENV_MAX_INFLIGHT, "0")),
            max_queue=int(env.get(ENV_MAX_QUEUE, "0")),
            default_deadline_ms=float(env.get(ENV_DEADLINE_MS, "0")),
            drain_deadline_s=float(env.get(ENV_DRAIN_S, "10")),
        )

    # ----------------------------------------------------------- admission

    def _retry_after(self) -> int:
        # rough wait estimate: everything ahead of a new arrival, one
        # second per queued/running request, floor 1s (callers hold no lock)
        return max(1, self._waiting + (1 if self._inflight else 0))

    def acquire(
        self,
        deadline_ms: float | None = None,
        batchable: bool = False,
        tenant=None,
        lines: int = 0,
    ) -> str:
        """Admit or refuse one request. Returns the route — ``"device"``
        (free slot), ``"batched"`` (had to queue, but the transport's
        engine runs the micro-batcher: the request coalesces into the next
        device batch — a FIRST-CLASS outcome with full device service, not
        a degradation), or ``"host"`` (had to queue without batching:
        degrade to the host path) — or raises :class:`AdmissionRejected`.
        Callers MUST pair a successful acquire with :meth:`release`
        (passing the same ``tenant``).

        ``deadline_ms`` is this request's budget from arrival (header);
        None uses the configured default; 0/negative budget means none.

        ``tenant`` is an optional :class:`~log_parser_tpu.runtime.tenancy.
        TenantQuota` refining this shared gate per tenant: a lines/s
        token bucket debited with ``lines``, an in-flight cap, and a
        queue share — each shed as 429 before the request can crowd the
        global bounds (413 with no Retry-After when one request declares
        more lines than the bucket's whole burst capacity: retrying it
        is futile). Quota counters are mutated under ``_cv`` so they
        need no lock of their own.
        """
        if deadline_ms is None:
            deadline_ms = self.default_deadline_ms
        deadline = (
            self.clock() + deadline_ms / 1e3 if deadline_ms and deadline_ms > 0
            else None
        )
        if tenant is not None:
            from log_parser_tpu.runtime import faults

            faults.fire("tenant_quota")  # conlint: contained-by-caller (transports map the escape like any analyze failure)
        with self._cv:
            if self._draining:
                self.shed_draining += 1
                raise AdmissionRejected("draining", self._retry_after(), 503)
            if tenant is not None:
                wait_s = tenant.debit_lines(lines)
                if wait_s is not None:
                    self.shed_tenant += 1
                    if wait_s == float("inf"):
                        # the request declares more lines than the bucket
                        # can EVER hold: no Retry-After, 413 — a retry of
                        # the same request is futile and the client must
                        # know (split it or raise the tenant's burst)
                        tenant.shed_oversize += 1
                        raise AdmissionRejected("tenant burst", 0, 413)
                    tenant.shed_rate += 1
                    raise AdmissionRejected(
                        "tenant rate", max(1, int(wait_s + 0.999)), 429
                    )
                if (
                    tenant.max_inflight > 0
                    and tenant.inflight >= tenant.max_inflight
                ):
                    tenant.shed_inflight += 1
                    self.shed_tenant += 1
                    raise AdmissionRejected(
                        "tenant inflight", self._retry_after(), 429
                    )
            if self.max_inflight <= 0 or self._inflight < self.max_inflight:
                # unbounded mode still counts in-flight so drain can wait
                self._inflight += 1
                self.admitted_device += 1
                self._tenant_admit(tenant, lines)
                return "device"
            if tenant is not None and tenant.max_queued > 0 \
                    and tenant.queued >= tenant.max_queued:
                # queue share: one noisy tenant cannot occupy the whole
                # global wait queue
                tenant.shed_queue += 1
                self.shed_tenant += 1
                raise AdmissionRejected(
                    "tenant queue", self._retry_after(), 429
                )
            if self._waiting >= self.max_queue:
                self.shed_queue_full += 1
                raise AdmissionRejected("queue full", self._retry_after(), 429)
            self._waiting += 1
            if tenant is not None:
                tenant.queued += 1
            try:
                while True:
                    if self._draining:
                        self.shed_draining += 1
                        raise AdmissionRejected(
                            "draining", self._retry_after(), 503
                        )
                    if self._inflight < self.max_inflight and (
                        tenant is None
                        or tenant.max_inflight <= 0
                        or tenant.inflight < tenant.max_inflight
                    ):
                        # queue head: starting past the deadline is dead
                        # work — shed instead
                        if deadline is not None and self.clock() >= deadline:
                            self.shed_deadline += 1
                            raise AdmissionRejected(
                                "deadline", self._retry_after(), 429
                            )
                        self._inflight += 1
                        self._tenant_admit(tenant, lines)
                        if batchable:
                            # queued-then-batched: the wait bought this
                            # request a shared device batch, not the
                            # golden host path — count it as admission,
                            # not degradation
                            self.admitted_batched += 1
                            return "batched"
                        self.admitted_host += 1
                        return "host"
                    timeout = (
                        None if deadline is None else deadline - self.clock()
                    )
                    if timeout is not None and timeout <= 0:
                        self.shed_deadline += 1
                        raise AdmissionRejected(
                            "deadline", self._retry_after(), 429
                        )
                    self._cv.wait(timeout)
            finally:
                self._waiting -= 1
                if tenant is not None:
                    tenant.queued -= 1

    @staticmethod
    def _tenant_admit(tenant, lines: int) -> None:
        # caller holds _cv
        if tenant is not None:
            tenant.inflight += 1
            tenant.admitted += 1
            tenant.lines_admitted += int(lines)

    def release(self, tenant=None) -> None:
        with self._cv:
            self._inflight -= 1
            if tenant is not None:
                tenant.inflight -= 1
            self._cv.notify_all()

    # --------------------------------------------------------------- drain

    @property
    def draining(self) -> bool:
        with self._cv:
            return self._draining

    def begin_drain(self) -> None:
        """Refuse new work from now on; queued waiters are woken and shed."""
        with self._cv:
            self._draining = True
            self._cv.notify_all()

    def wait_idle(self, timeout_s: float | None = None) -> bool:
        """Block until no request is in flight (True) or the drain deadline
        passes (False — the operator chose to abandon stragglers)."""
        if timeout_s is None:
            timeout_s = self.drain_deadline_s
        with self._cv:
            return self._cv.wait_for(
                lambda: self._inflight == 0, timeout=timeout_s
            )

    # ------------------------------------------------------- observability

    @property
    def inflight(self) -> int:
        with self._cv:
            return self._inflight

    @property
    def queued(self) -> int:
        with self._cv:
            return self._waiting

    def stats(self) -> dict:
        with self._cv:
            return {
                "maxInflight": self.max_inflight,
                "maxQueue": self.max_queue,
                "inflight": self._inflight,
                "queued": self._waiting,
                "draining": self._draining,
                "admittedDevice": self.admitted_device,
                "admittedHost": self.admitted_host,
                "admittedBatched": self.admitted_batched,
                "shedQueueFull": self.shed_queue_full,
                "shedDeadline": self.shed_deadline,
                "shedDraining": self.shed_draining,
                "shedTenant": self.shed_tenant,
            }


_ATTACH_LOCK = threading.Lock()

# /metrics view over AdmissionController.stats() — registered against
# the engine's obs bundle in shared_gate() and read at scrape time, so
# the ladder counters have exactly one home (log_parser_tpu/obs)
METRIC_SAMPLES = (
    ("admittedDevice", "logparser_admission_total", {"outcome": "device"}),
    ("admittedHost", "logparser_admission_total", {"outcome": "host"}),
    ("admittedBatched", "logparser_admission_total", {"outcome": "batched"}),
    ("shedQueueFull", "logparser_admission_total",
     {"outcome": "shed_queue_full"}),
    ("shedDeadline", "logparser_admission_total",
     {"outcome": "shed_deadline"}),
    ("shedDraining", "logparser_admission_total",
     {"outcome": "shed_draining"}),
    ("shedTenant", "logparser_admission_total", {"outcome": "shed_tenant"}),
    ("inflight", "logparser_inflight", {}),
    ("queued", "logparser_admission_queued", {}),
)


def shared_gate(engine) -> AdmissionController:
    """The engine-wide admission gate, created on first use (env-config)
    and attached to the engine so every transport wrapping this engine —
    HTTP, framed shim, gRPC — admits through the same bounded semaphore."""
    with _ATTACH_LOCK:
        gate = getattr(engine, "admission_gate", None)
        if gate is None:
            gate = AdmissionController.from_env()
            engine.admission_gate = gate
            obs = getattr(engine, "obs", None)
            if obs is not None:
                obs.add_stats_collector("admission", gate.stats, METRIC_SAMPLES)
        return gate


def install_drain_handlers(
    server, gate, log, on_second_signal=None, on_drained=None
):
    """Route SIGTERM/SIGINT through the drain path: flip the gate (readiness
    goes 503, new work refused), let in-flight requests finish up to the
    drain deadline, then stop ``server``'s accept loop — ``serve_forever``
    returns and the caller's normal shutdown sequence (follower sentinel,
    server_close) runs exactly as on a clean exit, never mid-request.

    ``on_drained`` runs after the in-flight wait, before the accept loop
    stops — the journal flush hook: every frequency record the drained
    requests appended is fsync'd before the process exits (a clean
    shutdown must never need replay).

    A second signal skips the wait and stops immediately. Returns the
    handler (so tests can invoke it without a real signal). Must be called
    from the main thread (CPython signal rule)."""
    import signal

    state = {"signals": 0}

    def _drain():
        drained = gate.wait_idle()
        if not drained:
            log.warning(
                "drain deadline (%.1fs) passed with %d request(s) still "
                "in flight; stopping anyway",
                gate.drain_deadline_s,
                gate.inflight,
            )
        if on_drained is not None:
            try:
                on_drained()
            except Exception:
                log.exception("on_drained hook failed; stopping anyway")
        server.shutdown()

    def _handler(signum, frame):
        state["signals"] += 1
        if state["signals"] > 1:
            log.info("second signal: stopping immediately")
            if on_drained is not None:
                # best-effort durability even on an impatient operator's
                # double ^C — a flush is milliseconds
                try:
                    on_drained()
                except Exception:
                    log.exception("on_drained hook failed on second signal")
            if on_second_signal is not None:
                on_second_signal()
            server.shutdown()
            return
        log.info(
            "signal %d: draining (readiness 503, %d in flight, up to %.1fs)",
            signum,
            gate.inflight,
            gate.drain_deadline_s,
        )
        gate.begin_drain()
        # serve_forever blocks the main thread (where this handler runs);
        # the idle-wait + shutdown must happen off-thread
        threading.Thread(target=_drain, name="drain", daemon=True).start()

    signal.signal(signal.SIGTERM, _handler)
    signal.signal(signal.SIGINT, _handler)
    return _handler
