"""PatternBank — the immutable compiled form of a pattern library.

Where the reference re-compiles every regex on every request into mutable
singleton objects (AnalysisService.java:55-86 — the latent race of SURVEY.md
§5.2), this framework compiles the whole library exactly once into an
immutable bank of automata plus the static index structure the scoring
kernel needs:

- every distinct regex (primary, secondary, sequence-event, plus the four
  hardcoded context regexes of ContextAnalysisService.java:27-34) gets one
  *matcher column*; match kernels produce a ``[lines, columns]`` boolean
  cube, and all scoring factors are computed from column indexes;
- per-pattern static arrays (confidence, severity multiplier, context
  window sizes) are precomputed as numpy arrays ready to close over in the
  jitted scoring kernel;
- each matcher column carries its compiled DFA when the automaton path
  supports the regex, or a host-side compiled ``re`` fallback when it does
  not (and, when even the golden translation fails, the pattern is skipped
  with the same per-pattern containment as the golden engine).
"""

from __future__ import annotations

import dataclasses
import logging
import re

import numpy as np

from log_parser_tpu.golden.engine import SEVERITY_MULTIPLIERS
from log_parser_tpu.golden.javacompat import compile_java_regex
from log_parser_tpu.models.pattern import Pattern, PatternSet
from log_parser_tpu.patterns.regex import (
    CompiledDfa,
    DfaLimitError,
    RegexUnsupportedError,
    extract_literals,
    parse_java_regex,
)
from log_parser_tpu.patterns.regex.cache import compile_regex_to_dfa_cached
from log_parser_tpu.patterns.regex.literals import exact_sequences
from log_parser_tpu.patterns.regex.literals import Literal

log = logging.getLogger(__name__)

# the four hardcoded context regexes — ContextAnalysisService.java:27-34
CONTEXT_REGEXES: list[tuple[str, bool]] = [
    (r"\b(ERROR|FATAL|CRITICAL|SEVERE)\b", True),
    (r"\b(WARN|WARNING)\b", True),
    (r"^\s*at\s+[\w\.\$]+\(.*\)\s*$", False),
    (r"\b\w*Exception\b|\b\w*Error\b", False),
]
CTX_ERROR, CTX_WARN, CTX_STACK, CTX_EXCEPTION = range(4)


class MatcherColumn:
    """One distinct regex to evaluate per line.

    Matcher tier (first that applies): ``exact_seqs`` → bit-parallel
    Shift-Or (O(1) in bank size per line-byte); ``dfa`` → packed automaton
    bank; neither → host ``re`` over every line.

    ``host`` (the golden-compiled reference matcher) is LAZY: eagerly
    compiling it for every column cost ~5 s/10k patterns at boot, while
    only host-tier columns and override lines ever use it. The snapshot
    path (libcache.py) relies on this — validation already happened when
    the snapshot was built."""

    __slots__ = ("regex", "case_insensitive", "dfa", "literals",
                 "exact_seqs", "_host")

    def __init__(
        self,
        regex: str,
        case_insensitive: bool,
        dfa: CompiledDfa | None,  # None -> host fallback only
        literals: frozenset[Literal] | None,  # None -> unfactorable
        exact_seqs: tuple | None = None,  # fixed byte-class seqs == regex
        host: re.Pattern[str] | None = None,
    ):
        self.regex = regex
        self.case_insensitive = case_insensitive
        self.dfa = dfa
        self.literals = literals
        self.exact_seqs = exact_seqs
        self._host = host

    @property
    def host(self) -> re.Pattern[str]:
        if self._host is None:
            self._host = compile_java_regex(self.regex, self.case_insensitive)
        return self._host

    def __getstate__(self):
        return (self.regex, self.case_insensitive, self.dfa, self.literals,
                self.exact_seqs)

    def __setstate__(self, state):
        (self.regex, self.case_insensitive, self.dfa, self.literals,
         self.exact_seqs) = state
        self._host = None


@dataclasses.dataclass
class SecondaryEntry:
    pattern_idx: int
    column: int
    weight: float
    window: int  # already min'd with config max_window by the kernel


@dataclasses.dataclass
class SequenceEntry:
    pattern_idx: int
    bonus: float
    event_columns: list[int]  # in sequence order


class PatternBank:
    """Compiled, immutable library: matcher columns + static scoring arrays.

    ``patterns`` holds the kept patterns in discovery order (set-major, then
    pattern order within the set — AnalysisService.java:91-92), which is the
    order events must be emitted in.
    """

    def __init__(self, pattern_sets: list[PatternSet]):
        from log_parser_tpu.patterns import libcache

        self.pattern_sets = pattern_sets
        self.columns: list[MatcherColumn] = []
        self._column_by_key: dict[tuple[str, bool], int] = {}

        self.patterns: list[Pattern] = []
        self.skipped_patterns: list[tuple[str, str]] = []
        primary_cols: list[int] = []
        self.secondaries: list[SecondaryEntry] = []
        self.sequences: list[SequenceEntry] = []

        key = libcache.library_key(pattern_sets, CONTEXT_REGEXES)
        snap = libcache.load(key)
        if snap is not None:
            try:
                # whole-library warm path: one read replaces every
                # per-column parse/DFA/literal build and every eager
                # golden re compile
                columns = snap["columns"]
                by_index = [
                    (ps.patterns or [])[pi]
                    for ps, kept in zip(
                        pattern_sets, snap["kept"], strict=True
                    )
                    for pi in kept
                ]
                self.columns = columns
                self._column_by_key = {
                    (c.regex, c.case_insensitive): i
                    for i, c in enumerate(columns)
                }
                self.patterns = by_index
                self.skipped_patterns = list(snap["skipped"])
                primary_cols = list(snap["primary_cols"])
                self.secondaries = list(snap["secondaries"])
                self.sequences = list(snap["sequences"])
                if self.skipped_patterns:
                    # the cold build logged each skip with its reason;
                    # keep the fact visible on every warm boot too
                    log.warning(
                        "Bank snapshot restored %d skipped pattern(s): %s",
                        len(self.skipped_patterns),
                        [pid for pid, _ in self.skipped_patterns[:10]],
                    )
            except Exception as exc:  # malformed snapshot: rebuild cold
                log.warning("Bank snapshot restore failed, rebuilding: %s", exc)
                self.columns = []
                self._column_by_key = {}
                self.patterns = []
                self.skipped_patterns = []
                primary_cols = []
                self.secondaries = []
                self.sequences = []
                snap = None
        # key -> (dfa, literals | None, exact_seqs | None) from the
        # batched native prepass
        self._dfa_prebuilt: dict[tuple[str, bool], tuple] = {}
        if snap is None:
            self._batch_precompile(pattern_sets)
            # context columns first so their indexes are the CTX_* consts
            for rx, ci in CONTEXT_REGEXES:
                self._intern_column(rx, ci)

            kept: list[list[int]] = []
            for ps in pattern_sets:
                kept.append([])
                for pi, pattern in enumerate(ps.patterns or []):
                    mark = len(self.columns)
                    try:
                        entry = self._compile_pattern(pattern, len(self.patterns))
                    except (ValueError, re.error) as exc:
                        log.error("Skipping pattern %r: %s", pattern.id, exc)
                        self.skipped_patterns.append((pattern.id, str(exc)))
                        # roll back columns interned for the aborted
                        # pattern so the match kernels never pay for
                        # orphan regexes
                        for col in self.columns[mark:]:
                            del self._column_by_key[
                                (col.regex, col.case_insensitive)
                            ]
                        del self.columns[mark:]
                        continue
                    if entry is None:  # primary-less: compiles, never matches
                        continue
                    pcol, secs, seqs = entry
                    self.patterns.append(pattern)
                    kept[-1].append(pi)
                    primary_cols.append(pcol)
                    self.secondaries.extend(secs)
                    self.sequences.extend(seqs)
            libcache.save(
                key,
                {
                    "columns": self.columns,
                    "kept": kept,
                    "skipped": self.skipped_patterns,
                    "primary_cols": primary_cols,
                    "secondaries": self.secondaries,
                    "sequences": self.sequences,
                },
            )

        self._dfa_prebuilt.clear()
        self.primary_columns = np.asarray(primary_cols, dtype=np.int32)
        self.n_patterns = len(self.patterns)
        self.n_columns = len(self.columns)

        # ---- static per-pattern scoring arrays -----------------------------
        self.confidence = np.asarray(
            [p.primary_pattern.confidence for p in self.patterns], dtype=np.float64
        )
        self.severity_multiplier = np.asarray(
            [
                SEVERITY_MULTIPLIERS.get((p.severity or "").upper(), 1.0)
                for p in self.patterns
            ],
            dtype=np.float64,
        )
        self.has_context_rules = np.asarray(
            [p.context_extraction is not None for p in self.patterns], dtype=bool
        )
        # negative YAML window values behave as 0 in the golden semantics:
        # Python slices like lines[max(0, idx-(-5)):idx] are simply empty
        self.ctx_before = np.asarray(
            [
                max(0, p.context_extraction.lines_before) if p.context_extraction else 0
                for p in self.patterns
            ],
            dtype=np.int32,
        )
        self.ctx_after = np.asarray(
            [
                max(0, p.context_extraction.lines_after) if p.context_extraction else 0
                for p in self.patterns
            ],
            dtype=np.int32,
        )
        # empty-trimmed pattern id => frequency tracking applies
        # (FrequencyTrackingService.java:42,65)
        self.has_freq_id = np.asarray(
            [bool((p.id or "").strip()) for p in self.patterns], dtype=bool
        )
        # patterns sharing an id share one frequency counter: map each
        # pattern to a counter slot
        self.freq_ids: list[str] = []
        slot_by_id: dict[str, int] = {}
        slots = []
        for p in self.patterns:
            pid = p.id or ""
            if not pid.strip():
                slots.append(-1)
                continue
            if pid not in slot_by_id:
                slot_by_id[pid] = len(self.freq_ids)
                self.freq_ids.append(pid)
            slots.append(slot_by_id[pid])
        self.freq_slot = np.asarray(slots, dtype=np.int32)
        self.n_freq_slots = len(self.freq_ids)

    # ------------------------------------------------------------------ build

    # NOTE: changing what _intern_column/_compile_pattern build or how
    # skip decisions are made requires bumping libcache.SNAPSHOT_VERSION —
    # warm boots restore their outputs from the content-keyed snapshot.
    def _batch_precompile(self, pattern_sets: list[PatternSet]) -> None:
        """Compile every column regex the cold build will need through the
        native batched parse→NFA→DFA pipeline in ONE call (the per-regex
        Python pipeline costs ~4 s of a 10k-library boot in parse + NFA +
        ctypes crossings alone).  Disk-cached keys are left to the cache
        read path; native declines (unsupported constructs, state caps)
        are simply absent from the prebuilt map, so ``_intern_column``
        reproduces the exact Python-pipeline classification for them."""
        from log_parser_tpu.native.dfabuild import build_dfas_batch

        keys: list[tuple[str, bool]] = list(CONTEXT_REGEXES)
        for ps in pattern_sets:
            for p in ps.patterns or []:
                if p.primary_pattern is None:
                    continue  # validation-only: no column interned
                keys.append((p.primary_pattern.regex, False))
                for sec in p.secondary_patterns or []:
                    keys.append((sec.regex, False))
                for seq in p.sequence_patterns or []:
                    for ev in seq.events or []:
                        keys.append((ev.regex, False))
        seen: set[tuple[str, bool]] = set()
        todo = []
        for k in keys:
            if k not in seen:
                seen.add(k)
                todo.append(k)
        if not todo:
            return
        # no disk-cache consultation: the one-call native pipeline is
        # FASTER than 10k individual pack reads + Python parses, so the
        # per-regex cache only serves native DECLINES (in _intern_column's
        # fallback) and hosts without a toolchain (batch is None)
        batch = build_dfas_batch(todo, with_extraction=True)
        if batch is None:  # native lib unavailable: per-column fallback
            return
        for (regex, ci), item in zip(todo, batch):
            if item is None:
                continue
            (trans, byte_class, accept, start), lits, seqs = item
            dfa = CompiledDfa(
                regex=regex,
                trans=trans,
                byte_class=byte_class,
                accept_end=accept,
                start=start,
                n_states=trans.shape[0],
                n_classes=trans.shape[1],
            )
            self._dfa_prebuilt[(regex, ci)] = (dfa, lits, seqs)

    def _intern_column(self, regex: str, case_insensitive: bool) -> int:
        key = (regex, case_insensitive)
        col = self._column_by_key.get(key)
        if col is not None:
            return col
        host = compile_java_regex(regex, case_insensitive)  # raises -> skip pattern
        dfa: CompiledDfa | None = None
        literals: frozenset[Literal] | None = None
        exact_seqs = None
        pre = self._dfa_prebuilt.get(key)
        if pre is not None:
            # batched native prepass already parsed, extracted, and
            # determinized this regex — skip the whole Python pipeline
            dfa, literals, exact_seqs = pre
        else:
            try:
                node = parse_java_regex(regex, case_insensitive)
                exact_seqs = exact_sequences(node)
                literals = extract_literals(node)
                # DFA is compiled (cache-amortized) even for
                # Shift-Or-capable columns: MatcherBanks picks the tier
                # per bank size; the parsed node rides along so a cache
                # miss doesn't re-parse
                dfa = compile_regex_to_dfa_cached(
                    regex, case_insensitive, node=node
                )
            except (RegexUnsupportedError, DfaLimitError) as exc:
                if exact_seqs is None:
                    if literals is None:
                        # host-only column (lookaround/backref): a
                        # lenient language-WIDENING parse can still
                        # yield required literals, which lets the engine
                        # prefilter candidate lines instead of running
                        # host re over every line of every request (the
                        # 50x cliff of VERDICT r3 #3)
                        try:
                            literals = extract_literals(
                                parse_java_regex(regex, case_insensitive,
                                                 lenient=True)
                            )
                        except (RegexUnsupportedError, ValueError):
                            literals = None
                    if literals is None:
                        log.warning(
                            "Host-fallback matcher for %r (%s): NO literal "
                            "prefilter — every request pays a full host-re "
                            "scan over every log line for this pattern",
                            regex, exc,
                        )
                    else:
                        log.warning(
                            "Host-fallback matcher for %r (%s): literal-"
                            "prefiltered host verification", regex, exc,
                        )
        col = len(self.columns)
        self.columns.append(
            MatcherColumn(
                regex=regex,
                case_insensitive=case_insensitive,
                dfa=dfa,
                host=host,
                literals=literals,
                exact_seqs=exact_seqs,
            )
        )
        self._column_by_key[key] = col
        return col

    def _compile_pattern(
        self, pattern: Pattern, pattern_idx: int
    ) -> tuple[int, list[SecondaryEntry], list[SequenceEntry]] | None:
        """Returns None for a primary-less pattern (it can never match, but
        its secondary/sequence regexes are still validated so bad ones land
        in ``skipped_patterns`` exactly like the golden engine's)."""
        if pattern.primary_pattern is None:
            for sec in pattern.secondary_patterns or []:
                compile_java_regex(sec.regex)
            for seq in pattern.sequence_patterns or []:
                for ev in seq.events or []:
                    compile_java_regex(ev.regex)
            return None
        pcol = self._intern_column(pattern.primary_pattern.regex, False)
        secs = [
            SecondaryEntry(
                pattern_idx=pattern_idx,
                column=self._intern_column(sec.regex, False),
                weight=sec.weight,
                window=sec.proximity_window,
            )
            for sec in pattern.secondary_patterns or []
        ]
        seqs = []
        for seq in pattern.sequence_patterns or []:
            events = seq.events or []
            seqs.append(
                SequenceEntry(
                    pattern_idx=pattern_idx,
                    bonus=seq.bonus_multiplier,
                    event_columns=[
                        self._intern_column(ev.regex, False) for ev in events
                    ],
                )
            )
        return pcol, secs, seqs
