"""Whole-library compile snapshot keyed by pattern-set content hash.

The per-regex DFA cache (regex/cache.py) already amortizes NFA→DFA
construction, but a warm 10k-library boot still paid ~20 s: ~1 ms of
npz/zipfile overhead per cached regex read, times every interned column,
times every bank the engine builds (the full bank plus one per pattern
shard), plus eager golden ``re`` compilation and literal extraction for
every column. The reference reloads its library in milliseconds
(PatternService.java:45-69 — it just parses YAML; compilation happens
per request); boot-time parity needs the whole *compiled bank* to load
in one read.

This module snapshots the expensive half of ``PatternBank.__init__`` —
interned columns (DFA tables, exact sequences, literal factors), kept /
skipped pattern decisions, secondary and sequence index entries — into
ONE pickle file keyed by ``sha256`` of the full serialized pattern sets
plus every compiler version that shapes the output. Golden ``re``
patterns are NOT stored: columns recompile them lazily on first use
(``MatcherColumn.host``), and the snapshot records that validation
already succeeded (the build is deterministic, so the same library
makes the same skip decisions).

Trust model: the cache directory (``$LOG_PARSER_TPU_CACHE`` or
``~/.cache/log_parser_tpu``) is user-private (created 0700) and written
only by this process — the same trust boundary as JAX's persistent
executable cache, which deserializes compiled binaries from the same
tree. Entries are pickles; do not point the cache at untrusted storage.
Corrupt or version-skewed entries are ignored and rebuilt.

Disable with ``LOG_PARSER_TPU_CACHE=0`` (shared switch with the DFA
cache); ``LOG_PARSER_TPU_LIBCACHE=0`` disables just this layer.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import pathlib
import pickle
import sys
from typing import Any

from log_parser_tpu.patterns.regex.cache import (
    COMPILER_VERSION,
    atomic_publish,
    cache_subdir,
)
from log_parser_tpu.patterns.regex.literals import LITERALS_VERSION

log = logging.getLogger(__name__)

# BUMP when the bank-build logic changes what a snapshot stores or how
# kept/skipped decisions are made (PatternBank._compile_pattern /
# _intern_column) — the content hash cannot see code edits. The Python
# minor version is also folded into the key: skip decisions encode
# ``re``-module acceptance, which changes across interpreter versions,
# and warm boots trust them without revalidating.
SNAPSHOT_VERSION = 2


def _dir() -> pathlib.Path | None:
    if os.environ.get("LOG_PARSER_TPU_LIBCACHE") == "0":
        return None
    return cache_subdir("bank")


def library_key(pattern_sets, context_regexes) -> str | None:
    """Deterministic content hash, or None when the sets don't serialize
    (unhashable custom objects — then the cache is skipped)."""
    try:
        payload = json.dumps(
            [ps.to_dict() for ps in pattern_sets],
            sort_keys=True,
            ensure_ascii=False,
            default=repr,
        )
    except Exception:
        return None
    h = hashlib.sha256()
    h.update(
        f"bank-v{SNAPSHOT_VERSION}|dfa-v{COMPILER_VERSION}"
        f"|lit-v{LITERALS_VERSION}|py-{sys.version_info[0]}.{sys.version_info[1]}"
        f"|ctx={context_regexes!r}|".encode()
    )
    h.update(payload.encode())
    return h.hexdigest()


def load(key: str | None) -> dict[str, Any] | None:
    d = _dir()
    if d is None or key is None:
        return None
    path = d / f"{key}.pkl"
    if not path.exists():
        return None
    try:
        with open(path, "rb") as f:
            snap = pickle.load(f)
        if snap.get("version") != SNAPSHOT_VERSION:
            return None
        return snap
    except Exception as exc:
        log.warning("Ignoring corrupt bank snapshot %s: %s", path.name, exc)
        return None


def save(key: str | None, snap: dict[str, Any]) -> None:
    d = _dir()
    if d is None or key is None:
        return
    snap = dict(snap, version=SNAPSHOT_VERSION)
    try:
        d.mkdir(parents=True, exist_ok=True)
        os.chmod(d, 0o700)
    except OSError as exc:
        log.warning("Bank snapshot dir unavailable: %s", exc)
        return
    atomic_publish(
        d,
        f"{key}.pkl",
        lambda f: pickle.dump(snap, f, protocol=pickle.HIGHEST_PROTOCOL),
    )
