"""Whole-library compile snapshot keyed by pattern-set content hash.

The per-regex DFA cache (regex/cache.py) already amortizes NFA→DFA
construction, but a warm 10k-library boot still paid ~20 s: ~1 ms of
npz/zipfile overhead per cached regex read, times every interned column,
times every bank the engine builds (the full bank plus one per pattern
shard), plus eager golden ``re`` compilation and literal extraction for
every column. The reference reloads its library in milliseconds
(PatternService.java:45-69 — it just parses YAML; compilation happens
per request); boot-time parity needs the whole *compiled bank* to load
in one read.

This module snapshots the expensive half of ``PatternBank.__init__`` —
interned columns (DFA tables, exact sequences, literal factors), kept /
skipped pattern decisions, secondary and sequence index entries — into
ONE pickle file keyed by ``sha256`` of the full serialized pattern sets
plus every compiler version that shapes the output. Golden ``re``
patterns are NOT stored: columns recompile them lazily on first use
(``MatcherColumn.host``), and the snapshot records that validation
already succeeded (the build is deterministic, so the same library
makes the same skip decisions).

Trust model: the cache directory (``$LOG_PARSER_TPU_CACHE`` or
``~/.cache/log_parser_tpu``) is user-private (created 0700) and written
only by this process — the same trust boundary as JAX's persistent
executable cache, which deserializes compiled binaries from the same
tree. Entries are pickles; do not point the cache at untrusted storage.

Crash safety: entries publish via write-to-temp + fsync + atomic rename
(regex/cache.py ``atomic_publish``) with a sha256 content checksum in a
``<key>.pkl.sum`` sidecar — the snapshot file itself stays a bare pickle
so older readers (and tests) keep working. A checksum mismatch or an
unreadable pickle quarantines the entry (renamed ``<key>.pkl.corrupt``,
kept for post-mortems) and the bank rebuilds cold; nothing raises out of
:func:`load`. A sidecar-less entry is trusted like before (legacy /
hand-placed entries). Only the half-open window between publishing the
snapshot and its sidecar can misclassify a good entry, and the cost is
one rebuild, not wrong scores. The ``cache`` fault site
(``LOG_PARSER_TPU_FAULTS=cache_raise``) injects read failures here —
contained as a miss, never a quarantine of a healthy entry.

Compiled-group substructure sharing: the content key already proves two
banks identical, so within one process every bank built from the same
key SHARES one snapshot object — 1,000 tenants on the same infra
patterns hold one DFA pack, not 1,000 pickle-copies of it.
``PatternBank``'s warm path assigns ``snap["columns"]`` by reference and
``MatcherColumn`` is immutable (lazy ``host`` compile is idempotent), so
aliasing the pack across engines is safe by construction. The memo is
keyed by (cache dir, content key), LRU-bounded
(``LOG_PARSER_TPU_PACK_CACHE`` entries, default 64) so tenant eviction
still frees memory for fleets of *distinct* banks, and disabled together
with the layer (or alone via ``LOG_PARSER_TPU_PACK_SHARE=0``).

Disable with ``LOG_PARSER_TPU_CACHE=0`` (shared switch with the DFA
cache); ``LOG_PARSER_TPU_LIBCACHE=0`` disables just this layer.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import pathlib
import pickle
import sys
import threading
from collections import OrderedDict
from typing import Any

from log_parser_tpu.patterns.regex.cache import (
    COMPILER_VERSION,
    atomic_publish,
    cache_subdir,
)
from log_parser_tpu.patterns.regex.literals import LITERALS_VERSION

log = logging.getLogger(__name__)

# BUMP when the bank-build logic changes what a snapshot stores or how
# kept/skipped decisions are made (PatternBank._compile_pattern /
# _intern_column) — the content hash cannot see code edits. The Python
# minor version is also folded into the key: skip decisions encode
# ``re``-module acceptance, which changes across interpreter versions,
# and warm boots trust them without revalidating.
SNAPSHOT_VERSION = 2


def _dir() -> pathlib.Path | None:
    if os.environ.get("LOG_PARSER_TPU_LIBCACHE") == "0":
        return None
    return cache_subdir("bank")


# ------------------------------------------------- shared compiled packs

_DEFAULT_PACK_ENTRIES = 64

_pack_lock = threading.Lock()
# (cache dir, content key) -> snapshot dict, LRU order. Keyed by dir so
# tests pointing LOG_PARSER_TPU_CACHE at a tmpdir never see another
# run's packs.
_packs: OrderedDict[tuple[str, str], dict[str, Any]] = OrderedDict()
_pack_stats = {"built": 0, "shared": 0}


def _pack_limit() -> int:
    try:
        return max(0, int(os.environ.get("LOG_PARSER_TPU_PACK_CACHE",
                                         _DEFAULT_PACK_ENTRIES)))
    except ValueError:
        return _DEFAULT_PACK_ENTRIES


def _share_enabled() -> bool:
    return (os.environ.get("LOG_PARSER_TPU_PACK_SHARE") != "0"
            and _pack_limit() > 0)


def _attr_values(obj: Any):
    if hasattr(obj, "__dict__"):
        return list(vars(obj).values())
    return [getattr(obj, s, None) for s in getattr(obj, "__slots__", ())]


def _pack_bytes(snap: dict[str, Any]) -> int:
    """Approximate resident bytes of one pack: the numpy DFA planes are
    the dominant term; everything else is noise. The planes live one
    level down (MatcherColumn.dfa is a CompiledDfa holding the
    ndarrays), so descend one attribute level."""
    total = 0
    for column in snap.get("columns", ()) or ():
        for value in _attr_values(column):
            nbytes = getattr(value, "nbytes", None)
            if isinstance(nbytes, int):
                total += nbytes
            elif value is not None and not isinstance(
                value, (str, bytes, int, float, bool, frozenset, tuple)
            ):
                for inner in _attr_values(value):
                    nbytes = getattr(inner, "nbytes", None)
                    if isinstance(nbytes, int):
                        total += nbytes
    return total


def _pack_get(dir_key: str, key: str) -> dict[str, Any] | None:
    with _pack_lock:
        snap = _packs.get((dir_key, key))
        if snap is not None:
            _packs.move_to_end((dir_key, key))
            _pack_stats["shared"] += 1
        return snap


def _pack_put(dir_key: str, key: str, snap: dict[str, Any]) -> None:
    limit = _pack_limit()
    with _pack_lock:
        if (dir_key, key) not in _packs:
            _pack_stats["built"] += 1
        _packs[(dir_key, key)] = snap
        _packs.move_to_end((dir_key, key))
        while len(_packs) > limit:
            _packs.popitem(last=False)


def pack_stats() -> dict[str, Any]:
    """Sharing counters for tests and bench artifacts: ``built`` packs
    entered the memo, ``shared`` warm loads were answered from it (no
    disk read, no pickle copy), ``sharedBytes`` estimates what one
    resident pack weighs times its extra users."""
    with _pack_lock:
        resident = len(_packs)
        shared = _pack_stats["shared"]
        built = _pack_stats["built"]
        shared_bytes = sum(_pack_bytes(s) for s in _packs.values())
    return {
        "built": built,
        "shared": shared,
        "resident": resident,
        "residentBytes": shared_bytes,
    }


def reset_packs() -> None:
    """Drop the memo and zero the counters (test isolation)."""
    with _pack_lock:
        _packs.clear()
        _pack_stats["built"] = 0
        _pack_stats["shared"] = 0


def library_key(pattern_sets, context_regexes) -> str | None:
    """Deterministic content hash, or None when the sets don't serialize
    (unhashable custom objects — then the cache is skipped)."""
    try:
        payload = json.dumps(
            [ps.to_dict() for ps in pattern_sets],
            sort_keys=True,
            ensure_ascii=False,
            default=repr,
        )
    except Exception:
        return None
    h = hashlib.sha256()
    h.update(
        f"bank-v{SNAPSHOT_VERSION}|dfa-v{COMPILER_VERSION}"
        f"|lit-v{LITERALS_VERSION}|py-{sys.version_info[0]}.{sys.version_info[1]}"
        f"|ctx={context_regexes!r}|".encode()
    )
    h.update(payload.encode())
    return h.hexdigest()


def _sidecar(path: pathlib.Path) -> pathlib.Path:
    # ".pkl.sum", NOT ".sum": it must never match the "*.pkl" globs that
    # enumerate snapshots (tests and cleanup scripts count entries so)
    return path.with_name(path.name + ".sum")


def _quarantine(path: pathlib.Path, reason: str) -> None:
    """Move a corrupt entry aside (``.corrupt``) instead of deleting it —
    the bytes are the post-mortem — and drop its sidecar so the name
    reads as a plain miss from now on. Best-effort: an entry we cannot
    even rename is still just a miss."""
    log.warning("Quarantining corrupt bank snapshot %s: %s", path.name, reason)
    try:
        os.replace(path, path.with_name(path.name + ".corrupt"))
    except OSError as exc:
        log.warning("Could not quarantine %s: %s", path.name, exc)
    try:
        _sidecar(path).unlink()
    except OSError:
        pass


def load(key: str | None) -> dict[str, Any] | None:
    from log_parser_tpu.runtime import faults

    d = _dir()
    if d is None or key is None:
        return None
    if _share_enabled():
        # same content key ⇒ identical bank: alias the resident pack
        # instead of re-reading and re-materializing the pickle
        snap = _pack_get(str(d), key)
        if snap is not None:
            return snap
    path = d / f"{key}.pkl"
    if not path.exists():
        return None
    try:
        # chaos point: an injected cache fault is an I/O failure, not
        # corruption — contained as a miss, the entry stays untouched
        faults.fire("cache")
        blob = path.read_bytes()
    except Exception as exc:
        log.warning("Bank snapshot %s unreadable: %s", path.name, exc)
        return None
    recorded = None
    try:
        recorded = _sidecar(path).read_text().split()[0]
    except (OSError, IndexError):
        pass  # no sidecar: legacy entry, trusted as before
    if recorded is not None and recorded != hashlib.sha256(blob).hexdigest():
        _quarantine(path, "content checksum mismatch")
        return None
    try:
        snap = pickle.loads(blob)
        if snap.get("version") != SNAPSHOT_VERSION:
            return None
        if _share_enabled():
            _pack_put(str(d), key, snap)
        return snap
    except Exception as exc:
        # checksum passed (or legacy) yet unpicklable: torn/truncated
        # bytes from a pre-sidecar writer, or bit rot — same treatment
        _quarantine(path, f"undecodable: {exc}")
        return None


def save(key: str | None, snap: dict[str, Any]) -> None:
    d = _dir()
    if d is None or key is None:
        return
    snap = dict(snap, version=SNAPSHOT_VERSION)
    try:
        d.mkdir(parents=True, exist_ok=True)
        os.chmod(d, 0o700)
    except OSError as exc:
        log.warning("Bank snapshot dir unavailable: %s", exc)
        return
    blob = pickle.dumps(snap, protocol=pickle.HIGHEST_PROTOCOL)
    digest = hashlib.sha256(blob).hexdigest()
    if _share_enabled():
        # the builder's own snapshot seeds the memo: tenant #2 with the
        # same key shares tenant #1's pack without touching disk
        _pack_put(str(d), key, snap)
    atomic_publish(d, f"{key}.pkl", lambda f: f.write(blob))
    # sidecar second: a crash between the two leaves a good snapshot with
    # a stale/missing sidecar — worst case one spurious rebuild
    atomic_publish(
        d, f"{key}.pkl.sum", lambda f: f.write(f"{digest} {len(blob)}\n".encode())
    )
