"""YAML pattern-set loader.

Reproduces the reference's loading semantics (PatternService.java:45-85):

- recursively walk the pattern directory (Files.walk, :57);
- consider only regular files ending in ``.yml`` or ``.yaml`` (:58-63);
- parse each into a :class:`PatternSet`; files that fail to parse are logged
  and skipped, never fatal (:82-84);
- a missing/non-directory path logs an error and yields zero sets (:50-55).
"""

from __future__ import annotations

import logging
import os
from typing import Iterable

import yaml

from log_parser_tpu.models.pattern import PatternSet

log = logging.getLogger(__name__)

# Severity vocabulary of the scoring multipliers (golden/engine.py
# SEVERITY_MULTIPLIERS); anything else would silently score at 1.0×, below
# INFO — a typo'd CRITICAL must be a load error, not a quiet downgrade.
# tests/test_patlint.py pins this set equal to the multiplier table's keys.
VALID_SEVERITIES = frozenset({"CRITICAL", "HIGH", "MEDIUM", "LOW", "INFO"})


class PatternValidationError(ValueError):
    """A pattern set parsed but violates the schema.

    ``findings`` is a list of ``{"rule", "pattern_id", "detail"}`` dicts so
    HTTP surfaces (reload 409) and tools can report structure, not a blob.
    """

    def __init__(self, source: str, findings: list[dict]):
        detail = "; ".join(
            f"{f['rule']}({f['pattern_id']}): {f['detail']}" for f in findings
        )
        super().__init__(f"invalid pattern set {source}: {detail}")
        self.source = source
        self.findings = findings


def validate_pattern_set(pattern_set: PatternSet, source: str = "<set>") -> None:
    """Reject duplicate pattern ids and unknown severities at parse time.

    Scoped to ONE set: patterns sharing an id across different sets share a
    frequency counter by design (patterns/bank.py), so cross-set duplicates
    are a lint finding (analysis/lint.py), not a load error.
    """
    findings: list[dict] = []
    seen: set[str] = set()
    for pat in pattern_set.patterns or []:
        pid = pat.id or ""
        if pid and pid in seen:
            findings.append(
                {
                    "rule": "duplicate-id",
                    "pattern_id": pid,
                    "detail": "pattern id appears more than once in this set",
                }
            )
        seen.add(pid)
        if pat.severity and pat.severity.upper() not in VALID_SEVERITIES:
            findings.append(
                {
                    "rule": "unknown-severity",
                    "pattern_id": pid,
                    "detail": f"severity {pat.severity!r} is not one of "
                    f"{sorted(VALID_SEVERITIES)}",
                }
            )
    if findings:
        raise PatternValidationError(source, findings)


def load_pattern_file(path: str) -> PatternSet:
    """Parse one YAML file into a :class:`PatternSet`.

    Raises on malformed YAML or schema violations (duplicate ids, unknown
    severities) — the directory walker catches and skips, mirroring
    PatternService.java:77-85.
    """
    with open(path, "r", encoding="utf-8") as fh:
        data = yaml.safe_load(fh)
    if not isinstance(data, dict):
        raise ValueError(f"pattern file {path!r} is not a YAML mapping")
    pattern_set = PatternSet.from_dict(data)
    validate_pattern_set(pattern_set, source=path)
    return pattern_set


def _walk_yaml_files(directory: str) -> Iterable[str]:
    for root, _dirs, files in sorted(
        (r, d, f) for r, d, f in os.walk(directory)
    ):
        for name in sorted(files):
            if name.endswith((".yml", ".yaml")):
                path = os.path.join(root, name)
                if os.path.isfile(path):
                    yield path


def load_pattern_directory(directory: str) -> list[PatternSet]:
    """Load every ``*.yml``/``*.yaml`` under ``directory``, skipping bad files.

    Walk order is sorted for determinism. (The reference's ``Files.walk``
    order is filesystem-dependent; event discovery order depends on pattern-set
    order, AnalysisService.java:91, so we pin a deterministic order.)
    """
    if not os.path.isdir(directory):
        log.error("Pattern directory does not exist or is not a directory: %s", directory)
        return []

    sets: list[PatternSet] = []
    for path in _walk_yaml_files(directory):
        try:
            sets.append(load_pattern_file(path))
        except Exception:  # noqa: BLE001 — log-and-skip per PatternService.java:82-84
            log.exception("Failed to parse pattern file: %s", path)
    log.info("Successfully loaded %d pattern sets.", len(sets))
    return sets
