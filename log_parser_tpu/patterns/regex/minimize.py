"""DFA minimization: Moore/Hopcroft partition refinement + byte-class merge.

The Pallas kernel tier stores the union automaton as dense VMEM-resident
transition planes, so every state and every byte class is paid in bytes
and MXU FLOPs (ops/matchdfa_pallas.py). The subset construction in
dfa.py/multidfa.py is run-of-the-mill non-minimal: distinct (NFA-subset,
left-context) pairs often have identical forward behaviour — same output
words, same acceptance, transitions into the same blocks — and merging
them is a pure table shrink with zero semantic change. Measured on the
builtin bank's union groups this plus byte-class re-merge takes the
largest group's kernel planes from 13.1 MB to ~2 MB (PERF.md §16).

Algorithm: signature partition refinement (Moore's algorithm, the
n·log n Hopcroft variant's simpler O(n·C·iters) cousin) vectorized over
numpy — the initial partition groups states by their full observable
output signature, then each round re-partitions by (block, successor
blocks per class) rows via ``np.unique(axis=0)`` until the block count
is stable. Convergence on the builtin groups is 26–62 rounds at
~0.04–1.2 s per group, amortized by the on-disk caches.

Two invariants the rest of the stack depends on:

- **stable numbering** — blocks are renumbered by first-occurrence of a
  member state, so minimization is deterministic and the single-DFA
  MATCHED sink (state 0, dfa.py) keeps id 0: it is the first state, its
  block is renumbered 0, and absorbing+accepting is preserved by
  congruence.
- **word-ness survives the class merge** — for the union automaton two
  byte classes may share a transition column yet differ in word-char
  membership, and ``out2`` row selection reads the incoming byte's
  word-ness (``state*2 + rw``), so ``cls_is_word`` participates in the
  column signature. The single-regex DFA resolved assertions at
  construction, so its classes merge on transition columns alone.

Correctness is pinned differentially (tests/test_dfa_minimize.py):
exact product walks (analysis/subsumption.py) against the unminimized
automaton plus randomized byte-walk sampling.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from log_parser_tpu.patterns.regex.dfa import CompiledDfa
from log_parser_tpu.patterns.regex.multidfa import CompiledMultiDfa


def _refine(trans: np.ndarray, out_sig: np.ndarray) -> tuple[np.ndarray, int]:
    """Coarsest partition of states refining ``out_sig`` and closed under
    transitions. ``trans``: int [S, C]; ``out_sig``: int [S, K] observable
    outputs. Returns (block id per state, block count)."""
    S = trans.shape[0]
    if S == 0:
        return np.zeros(0, dtype=np.int64), 0
    _, block = np.unique(out_sig, axis=0, return_inverse=True)
    block = block.astype(np.int64).ravel()
    n = int(block.max()) + 1
    while True:
        rows = np.concatenate([block[:, None], block[trans]], axis=1)
        _, block = np.unique(rows, axis=0, return_inverse=True)
        block = block.astype(np.int64).ravel()
        n2 = int(block.max()) + 1
        if n2 == n:
            return block, n
        n = n2


def _stable_renumber(
    block: np.ndarray, n: int
) -> tuple[np.ndarray, np.ndarray]:
    """Renumber blocks by first occurrence so minimization is
    deterministic. Returns (renumbered block ids, representative member
    per block — the lowest original id in each)."""
    S = block.shape[0]
    first = np.full(n, S, dtype=np.int64)
    np.minimum.at(first, block, np.arange(S, dtype=np.int64))
    order = np.argsort(first, kind="stable")
    rank = np.empty(n, dtype=np.int64)
    rank[order] = np.arange(n, dtype=np.int64)
    return rank[block], first[order]


def _merge_classes(
    trans: np.ndarray, byte_class: np.ndarray, extra: np.ndarray | None
) -> tuple[np.ndarray, np.ndarray, np.ndarray, int]:
    """Merge byte classes with identical transition columns (and identical
    ``extra`` per-class columns, e.g. word-ness). Returns
    (trans [S, C'], byte_class [256], representative old class per new,
    C')."""
    C = trans.shape[1]
    cols = trans.T.astype(np.int64)
    if extra is not None:
        cols = np.concatenate([cols, extra.astype(np.int64)], axis=1)
    _, cmap = np.unique(cols, axis=0, return_inverse=True)
    cmap = cmap.astype(np.int64).ravel()
    n = int(cmap.max()) + 1 if C else 0
    cmap, creps = _stable_renumber(cmap, n)
    return (
        np.ascontiguousarray(trans[:, creps]),
        cmap[byte_class].astype(np.int32),
        creps,
        n,
    )


def minimize_multi_dfa(md: CompiledMultiDfa) -> CompiledMultiDfa:
    """Language-preserving shrink of a union multi-DFA: state partition
    refinement over the full observable signature (both word-ness out2
    rows + end-of-input accept words) followed by a word-ness-preserving
    byte-class re-merge. ``n_states_unmin`` records the pre-minimization
    count for the kernel-geometry report."""
    S = md.n_states
    if S == 0:
        return md
    unmin = md.n_states_unmin or S
    out_sig = np.concatenate(
        [
            md.out2.reshape(S, 2 * md.n_words).astype(np.int64),
            md.accept_words.astype(np.int64),
        ],
        axis=1,
    )
    block, n = _refine(md.trans, out_sig)
    block, reps = _stable_renumber(block, n)
    trans = np.ascontiguousarray(block[md.trans[reps]].astype(np.int32))
    out2 = np.ascontiguousarray(
        md.out2.reshape(S, 2, md.n_words)[reps].reshape(n * 2, md.n_words)
    )
    accept_words = np.ascontiguousarray(md.accept_words[reps])
    trans, byte_class, creps, n_classes = _merge_classes(
        trans, md.byte_class, md.cls_is_word[:, None]
    )
    return CompiledMultiDfa(
        trans=trans,
        byte_class=byte_class,
        cls_is_word=np.ascontiguousarray(md.cls_is_word[creps]),
        out2=out2,
        accept_words=accept_words,
        start=int(block[md.start]),
        n_states=n,
        n_classes=n_classes,
        n_patterns=md.n_patterns,
        n_words=md.n_words,
        n_states_unmin=unmin,
    )


def minimize_dfa(dfa: CompiledDfa) -> CompiledDfa:
    """Language-preserving shrink of a single-regex DFA (accept-at-end
    observable only). The MATCHED sink keeps id 0 — see module docstring."""
    S = dfa.n_states
    if S == 0:
        return dfa
    out_sig = dfa.accept_end.astype(np.int64)[:, None]
    block, n = _refine(dfa.trans, out_sig)
    block, reps = _stable_renumber(block, n)
    trans = np.ascontiguousarray(block[dfa.trans[reps]].astype(np.int32))
    accept_end = np.ascontiguousarray(dfa.accept_end[reps])
    trans, byte_class, _, n_classes = _merge_classes(
        trans, dfa.byte_class, None
    )
    return dataclasses.replace(
        dfa,
        trans=trans,
        byte_class=byte_class,
        accept_end=accept_end,
        start=int(block[dfa.start]),
        n_states=n,
        n_classes=n_classes,
    )
