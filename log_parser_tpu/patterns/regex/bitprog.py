"""Regex → bit-parallel extended Shift-And program.

Compiles the Java-dialect AST (parser.py) into linear *item* alternatives
executable by the gather-free bit engine (ops/bitglush.py) — the
Navarro-Raffinot extended Shift-And shaped for the TPU cost model: the
union multi-DFA tier's per-byte cost is a per-element random gather
(scalar-unit bound, PERF.md §1), while a bit program advances every
pattern with one contiguous ``[256, W]`` mask-row take plus elementwise
vector ops — no random gathers at all.

An *item* consumes bytes from one byte class with a repetition kind:

==========  ===========================  ==========================
kind        regex shape                  bit mechanics
==========  ===========================  ==========================
ONE         ``X``                        plain shift position
PLUS        ``X+``                       shift position + self-loop
STAR        ``X*`` (incl. ``.*`` gaps)   self-loop + ε-skippable
OPT         ``X?``                       ε-skippable
==========  ===========================  ==========================

Alternations, bounded repeats, and optional groups are expanded into
independent alternatives (each a linear item list) under caps; ``^``/``$``
anchor per alternative; ``\\b``/``\\B`` gate a specific item's shift-in
(``pre_assert``) or the alternative's acceptance (``post_assert``).

Anything that does not reduce to this shape — unbounded repeats of
multi-position groups, assertions adjacent to skippable items (beyond the
rewrite below), oversized expansions — raises :class:`BitUnsupportedError`
and the column stays on its automaton tier. Nothing is ever lost, only
routed.

Rewrite rule (containment soundness): a *leading, unanchored* ``\\b\\w*``
before a word-leading tail is dropped — any containment match of ``tail`` whose
first byte is a word char extends left through word chars to a word start,
which supplies both the boundary and the ``\\w*`` bytes. This is exactly
the ``\\b\\w*Exception\\b`` shape of the reference's context regex
(ContextAnalysisService.java:33).
"""

from __future__ import annotations

import dataclasses
import itertools

from log_parser_tpu.patterns.regex import reasons
from log_parser_tpu.patterns.regex.nfa import Nfa  # noqa: F401 (re-export convenience)
from log_parser_tpu.patterns.regex.parser import (
    Alt,
    Assertion,
    Cat,
    Empty,
    Lit,
    Node,
    Rep,
    WORD_BYTES,
    parse_java_regex,
)

ONE, PLUS, STAR, OPT = "one", "plus", "star", "opt"


class BitUnsupportedError(ValueError):
    """Regex shape outside the bit-parallel fragment.

    ``code`` is a stable reason code from :mod:`.reasons`, shared verbatim
    with the static analyzer's tier classifier.
    """

    def __init__(self, message: str, code: str = reasons.BIT_UNSUPPORTED_NODE):
        super().__init__(message)
        self.code = code


@dataclasses.dataclass(frozen=True)
class Item:
    byteset: frozenset[int]
    kind: str  # ONE | PLUS | STAR | OPT
    pre_assert: str | None = None  # None | 'b' | 'B'

    @property
    def skippable(self) -> bool:
        return self.kind in (STAR, OPT)

    @property
    def self_loop(self) -> bool:
        return self.kind in (STAR, PLUS)


@dataclasses.dataclass(frozen=True)
class BitAlternative:
    items: tuple[Item, ...]
    caret: bool = False  # anchored at line start
    post_assert: str | None = None  # None | '$' | 'b' | 'B'

    @property
    def n_positions(self) -> int:
        return len(self.items)

    def final_positions(self) -> list[int]:
        """Indices that accept: the last item, cascading back through a
        skippable suffix (``\\)\\s*$`` accepts at ``)`` too)."""
        out = []
        i = len(self.items) - 1
        while i >= 0:
            out.append(i)
            if not self.items[i].skippable:
                break
            i -= 1
        return out


@dataclasses.dataclass(frozen=True)
class BitProgram:
    alternatives: tuple[BitAlternative, ...]

    @property
    def n_positions(self) -> int:
        return sum(a.n_positions for a in self.alternatives)


    @property
    def max_skip_run(self) -> int:
        """Longest run of consecutive ε-skippable positions — the number
        of closure applications the engine must unroll."""
        best = 0
        for a in self.alternatives:
            run = 0
            for it in a.items:
                run = run + 1 if it.skippable else 0
                best = max(best, run)
        return best


# ------------------------------------------------------------- expansion

# caps keep the alternative product and the packed width bounded; a column
# that exceeds them simply stays on the union-DFA tier
MAX_ALTERNATIVES = 64
MAX_POSITIONS_PER_ALT = 96
MAX_BOUNDED_REPEAT = 16

_ASSERT = object()  # marker type tag for assertion elements


def _expand(node: Node) -> list[list]:
    """Node → list of alternatives, each a flat list of Item / ('assert',
    kind) elements. Raises BitUnsupportedError beyond the fragment/caps."""
    if isinstance(node, Empty):
        return [[]]
    if isinstance(node, Lit):
        return [[Item(node.byteset, ONE)]]
    if isinstance(node, Assertion):
        return [[(_ASSERT, node.kind)]]
    if isinstance(node, Alt):
        out: list[list] = []
        for opt in node.options:
            out.extend(_expand(opt))
            if len(out) > MAX_ALTERNATIVES:
                raise BitUnsupportedError("alternative expansion too large", reasons.BIT_EXPANSION_TOO_LARGE)
        return out
    if isinstance(node, Cat):
        outs: list[list] = [[]]
        for part in node.parts:
            exp = _expand(part)
            if len(outs) * len(exp) > MAX_ALTERNATIVES:
                raise BitUnsupportedError("alternative expansion too large", reasons.BIT_EXPANSION_TOO_LARGE)
            outs = [a + b for a, b in itertools.product(outs, exp)]
        return outs
    if isinstance(node, Rep):
        lo, hi = node.lo, node.hi
        if isinstance(node.child, Lit):
            bs = node.child.byteset
            if (lo, hi) == (0, None):
                return [[Item(bs, STAR)]]
            if (lo, hi) == (1, None):
                return [[Item(bs, PLUS)]]
            if hi is None:  # {m,}: m-1 fixed + PLUS
                if lo > MAX_BOUNDED_REPEAT:
                    raise BitUnsupportedError("repeat bound too large", reasons.BIT_REPEAT_TOO_LARGE)
                return [[Item(bs, ONE)] * (lo - 1) + [Item(bs, PLUS)]]
            if hi > MAX_BOUNDED_REPEAT:
                raise BitUnsupportedError("repeat bound too large", reasons.BIT_REPEAT_TOO_LARGE)
            return [[Item(bs, ONE)] * lo + [Item(bs, OPT)] * (hi - lo)]
        # multi-position child: expand bounded repeats as products
        if hi is None:
            raise BitUnsupportedError("unbounded repeat of a group", reasons.BIT_UNBOUNDED_GROUP)
        if hi > 4:
            raise BitUnsupportedError("group repeat bound too large", reasons.BIT_REPEAT_TOO_LARGE)
        child = _expand(node.child)
        out = []
        for n in range(lo, hi + 1):
            pieces: list[list] = [[]]
            for _ in range(n):
                pieces = [a + b for a, b in itertools.product(pieces, child)]
                if len(pieces) > MAX_ALTERNATIVES:
                    raise BitUnsupportedError("alternative expansion too large", reasons.BIT_EXPANSION_TOO_LARGE)
            out.extend(pieces)
            if len(out) > MAX_ALTERNATIVES:
                raise BitUnsupportedError("alternative expansion too large", reasons.BIT_EXPANSION_TOO_LARGE)
        return out
    raise BitUnsupportedError(
        f"unsupported node {type(node).__name__}", reasons.BIT_UNSUPPORTED_NODE
    )


def _attach(elements: list) -> BitAlternative:
    """Flat element list → BitAlternative with assertions attached to
    positions; raises on shapes the engine cannot gate exactly."""
    caret = False
    items: list[Item] = []
    pending: str | None = None  # assertion awaiting the next consuming item

    i = 0
    # leading assertions
    while i < len(elements) and isinstance(elements[i], tuple):
        kind = elements[i][1]
        if kind == "^":
            caret = True
        elif pending is None or pending == kind:
            pending = kind
        else:
            raise BitUnsupportedError("conflicting adjacent assertions", reasons.BIT_ASSERT_SHAPE)
        i += 1

    post: str | None = None
    while i < len(elements):
        el = elements[i]
        if isinstance(el, tuple):
            kind = el[1]
            if kind == "$":
                # must be trailing (possibly followed by more assertions)
                rest = elements[i + 1 :]
                if any(not isinstance(r, tuple) for r in rest):
                    raise BitUnsupportedError("mid-pattern $", reasons.BIT_ASSERT_SHAPE)
                post = "$"
                i += 1
                continue
            if kind == "^":
                # a mid-pattern line anchor can still be satisfiable when
                # the prefix matches empty (e.g. "x*^ab"); the allow-mask
                # machinery cannot express it, so route to an exact tier
                raise BitUnsupportedError("mid-pattern ^", reasons.BIT_ASSERT_SHAPE)
            if pending is not None and pending != kind:
                raise BitUnsupportedError("conflicting adjacent assertions", reasons.BIT_ASSERT_SHAPE)
            pending = kind
            i += 1
            continue
        item: Item = el
        if pending is not None:
            # rewrite: \b + \w* + word-leading next item → drop both.
            # Sound ONLY leading + unanchored (`not items and not caret`):
            # the containment argument extends the match left through word
            # chars to a word start, which a preceding consumed item or a
            # line anchor would pin in place ('=\b\w*Exception' must see
            # '=' adjacent to the tail; '^\b\w*Exception' must accept the
            # extension from column 0). Elsewhere, fall through to the
            # assertion-before-optional rejection so the column stays on
            # an exact automaton tier.
            nxt = elements[i + 1] if i + 1 < len(elements) else None
            if (
                pending == "b"
                and not items
                and not caret
                and item.kind == STAR
                and item.byteset == WORD_BYTES
                and isinstance(nxt, Item)
                and nxt.byteset <= WORD_BYTES
                and nxt.kind in (ONE, PLUS)  # a skippable next could match
                # empty, leaving a non-word byte as the first consumed one
            ):
                pending = None
                i += 1  # drop the \w* item; nxt keeps no assertion
                continue
            if item.skippable:
                raise BitUnsupportedError("assertion before optional item", reasons.BIT_ASSERT_SHAPE)
            item = dataclasses.replace(item, pre_assert=pending)
            pending = None
        items.append(item)
        i += 1

    if pending is not None:
        if post == "$":
            raise BitUnsupportedError("assertion combined with $", reasons.BIT_ASSERT_SHAPE)
        if pending not in ("b", "B"):
            raise BitUnsupportedError("trailing anchor assertion", reasons.BIT_ASSERT_SHAPE)
        post = pending  # trailing \b / \B
    if not items:
        raise BitUnsupportedError("empty (assertion-only) alternative", reasons.BIT_EMPTY_MATCH)
    if len(items) > MAX_POSITIONS_PER_ALT:
        raise BitUnsupportedError("alternative too long", reasons.BIT_TOO_LONG)
    if all(it.skippable for it in items):
        raise BitUnsupportedError("alternative matches the empty string", reasons.BIT_EMPTY_MATCH)
    if post in ("b", "B"):
        # acceptance cascades back through a skippable suffix; the gate is
        # exact only when every accepting position consumed the byte whose
        # wordness the engine tests — guaranteed for all cascade members
        pass
    return BitAlternative(items=tuple(items), caret=caret, post_assert=post)


def compile_bitprog(node: Node) -> BitProgram:
    """AST → BitProgram, or raise :class:`BitUnsupportedError`."""
    alts = [_attach(el) for el in _expand(node)]
    if not alts:
        raise BitUnsupportedError("no alternatives", reasons.BIT_UNSUPPORTED_NODE)
    return BitProgram(alternatives=tuple(alts))


# -------------------------------------------------- assert expansion

NONWORD_BYTES = frozenset(range(256)) - WORD_BYTES


def _leading_variants(alt: BitAlternative) -> list[tuple[tuple, bool]]:
    """Rewrite a first-item ``\\b``/``\\B`` pre-assert into explicit
    variants: a ``^`` variant when the virtual line-start predecessor
    (non-word) satisfies the assert, and a predecessor-byte-prefixed
    variant otherwise/additionally. The first byteset is split by
    word-ness so each variant's boundary answer is fixed."""
    first = alt.items[0]
    pa = first.pre_assert
    if pa is None:
        return [(alt.items, alt.caret)]
    outs: list[tuple[tuple, bool]] = []
    for part in (first.byteset & WORD_BYTES, first.byteset & NONWORD_BYTES):
        if not part:
            continue
        wp = part <= WORD_BYTES
        if first.kind == ONE:
            head: tuple = (Item(part, ONE),)
        elif first.kind == PLUS:  # \bx+ : boundary gates the first x only
            head = (
                Item(part, ONE),
                dataclasses.replace(first, kind=STAR, pre_assert=None),
            )
        else:  # skippable first items never carry pre_asserts (_attach)
            raise BitUnsupportedError("leading assert on optional item", reasons.BIT_ASSERT_SHAPE)
        body = head + alt.items[1:]
        start_ok = (pa == "b") == wp  # virtual predecessor is non-word
        if start_ok:
            outs.append((body, True))
        if not alt.caret:
            pred = NONWORD_BYTES if (pa == "b") == wp else WORD_BYTES
            outs.append(((Item(pred, ONE),) + body, False))
    if not outs:
        # e.g. ^\B<word>: the assert is unsatisfiable at position 0 —
        # still a legal (never-matching) regex; keep it on a gated tier
        raise BitUnsupportedError("unsatisfiable leading assert", reasons.BIT_ASSERT_SHAPE)
    return outs


def _trailing_variants(
    items: tuple, post: str | None
) -> list[tuple[tuple, str | None]]:
    """Rewrite a trailing ``\\b``/``\\B`` into an appended follow-byte
    item (reachable from every accepting cascade position via the
    ε-skip chain) plus a ``$`` variant when end-of-line satisfies the
    assert. Needs every accepting position's byteset word-ness to be
    pure; a single accepting position may be split to make it so."""
    if post not in ("b", "B"):
        return [(items, post)]
    fins = BitAlternative(items=items).final_positions()
    casc = [items[f] for f in fins]
    pure_w = all(it.byteset <= WORD_BYTES for it in casc)
    pure_n = all(it.byteset <= NONWORD_BYTES for it in casc)
    splits: list[tuple[tuple, bool]] = []  # (items, last-consumed-is-word)
    if pure_w or pure_n:
        splits.append((items, pure_w))
    elif len(fins) == 1:
        last = items[-1]
        for part in (last.byteset & WORD_BYTES, last.byteset & NONWORD_BYTES):
            if not part:
                continue
            if last.kind == ONE:
                base = items[:-1] + (Item(part, ONE),)
            elif last.kind == PLUS:  # x+\b : only the last x faces the \b
                base = items[:-1] + (
                    dataclasses.replace(last, kind=STAR, pre_assert=None),
                    Item(part, ONE),
                )
            else:
                raise BitUnsupportedError("trailing assert after optional", reasons.BIT_ASSERT_SHAPE)
            splits.append((base, part <= WORD_BYTES))
    else:
        raise BitUnsupportedError("word-ness-impure trailing cascade", reasons.BIT_ASSERT_SHAPE)
    outs: list[tuple[tuple, str | None]] = []
    for base, wl in splits:
        follow = (NONWORD_BYTES if wl else WORD_BYTES) if post == "b" else (
            WORD_BYTES if wl else NONWORD_BYTES
        )
        outs.append((base + (Item(follow, ONE),), None))
        if (post == "b") == wl:  # virtual end-of-line byte is non-word
            outs.append((base, "$"))
    if not outs:
        raise BitUnsupportedError("unsatisfiable trailing assert", reasons.BIT_ASSERT_SHAPE)
    return outs


def has_asserts(prog: BitProgram) -> bool:
    return any(
        alt.post_assert in ("b", "B")
        or any(it.pre_assert is not None for it in alt.items)
        for alt in prog.alternatives
    )


def expand_asserts(prog: BitProgram) -> BitProgram:
    """Program-level de-assert rewrite: eliminate every ``\\b``/``\\B``
    by expanding into ``^``/``$`` variants and explicit neighbor-byte
    items. The payoff is bank-wide: BitGlushBank's capability flags drop
    the word-ness tracking, allow select, and boundary-hit op groups
    from the scan body for a fully assert-free bank (~8 of ~18 ops/byte
    on the builtin library — PERF.md §9b). Raises
    :class:`BitUnsupportedError` on shapes outside the rewrite
    (mid-pattern asserts, impure multi-position cascades, cap blowups);
    the caller then keeps the exact gated original."""
    new_alts: list[BitAlternative] = []
    for alt in prog.alternatives:
        if alt.post_assert not in ("b", "B") and not any(
            it.pre_assert is not None for it in alt.items
        ):
            new_alts.append(alt)
            continue
        if any(it.pre_assert is not None for it in alt.items[1:]):
            raise BitUnsupportedError("mid-pattern assert", reasons.BIT_ASSERT_SHAPE)
        for body, caret in _leading_variants(alt):
            for t_items, t_post in _trailing_variants(body, alt.post_assert):
                if len(t_items) > MAX_POSITIONS_PER_ALT:
                    raise BitUnsupportedError("expanded alternative too long", reasons.BIT_TOO_LONG)
                new_alts.append(
                    BitAlternative(
                        items=tuple(t_items), caret=caret, post_assert=t_post
                    )
                )
                if len(new_alts) > MAX_ALTERNATIVES:
                    raise BitUnsupportedError("assert expansion too large", reasons.BIT_EXPANSION_TOO_LARGE)
    out = BitProgram(alternatives=tuple(new_alts))
    assert not has_asserts(out)
    return out


def truncate_long_alternatives(
    prog: BitProgram, max_items
) -> tuple[BitProgram, bool] | None:
    """Cut every alternative longer than its item budget down to that
    budget, dropping its post-assertion. ``max_items`` is an int or a
    callable ``(BitAlternative) -> int`` — packers whose per-alternative
    overhead varies (e.g. the bitglush caret guard bit) pass a callable
    so a truncated allocation can never exceed the packer's word size.

    The truncated program *over-approximates* the original: a line the
    full alternative matches always contains a match of its item prefix
    (each of the first ``max_items`` items was consumed or skipped at
    the same place, and ``final_positions`` cascading covers a skipped
    tail), and dropping ``$``/``\b`` post-assertions only weakens the
    condition further. Callers therefore MUST re-verify every flagged
    line with the exact host regex (runtime/engine.py does, per event
    at assembly) — used so long alternatives never force the packed
    bank onto the cross-word chain path (ops/bitglush.py).

    Returns (program, changed). Returns None when some long
    alternative's prefix would be all-skippable — a truncated program
    that matches EVERY line selects the whole corpus for host
    verification, which is worse than keeping the exact chain path.
    """
    alts: list[BitAlternative] = []
    changed = False
    for a in prog.alternatives:
        budget = max_items(a) if callable(max_items) else max_items
        if a.n_positions <= budget:
            alts.append(a)
            continue
        head = a.items[:budget]
        if all(it.skippable for it in head):
            return None
        alts.append(
            BitAlternative(items=tuple(head), caret=a.caret, post_assert=None)
        )
        changed = True
    return BitProgram(alternatives=tuple(alts)), changed


def compile_bitprog_regex(regex: str, case_insensitive: bool) -> BitProgram:
    return compile_bitprog(parse_java_regex(regex, case_insensitive))
