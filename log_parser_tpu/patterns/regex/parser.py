"""Java-dialect regex parser → byte-level AST.

Parses the subset of ``java.util.regex`` syntax that pattern libraries
actually use (the dialect floor is set by the reference's own hardcoded
regexes, ContextAnalysisService.java:27-34: alternation, groups, ``^`` ``$``
``\\b`` anchors, ``\\w``-style classes, ``[...]`` classes, ``*``/``+``
quantifiers, case-insensitive matching) into an AST over *bytes* so the
downstream NFA/DFA run on uint8 log lines.

Non-ASCII characters in a pattern are expanded to their UTF-8 byte
sequences; ``.`` and negated classes include all non-ASCII bytes, which
makes the automaton a faithful matcher on ASCII lines and a *superset*
matcher on non-ASCII lines (a multi-byte char can satisfy two ``.``\\ s).
The engine routes non-ASCII lines to host verification, so this never
changes end-to-end results.

Constructs with no finite-automaton equivalent (lookaround, backreferences)
or with semantics we refuse to approximate (possessive quantifiers, atomic
groups, class intersection ``&&``) raise :class:`RegexUnsupportedError`; the
caller falls back to host-side matching for those patterns.
"""

from __future__ import annotations

import dataclasses
from typing import Union

from log_parser_tpu.patterns.regex import reasons

MAX_BYTE = 0xFF

WORD_BYTES = frozenset(
    b for b in range(256)
    if chr(b).isascii() and (chr(b).isalnum() or chr(b) == "_")
)
DIGIT_BYTES = frozenset(range(ord("0"), ord("9") + 1))
SPACE_BYTES = frozenset(b" \t\n\x0b\f\r")
ALL_BYTES = frozenset(range(256))
# Java '.' default: any char but line terminators (\n \r; the Unicode ones
# are non-ASCII and therefore already in the superset-on-non-ASCII caveat).
DOT_BYTES = ALL_BYTES - frozenset(b"\n\r")

_CLASS_SHORTHANDS = {
    "d": DIGIT_BYTES,
    "D": ALL_BYTES - DIGIT_BYTES,
    "w": WORD_BYTES,
    "W": ALL_BYTES - WORD_BYTES,
    "s": SPACE_BYTES,
    "S": ALL_BYTES - SPACE_BYTES,
}

_SIMPLE_ESCAPES = {
    "n": ord("\n"),
    "t": ord("\t"),
    "r": ord("\r"),
    "f": ord("\f"),
    "a": 0x07,
    "e": 0x1B,
}

_POSIX_CONTENTS = {
    "Alpha": frozenset(b for b in range(256) if chr(b).isascii() and chr(b).isalpha()),
    "Digit": DIGIT_BYTES,
    "Alnum": frozenset(b for b in range(256) if chr(b).isascii() and chr(b).isalnum()),
    "Upper": frozenset(range(ord("A"), ord("Z") + 1)),
    "Lower": frozenset(range(ord("a"), ord("z") + 1)),
    "Space": SPACE_BYTES,
    "Punct": frozenset(b for b in range(33, 127) if not chr(b).isalnum()),
    "XDigit": DIGIT_BYTES | frozenset(b"abcdefABCDEF"),
}


class RegexUnsupportedError(ValueError):
    """Raised for Java regex constructs the automaton path cannot express.

    ``code`` is a stable reason code from :mod:`.reasons`, shared verbatim
    with the static analyzer's tier classifier so predicted and actual
    decline reasons cannot drift.
    """

    def __init__(self, message: str, code: str = reasons.RX_SYNTAX):
        super().__init__(message)
        self.code = code


# ----------------------------------------------------------------- AST nodes


@dataclasses.dataclass(frozen=True)
class Lit:
    """Match exactly one byte from ``byteset``."""

    byteset: frozenset[int]


@dataclasses.dataclass(frozen=True)
class Cat:
    parts: tuple["Node", ...]


@dataclasses.dataclass(frozen=True)
class Alt:
    options: tuple["Node", ...]


@dataclasses.dataclass(frozen=True)
class Rep:
    """``child`` repeated between ``lo`` and ``hi`` times (``hi=None`` = ∞).
    Laziness is irrelevant for boolean find() semantics and is discarded."""

    child: "Node"
    lo: int
    hi: int | None


@dataclasses.dataclass(frozen=True)
class Assertion:
    """Zero-width assertion: ``^`` ``$`` ``b`` (word boundary) ``B``."""

    kind: str


@dataclasses.dataclass(frozen=True)
class Empty:
    pass


Node = Union[Lit, Cat, Alt, Rep, Assertion, Empty]


def _fold_byte(b: int) -> frozenset[int]:
    """Case-insensitive byte set for an ASCII byte."""
    ch = chr(b)
    if ch.isascii() and ch.isalpha():
        return frozenset({ord(ch.lower()), ord(ch.upper())})
    return frozenset({b})


def _char_to_bytesets(ch: str, ci: bool) -> list[frozenset[int]]:
    """One char → a sequence of single-byte sets (UTF-8 expansion)."""
    if ord(ch) < 128:
        return [_fold_byte(ord(ch)) if ci else frozenset({ord(ch)})]
    return [frozenset({b}) for b in ch.encode("utf-8")]


class _Parser:
    def __init__(
        self,
        pattern: str,
        case_insensitive: bool = False,
        lenient: bool = False,
    ):
        self.p = pattern
        self.i = 0
        self.n = len(pattern)
        self.ci = case_insensitive
        self.lenient = lenient
        self._quoted_run = False  # last atom was a multi-char \Q..\E run

    def fail(
        self, what: str, code: str = reasons.RX_SYNTAX
    ) -> RegexUnsupportedError:
        return RegexUnsupportedError(
            f"{what} at index {self.i} in {self.p!r}", code=code
        )

    def peek(self) -> str | None:
        return self.p[self.i] if self.i < self.n else None

    def take(self) -> str:
        ch = self.p[self.i]
        self.i += 1
        return ch

    # grammar: alt := cat ('|' cat)* ; cat := rep* ; rep := atom quant?

    def parse(self) -> Node:
        node = self.parse_alt()
        if self.i < self.n:
            raise self.fail(f"unexpected {self.p[self.i]!r}")
        return node

    def parse_alt(self) -> Node:
        options = [self.parse_cat()]
        while self.peek() == "|":
            self.take()
            options.append(self.parse_cat())
        return options[0] if len(options) == 1 else Alt(tuple(options))

    def parse_cat(self) -> Node:
        parts: list[Node] = []
        while self.i < self.n and self.peek() not in ("|", ")"):
            parts.append(self.parse_rep())
        if not parts:
            return Empty()
        return parts[0] if len(parts) == 1 else Cat(tuple(parts))

    def parse_rep(self) -> Node:
        self._quoted_run = False
        atom = self.parse_atom()  # _quoted() sets the flag
        was_quoted = self._quoted_run
        while True:
            quant = self._parse_quantifier()
            if quant is None:
                return atom
            if was_quoted and isinstance(atom, Cat):
                # Java binds a quantifier after \Q..\E to the LAST quoted
                # char (quoting is per-char escaping), but this parser
                # returns the run as one atom — quantifying it would
                # repeat the WHOLE run. Decline to the host path, whose
                # translation has the exact Java binding.
                raise self.fail(
                    "quantifier after multi-char \\Q..\\E run",
                    reasons.RX_QUOTED_QUANTIFIER,
                )
            lo, hi = quant
            if isinstance(atom, Assertion):
                # quantified assertions are meaningless; Java allows (\b)* etc.
                atom = atom if lo > 0 else Empty()
                continue
            atom = Rep(atom, lo, hi)

    def _parse_quantifier(self) -> tuple[int, int | None] | None:
        ch = self.peek()
        if ch == "*":
            self.take()
            lo, hi = 0, None
        elif ch == "+":
            self.take()
            lo, hi = 1, None
        elif ch == "?":
            self.take()
            lo, hi = 0, 1
        elif ch == "{":
            mark = self.i
            self.take()
            digits = ""
            while self.peek() and self.peek().isdigit():
                digits += self.take()
            if not digits:
                self.i = mark  # literal '{'
                return None
            lo = int(digits)
            hi: int | None = lo
            if self.peek() == ",":
                self.take()
                digits2 = ""
                while self.peek() and self.peek().isdigit():
                    digits2 += self.take()
                hi = int(digits2) if digits2 else None
            if self.peek() != "}":
                self.i = mark
                return None
            self.take()
            if hi is not None and hi < lo:
                raise self.fail("quantifier max < min")
        else:
            return None
        nxt = self.peek()
        if nxt == "+":
            if not self.lenient:
                raise self.fail("possessive quantifier", reasons.RX_POSSESSIVE)
            self.take()  # lenient: read as greedy (a language superset)
        elif nxt == "?":
            self.take()  # lazy — same language
        return lo, hi

    def parse_atom(self) -> Node:
        ch = self.take()
        if ch == "(":
            return self._parse_group()
        if ch == "[":
            return Lit(self._parse_class())
        if ch == ".":
            return Lit(DOT_BYTES)
        if ch == "^":
            return Assertion("^")
        if ch == "$":
            return self._java_dollar()
        if ch == "\\":
            return self._parse_escape()
        if ch in ("*", "+", "?"):
            raise self.fail(f"dangling quantifier {ch!r}")
        return self._literal(ch)

    def _java_dollar(self) -> Node:
        """Java ``$``/``\\Z`` (non-MULTILINE): end of input, or before a
        *final* line terminator. Lines here never contain ``\\n`` (they come
        from the split at AnalysisService.java:53) but may end in a lone
        ``\\r``; for boolean find() semantics the zero-width lookahead
        ``(?=\\r?\\z)`` is equivalent to consuming an optional final ``\\r``."""
        return Alt((Assertion("$"), Cat((Lit(frozenset({0x0D})), Assertion("$")))))

    def _literal(self, ch: str) -> Node:
        sets = _char_to_bytesets(ch, self.ci)
        if len(sets) == 1:
            return Lit(sets[0])
        return Cat(tuple(Lit(s) for s in sets))

    def _parse_group(self) -> Node:
        if self.peek() == "?":
            self.take()
            nxt = self.peek()
            if nxt == ":":
                self.take()
            elif nxt == "<":
                self.take()
                if self.peek() in ("=", "!"):
                    if not self.lenient:
                        raise self.fail("lookbehind", reasons.RX_LOOKAROUND)
                    return self._lenient_zero_width()
                # named group (?<name>...)
                while self.peek() not in (">", None):
                    self.take()
                if self.peek() != ">":
                    raise self.fail("unterminated group name")
                self.take()
            elif nxt in ("=", "!"):
                if not self.lenient:
                    raise self.fail("lookahead", reasons.RX_LOOKAROUND)
                return self._lenient_zero_width()
            elif nxt == ">":
                if not self.lenient:
                    raise self.fail("atomic group", reasons.RX_ATOMIC_GROUP)
                # lenient: plain group (atomic language ⊆ greedy language)
                self.take()
                node = self.parse_alt()
                if self.peek() != ")":
                    raise self.fail("unbalanced group")
                self.take()
                return node
            elif nxt is not None and nxt in "idmsuxU-":
                # inline flags (?i) / (?i:...) — only 'i' is honored
                flags = ""
                while self.peek() is not None and self.peek() in "idmsuxU-":
                    flags += self.take()
                # x (free-spacing retokenizes), u/U (Unicode case folding)
                # reshape the language even for widening purposes
                bad = "xuU" if self.lenient else "dmsuxU"
                if any(f in flags for f in bad):
                    raise self.fail(
                        f"inline flags {flags!r}", reasons.RX_INLINE_FLAGS
                    )
                if self.peek() == ")":
                    # (?i) applies to the rest of the pattern
                    self.take()
                    self.ci = True
                    return Empty()
                if self.peek() != ":":
                    raise self.fail(
                        "bad inline flag group", reasons.RX_INLINE_FLAGS
                    )
                self.take()
                saved = self.ci
                self.ci = "i" in flags and "-" not in flags
                node = self.parse_alt()
                if self.peek() != ")":
                    raise self.fail("unbalanced group")
                self.take()
                self.ci = saved
                return node
            else:
                raise self.fail(f"group construct (?{nxt}")
        # bracketing group body: Java scopes inline flags to the enclosing
        # group, so a (?i) inside this body expires at the closing ')'
        saved_ci = self.ci
        node = self.parse_alt()
        self.ci = saved_ci
        if self.peek() != ")":
            raise self.fail("unbalanced group")
        self.take()
        return node

    def _parse_escape(self) -> Node:
        if self.i >= self.n:
            raise self.fail("trailing backslash")
        ch = self.take()
        if ch == "b":
            return Assertion("b")
        if ch == "B":
            return Assertion("B")
        if ch in ("A",):
            return Assertion("^")
        if ch == "z":  # absolute end of input
            return Assertion("$")
        if ch == "Z":  # before a final line terminator, like $
            return self._java_dollar()
        if ch == "G":
            if not self.lenient:
                raise self.fail("\\G", reasons.RX_ESCAPE_UNSUPPORTED)
            return Empty()  # anchor dropped: widens
        if ch.isdigit():
            if not self.lenient:
                raise self.fail("backreference", reasons.RX_BACKREFERENCE)
            while self.peek() is not None and self.peek().isdigit():
                self.take()
            return self._lenient_any_run()
        if ch == "k":
            if not self.lenient:
                raise self.fail(
                    "named backreference", reasons.RX_BACKREFERENCE
                )
            if self.peek() == "<":
                while self.peek() not in (">", None):
                    self.take()
                if self.peek() == ">":
                    self.take()
            return self._lenient_any_run()
        if ch in _CLASS_SHORTHANDS:
            return Lit(_CLASS_SHORTHANDS[ch])
        if ch in ("p", "P"):
            content = self._posix_contents()
            return Lit(ALL_BYTES - content if ch == "P" else content)
        if ch == "x":
            return self._literal(chr(self._hex(2)))
        if ch == "u":
            return self._literal(chr(self._hex(4)))
        if ch == "0":
            if not self.lenient:
                raise self.fail("octal escape", reasons.RX_ESCAPE_UNSUPPORTED)
            digits = 0
            while digits < 3 and self.peek() is not None and self.peek() in "01234567":
                self.take()
                digits += 1
            return Lit(ALL_BYTES)  # some byte: widens
        if ch == "Q":
            return self._quoted()
        if ch == "c":
            if not self.lenient:
                raise self.fail(
                    "control escape", reasons.RX_ESCAPE_UNSUPPORTED
                )
            if self.peek() is not None:
                self.take()
            return Lit(ALL_BYTES)
        if ch in _SIMPLE_ESCAPES:
            return Lit(frozenset({_SIMPLE_ESCAPES[ch]}))
        # escaped metachar or ordinary char: literal
        return self._literal(ch)

    def _lenient_zero_width(self) -> Node:
        """Lenient lookaround: consume ``=``/``!`` + body + ``)`` and
        drop the constraint (zero-width → ε widens the language)."""
        self.take()  # the = or !
        self.parse_alt()  # body parses (recursively lenient), discarded
        if self.peek() != ")":
            raise self.fail("unbalanced lookaround")
        self.take()
        return Empty()

    def _lenient_any_run(self) -> Node:
        """Lenient backreference: any byte run incl. empty — the widest
        thing the captured text could be."""
        return Rep(Lit(ALL_BYTES), 0, None)

    def _posix_contents(self) -> frozenset[int]:
        if self.peek() != "{":
            raise self.fail("\\p without {", reasons.RX_ESCAPE_UNSUPPORTED)
        self.take()
        name = ""
        while self.peek() not in ("}", None):
            name += self.take()
        if self.peek() != "}":
            raise self.fail("unterminated \\p{")
        self.take()
        if name not in _POSIX_CONTENTS:
            raise self.fail(
                f"\\p{{{name}}}", reasons.RX_ESCAPE_UNSUPPORTED
            )
        return _POSIX_CONTENTS[name]

    def _hex(self, digits: int) -> int:
        value = self.p[self.i : self.i + digits]
        if len(value) != digits:
            raise self.fail("bad hex escape")
        self.i += digits
        return int(value, 16)

    def _quoted(self) -> Node:
        """\\Q ... \\E literal run."""
        parts: list[Node] = []
        while self.i < self.n:
            if self.p.startswith("\\E", self.i):
                self.i += 2
                break
            parts.append(self._literal(self.take()))
        if not parts:
            return Empty()
        if len(parts) == 1:
            return parts[0]
        self._quoted_run = True  # parse_rep declines to quantify the run
        return Cat(tuple(parts))

    # ----------------------------------------------------------- char class

    def _parse_class(self) -> frozenset[int]:
        negated = False
        if self.peek() == "^":
            self.take()
            negated = True
        members: set[int] = set()

        def add_byteset(bs: frozenset[int]) -> None:
            members.update(bs)

        first = True
        while True:
            ch = self.peek()
            if ch is None:
                raise self.fail("unterminated character class")
            if ch == "]" and not first:
                self.take()
                break
            first = False
            if ch == "[":
                raise self.fail(
                    "nested character class", reasons.RX_CLASS_UNSUPPORTED
                )
            if ch == "&" and self.p.startswith("&&", self.i):
                raise self.fail(
                    "class intersection &&", reasons.RX_CLASS_INTERSECTION
                )
            kind, value = self._class_member()
            if kind == "set":  # shorthand like \w — cannot anchor a range
                add_byteset(value)
                continue
            lo = value
            if self.peek() == "-" and self.i + 1 < self.n and self.p[self.i + 1] != "]":
                self.take()
                kind2, hi = self._class_member()
                if kind2 != "byte":
                    raise self.fail(
                        "bad range endpoint", reasons.RX_CLASS_UNSUPPORTED
                    )
                if hi < lo:
                    raise self.fail(
                        "reversed range", reasons.RX_CLASS_UNSUPPORTED
                    )
                for b in range(lo, hi + 1):
                    add_byteset(_fold_byte(b) if self.ci else frozenset({b}))
            else:
                add_byteset(_fold_byte(lo) if self.ci else frozenset({lo}))
        if negated:
            return frozenset(ALL_BYTES - members)
        return frozenset(members)

    def _class_member(self) -> tuple[str, frozenset[int] | int]:
        """One class member: ("byte", code) for a single char usable as a
        range endpoint, or ("set", byteset) for a shorthand class."""
        ch = self.take()
        if ch != "\\":
            code = ord(ch)
            if code >= 128:
                raise self.fail(
                    "non-ASCII in character class",
                    reasons.RX_CLASS_UNSUPPORTED,
                )
            return "byte", code
        esc = self.take() if self.i < self.n else None
        if esc is None:
            raise self.fail("trailing backslash in class")
        if esc in _CLASS_SHORTHANDS:
            return "set", _CLASS_SHORTHANDS[esc]
        if esc in ("p", "P"):
            content = self._posix_contents()
            return "set", (ALL_BYTES - content if esc == "P" else content)
        if esc == "x":
            return "byte", self._hex(2)
        if esc == "u":
            code = self._hex(4)
            if code >= 128:
                raise self.fail(
                    "non-ASCII in character class",
                    reasons.RX_CLASS_UNSUPPORTED,
                )
            return "byte", code
        if esc in _SIMPLE_ESCAPES:
            return "byte", _SIMPLE_ESCAPES[esc]
        if esc == "b":
            raise self.fail(
                "\\b inside character class", reasons.RX_CLASS_UNSUPPORTED
            )
        code = ord(esc)
        if code >= 128:
            raise self.fail(
                "non-ASCII in character class", reasons.RX_CLASS_UNSUPPORTED
            )
        return "byte", code


def parse_java_regex(
    pattern: str, case_insensitive: bool = False, lenient: bool = False
) -> Node:
    """Parse ``pattern`` (Java dialect) into a byte-level AST.

    Raises :class:`RegexUnsupportedError` for constructs outside the automaton
    subset; callers fall back to host-side matching.

    ``lenient=True`` produces a *language-widening approximation* instead
    of raising for most host-only constructs (lookaround → ε, backreference
    → ``.*``-of-any-bytes, atomic → plain group, possessive → greedy, octal
    and control escapes → any byte, ``\\G`` → ε, inline m/s/d flags →
    accepted). The result must NEVER be used for matching — only for
    analyses that are sound under widening, like required-literal
    extraction (literals.py): a literal required by a superset language is
    required by the true one. Constructs whose lenient reading could
    NARROW or reshape the language (x/u/U flags, class intersection,
    nested or non-ASCII classes) still raise.
    """
    return _Parser(pattern, case_insensitive, lenient=lenient).parse()
