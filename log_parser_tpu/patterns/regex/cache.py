"""On-disk DFA compile cache keyed by regex content hash.

SURVEY.md §5.4: the reference has no persistence at all (patterns are
re-read at boot, PatternService.java:45-69). For the high-cardinality
10k-regex configuration, NFA→DFA subset construction + minimization
dominates engine startup, so compiled automata are snapshotted to disk
keyed by ``sha256(compiler_version, regex, flags)`` — per regex, not per
library, so libraries that share patterns share cache entries. Corrupt or
stale entries are ignored and recompiled (the same log-and-skip containment
the loader applies to bad YAML files).

Cache location: ``$LOG_PARSER_TPU_CACHE`` (used exactly as given) or the
default ``~/.cache/log_parser_tpu/dfa``; set ``LOG_PARSER_TPU_CACHE=0`` to
disable.
"""

from __future__ import annotations

import hashlib
import logging
import os
import pathlib
import tempfile

import numpy as np

from log_parser_tpu.patterns.regex.dfa import CompiledDfa, compile_regex_to_dfa

log = logging.getLogger(__name__)

# bump to invalidate every entry when the compiler's output changes shape
COMPILER_VERSION = 1


def _cache_dir() -> pathlib.Path | None:
    env = os.environ.get("LOG_PARSER_TPU_CACHE")
    if env == "0":
        return None
    if env:
        return pathlib.Path(env)
    return pathlib.Path.home() / ".cache" / "log_parser_tpu" / "dfa"


def cache_subdir(name: str) -> pathlib.Path | None:
    """Directory for another cache layer (``bank``, ``ac``, …), honoring
    the same ``LOG_PARSER_TPU_CACHE`` switch: an explicit dir hosts the
    layers as subdirectories beside the dfa entries; the default tree is
    ``~/.cache/log_parser_tpu/<name>``."""
    env = os.environ.get("LOG_PARSER_TPU_CACHE")
    if env == "0":
        return None
    if env:
        return pathlib.Path(env) / name
    return pathlib.Path.home() / ".cache" / "log_parser_tpu" / name


def atomic_publish(directory: pathlib.Path, name: str, writer) -> None:
    """Best-effort atomic cache write shared by every cache layer (dfa /
    bank / ac): ``writer(file)`` fills a tempfile that is then renamed
    into place, so concurrent readers never see a torn entry. ANY
    failure is logged and swallowed — a cache write must never break
    the build it is caching (the read sides contain corrupt entries the
    same way)."""
    tmp = None
    try:
        directory.mkdir(parents=True, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp")
        with os.fdopen(fd, "wb") as f:
            writer(f)
        os.replace(tmp, directory / name)
        tmp = None
    except Exception as exc:
        log.warning("cache write failed for %s: %s", name, exc)
    finally:
        if tmp is not None:
            try:
                os.unlink(tmp)
            except OSError:
                pass


def _key(regex: str, case_insensitive: bool, max_states: int) -> str:
    h = hashlib.sha256()
    h.update(f"v{COMPILER_VERSION}|ci={int(case_insensitive)}|ms={max_states}|".encode())
    h.update(regex.encode())
    return h.hexdigest()


def compile_regex_to_dfa_cached(
    regex: str, case_insensitive: bool = False, max_states: int = 4096
) -> CompiledDfa:
    """``compile_regex_to_dfa`` with a transparent on-disk snapshot."""
    cache = _cache_dir()
    if cache is None:
        return compile_regex_to_dfa(regex, case_insensitive, max_states)
    path = cache / f"{_key(regex, case_insensitive, max_states)}.npz"

    if path.exists():
        try:
            with np.load(path, allow_pickle=False) as z:
                return CompiledDfa(
                    regex=regex,
                    trans=z["trans"],
                    byte_class=z["byte_class"],
                    accept_end=z["accept_end"],
                    start=int(z["start"]),
                    n_states=int(z["n_states"]),
                    n_classes=int(z["n_classes"]),
                )
        except Exception as exc:  # corrupt entry: recompile, rewrite
            log.warning("Ignoring corrupt DFA cache entry %s: %s", path.name, exc)

    dfa = compile_regex_to_dfa(regex, case_insensitive, max_states)
    atomic_publish(
        cache,
        path.name,
        lambda f: np.savez(
            f,
            trans=dfa.trans,
            byte_class=dfa.byte_class,
            accept_end=dfa.accept_end,
            start=np.int64(dfa.start),
            n_states=np.int64(dfa.n_states),
            n_classes=np.int64(dfa.n_classes),
        ),
    )
    return dfa
