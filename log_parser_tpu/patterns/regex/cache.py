"""On-disk DFA compile cache keyed by regex content hash.

SURVEY.md §5.4: the reference has no persistence at all (patterns are
re-read at boot, PatternService.java:45-69). For the high-cardinality
10k-regex configuration, NFA→DFA subset construction + minimization
dominates engine startup, so compiled automata are snapshotted to disk
keyed by ``sha256(compiler_version, regex, flags)`` — per regex, not per
library, so libraries that share patterns share cache entries. Corrupt or
stale entries are ignored and recompiled (the same log-and-skip containment
the loader applies to bad YAML files).

Cache location: ``$LOG_PARSER_TPU_CACHE`` (used exactly as given) or the
default ``~/.cache/log_parser_tpu/dfa``; set ``LOG_PARSER_TPU_CACHE=0`` to
disable.
"""

from __future__ import annotations

import atexit
import hashlib
import json
import logging
import os
import pathlib
import queue
import tempfile
import threading
import time

import numpy as np

from log_parser_tpu.patterns.regex.dfa import CompiledDfa, compile_regex_to_dfa

log = logging.getLogger(__name__)

# bump to invalidate every entry when the compiler's output changes shape
# v3: compile_regex_to_dfa minimizes (minimize.py) — v2 entries would
# serve stale unminimized automata under the new kernel-admission math
COMPILER_VERSION = 3

# ------------------------------------------------------- raw entry format
# Entries are a homegrown raw binary, not npz: np.savez routes every
# array through Python-level zipfile machinery, which is GIL-bound — at
# 10k entries the writes cost ~5 s of a 15 s cold boot even when
# deferred to the write-behind thread (the GIL hands the cost right back
# to the build).  The format is a one-call buffered write and is
# pickle-free (a forged cache entry can corrupt a DFA, which the load
# guards catch, but cannot execute code).

_MAGIC = b"LPDFA\x02"


def _read_arrays(buf: bytes) -> dict[str, np.ndarray]:
    if buf[: len(_MAGIC)] != _MAGIC:
        raise ValueError("bad magic")
    off = len(_MAGIC)
    n = int.from_bytes(buf[off : off + 2], "little")
    off += 2
    out: dict[str, np.ndarray] = {}
    for _ in range(n):
        hlen = int.from_bytes(buf[off : off + 2], "little")
        off += 2
        name, dtype, shape_s = buf[off : off + hlen].decode().split("\n")
        off += hlen
        nbytes = int.from_bytes(buf[off : off + 8], "little")
        off += 8
        shape = tuple(int(x) for x in shape_s.split(",") if x)
        count = int(np.prod(shape, dtype=np.int64)) if shape else 1
        dt = np.dtype(dtype)
        if nbytes != count * dt.itemsize or off + nbytes > len(buf):
            raise ValueError("truncated entry")
        a = np.frombuffer(buf, dtype=dt, count=count, offset=off)
        out[name] = a.reshape(shape)
        off += nbytes
    return out


def _cache_dir() -> pathlib.Path | None:
    env = os.environ.get("LOG_PARSER_TPU_CACHE")
    if env == "0":
        return None
    if env:
        return pathlib.Path(env)
    return pathlib.Path.home() / ".cache" / "log_parser_tpu" / "dfa"


def cache_subdir(name: str) -> pathlib.Path | None:
    """Directory for another cache layer (``bank``, ``ac``, …), honoring
    the same ``LOG_PARSER_TPU_CACHE`` switch: an explicit dir hosts the
    layers as subdirectories beside the dfa entries; the default tree is
    ``~/.cache/log_parser_tpu/<name>``."""
    env = os.environ.get("LOG_PARSER_TPU_CACHE")
    if env == "0":
        return None
    if env:
        return pathlib.Path(env) / name
    return pathlib.Path.home() / ".cache" / "log_parser_tpu" / name


def atomic_publish(directory: pathlib.Path, name: str, writer) -> None:
    """Best-effort atomic cache write shared by every cache layer (dfa /
    bank / ac): ``writer(file)`` fills a tempfile that is flushed,
    fsynced, and then renamed into place, so concurrent readers never
    see a torn entry and a crash (power loss included) leaves either the
    old entry or the complete new one — never a prefix. ANY failure is
    logged and swallowed — a cache write must never break the build it
    is caching (the read sides contain corrupt entries the same way)."""
    tmp = None
    try:
        directory.mkdir(parents=True, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp")
        with os.fdopen(fd, "wb") as f:
            writer(f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, directory / name)
        tmp = None
    except Exception as exc:
        log.warning("cache write failed for %s: %s", name, exc)
    finally:
        if tmp is not None:
            try:
                os.unlink(tmp)
            except OSError:
                pass


# ---------------------------------------------------------- write-behind
# Cache writes are best-effort by contract (atomic_publish swallows every
# failure), so nothing entitles them to the BOOT critical path: a cold
# 10k-library build spent ~5 s of its 15.6 s writing per-regex npz
# entries inline (VERDICT r4 #8).  publish_async defers them to one
# daemon writer thread; flush() (and an atexit flush) bounds the loss
# window for short-lived processes.  Writes stay ordered (one queue, one
# thread) and torn-entry-safe (each still goes through atomic_publish's
# tempfile + rename).

_wb_queue: queue.Queue | None = None
_wb_lock = threading.Lock()


def _wb_loop() -> None:
    while True:
        item = _wb_queue.get()
        try:
            if callable(item):
                item()  # post-write hook (e.g. pack-index invalidation)
            else:
                atomic_publish(*item)
        finally:
            _wb_queue.task_done()


def _ensure_writer() -> queue.Queue:
    global _wb_queue
    with _wb_lock:
        if _wb_queue is None:
            _wb_queue = queue.Queue()
            threading.Thread(
                target=_wb_loop, name="lpt-cache-writebehind", daemon=True
            ).start()
            atexit.register(flush, 30.0)
        return _wb_queue


def publish_async(directory: pathlib.Path, name: str, writer) -> None:
    """:func:`atomic_publish`, deferred to the write-behind thread."""
    _ensure_writer().put((directory, name, writer))


# ------------------------------------------------------------- pack files
# Per-regex DFA entries are coalesced into PACK files (one data blob +
# one json index per build session) instead of one file each: at 10k
# entries the mkstemp/write/rename cycle per file cost ~3 s of wall even
# on the write-behind thread (syscall + GIL handoff), where one
# sequential pack write is ~0.2 s.  Readers union every index in the
# cache dir at first access; entries across sessions coexist (distinct
# uuid-named packs), and a torn pack write is caught by the per-entry
# magic check on read.

_PACK_PENDING_MAX = 2048  # auto-flush bound for long-running processes

_pack_pending: list[tuple[pathlib.Path, str, bytes]] = []  # (dir, key, blob)
_pack_index: dict[str, tuple[pathlib.Path, int, int]] | None = None
_pack_index_dir: pathlib.Path | None = None  # dir the cached index was read from
_pack_lock = threading.Lock()


def _pack_enqueue(cache: pathlib.Path, key: str, blob: bytes) -> None:
    _ensure_writer()  # guarantees the atexit flush is registered
    with _pack_lock:
        _pack_pending.append((cache, key, blob))
        do_flush = len(_pack_pending) >= _PACK_PENDING_MAX
    if do_flush:
        _flush_packs()


def _invalidate_pack_index() -> None:
    global _pack_index
    with _pack_lock:
        _pack_index = None


def _flush_packs() -> None:
    """Hand all pending entries to the write-behind thread as one pack +
    index pair PER TARGET DIR (a process can compile against several
    cache dirs — tests and benches switch LOG_PARSER_TPU_CACHE).
    Unflushed entries of this process are simply absent from lookups (a
    cache miss recompiles — never wrong); the in-memory index is
    re-read only AFTER the writes land (a queued hook), so a lookup
    racing the write cannot cache an index that permanently misses this
    session's entries."""
    global _pack_pending
    with _pack_lock:
        pending, _pack_pending = _pack_pending, []
    if not pending:
        return
    import uuid

    by_dir: dict[pathlib.Path, list[tuple[str, bytes]]] = {}
    for cache, key, entry in pending:
        by_dir.setdefault(cache, []).append((key, entry))
    for cache, entries in by_dir.items():
        # time-ordered stems: the index union takes the LAST entry per
        # key in sorted-name order, so a later republish (corrupt-entry
        # repair) genuinely wins over the torn original
        stem = f"pack-{time.time_ns():020d}-{uuid.uuid4().hex[:8]}"
        blob = bytearray()
        index: dict[str, list[int]] = {}
        for key, entry in entries:
            index[key] = [len(blob), len(entry)]
            blob += entry
        payload = bytes(blob)
        publish_async(cache, f"{stem}.pack", lambda f, p=payload: f.write(p))
        publish_async(
            cache,
            f"{stem}.packidx.json",
            lambda f, s=stem, i=index: f.write(
                json.dumps({"pack": f"{s}.pack", "entries": i}).encode()
            ),
        )
    _ensure_writer().put(_invalidate_pack_index)


def _load_pack_index(cache: pathlib.Path) -> dict:
    """Union of every session's index in the cache dir.  Stems are
    time-ordered and the union is taken in sorted-name order, so the
    NEWEST entry genuinely wins a key collision — which is what lets a
    corrupt-entry repair (republished under a later stem) permanently
    shadow the torn original."""
    global _pack_index, _pack_index_dir
    with _pack_lock:
        if _pack_index is not None and _pack_index_dir == cache:
            return _pack_index
        # one-time sweep of the pre-pack format: v1 kept one .npz per
        # regex (~10k dead files after the format change) that nothing
        # reads anymore
        try:
            for stale in cache.glob("*.npz"):
                try:
                    stale.unlink()
                except OSError:
                    pass
        except OSError:
            pass
        idx: dict[str, tuple[pathlib.Path, int, int]] = {}
        index_files: list[pathlib.Path] = []
        try:
            for ip in sorted(cache.glob("*.packidx.json")):
                try:
                    with open(ip) as f:
                        doc = json.load(f)
                    pack = cache / doc["pack"]
                    for key, (off, size) in doc["entries"].items():
                        idx[key] = (pack, int(off), int(size))
                    index_files.append(ip)
                except Exception as exc:
                    log.warning("Ignoring corrupt pack index %s: %s", ip, exc)
        except OSError:
            pass
        _pack_index = idx
        _pack_index_dir = cache
    if len(index_files) > _PACK_COMPACT_AT:
        _compact_packs(cache, idx, index_files)
    # idx (this load's view) stays valid through a compaction: the same
    # entries now live in the compacted pack, and the next loader
    # re-reads from disk (the compactor invalidated the module cache)
    return idx


#: Session count that triggers compaction: pack/index pairs accumulate
#: one per cold-build session (superseded keys keep their old packs), so
#: without reclamation a churn-heavy cache dir grows monotonically and
#: every fresh process parses every index.
_PACK_COMPACT_AT = 16


def _compact_packs(cache: pathlib.Path, idx: dict, index_files: list) -> None:
    """Rewrite all LIVE entries into one pack and drop the old files.
    Concurrent readers that already resolved an old pack hit ENOENT on
    the unlinked file and fall back to a recompile — never a wrong
    result; the in-memory index is invalidated so this process re-reads
    the compacted state."""
    entries: list[tuple[str, bytes]] = []
    for key in idx:
        blob = _pack_lookup(cache, key)
        if blob is not None:
            entries.append((key, blob))
    if not entries:
        return
    import uuid

    stem = f"pack-{time.time_ns():020d}-{uuid.uuid4().hex[:8]}"
    blob_all = bytearray()
    index: dict[str, list[int]] = {}
    for key, entry in entries:
        index[key] = [len(blob_all), len(entry)]
        blob_all += entry
    payload = bytes(blob_all)
    atomic_publish(cache, f"{stem}.pack", lambda f: f.write(payload))
    atomic_publish(
        cache,
        f"{stem}.packidx.json",
        lambda f: f.write(
            json.dumps({"pack": f"{stem}.pack", "entries": index}).encode()
        ),
    )
    for ip in index_files:
        for p in (ip, cache / ip.name.replace(".packidx.json", ".pack")):
            try:
                p.unlink()
            except OSError:
                pass
    # repoint the caller's live view at the compacted pack (the old
    # paths were just unlinked) and make the next loader re-read disk
    newpack = cache / f"{stem}.pack"
    for key, (off, size) in index.items():
        idx[key] = (newpack, off, size)
    _invalidate_pack_index()


def _pack_lookup(cache: pathlib.Path, key: str) -> bytes | None:
    ent = _load_pack_index(cache).get(key)
    if ent is None or ent[0] is None:
        return None
    pack, off, size = ent
    try:
        with open(pack, "rb") as f:
            f.seek(off)
            return f.read(size)
    except OSError:
        return None


def flush(timeout_s: float | None = None) -> bool:
    """Land queued cache writes and pending pack entries; True iff
    everything drained.  Benches call this between the timed cold build
    and the next timed phase so deferred writes cannot contend with a
    measurement."""
    _flush_packs()
    q = _wb_queue
    if q is None:
        return True
    if timeout_s is None:
        q.join()
        return True
    deadline = time.monotonic() + timeout_s
    while q.unfinished_tasks:
        if time.monotonic() >= deadline:
            return False
        time.sleep(0.05)
    return True


def _key(regex: str, case_insensitive: bool, max_states: int) -> str:
    h = hashlib.sha256()
    h.update(f"v{COMPILER_VERSION}|ci={int(case_insensitive)}|ms={max_states}|".encode())
    h.update(regex.encode())
    return h.hexdigest()


def compile_regex_to_dfa_cached(
    regex: str,
    case_insensitive: bool = False,
    max_states: int = 4096,
    node=None,
) -> CompiledDfa:
    """``compile_regex_to_dfa`` with a transparent on-disk snapshot.
    ``node``: the caller's already-parsed AST, reused on a cache miss so
    the regex is parsed once per boot, not once here and once in the
    column build."""
    cache = _cache_dir()
    if cache is None:
        return compile_regex_to_dfa(regex, case_insensitive, max_states, node=node)
    key = _key(regex, case_insensitive, max_states)

    blob = _pack_lookup(cache, key)
    if blob is not None:
        try:
            z = _read_arrays(blob)
            return CompiledDfa(
                regex=regex,
                trans=z["trans"],
                byte_class=z["byte_class"],
                accept_end=z["accept_end"],
                start=int(z["start"]),
                n_states=int(z["n_states"]),
                n_classes=int(z["n_classes"]),
            )
        except Exception as exc:  # corrupt entry: recompile, republish
            log.warning("Ignoring corrupt DFA cache entry %s: %s", key, exc)
            with _pack_lock:
                if _pack_index is not None:
                    _pack_index.pop(key, None)  # don't re-hit the torn bytes

    dfa = compile_regex_to_dfa(regex, case_insensitive, max_states, node=node)
    _pack_enqueue(cache, key, _entry_bytes(dfa))
    return dfa


def _entry_bytes(dfa: CompiledDfa) -> bytes:
    """THE entry writer (:func:`_read_arrays` is its inverse): flat
    bytes-join of MAGIC, count, then per-array
    ``len(head) | head | nbytes | raw`` records.  Heads are
    newline-separated ``name\\ndtype\\nshape`` (dtype.str contains "|"
    for byte-order-free dtypes like bool, so "|" can't delimit);
    ``reshape`` after ``ascontiguousarray`` keeps 0-d scalars 0-d."""
    parts = [_MAGIC, (6).to_bytes(2, "little")]
    for name, a in (
        ("trans", dfa.trans),
        ("byte_class", dfa.byte_class),
        ("accept_end", dfa.accept_end),
        ("start", np.int64(dfa.start)),
        ("n_states", np.int64(dfa.n_states)),
        ("n_classes", np.int64(dfa.n_classes)),
    ):
        shp = np.shape(a)
        a = np.ascontiguousarray(a).reshape(shp)
        head = f"{name}\n{a.dtype.str}\n{','.join(map(str, shp))}".encode()
        parts.append(len(head).to_bytes(2, "little"))
        parts.append(head)
        parts.append(a.nbytes.to_bytes(8, "little"))
        parts.append(a.tobytes())
    return b"".join(parts)
