"""Union multi-pattern DFA: R regexes, ONE automaton, one gather per byte.

The dense per-regex DFA bank costs one ``[B, R]`` transition gather per
scan step — measured at ~150ms per regex per 200k lines on TPU v5e, where
scalar-indexed gathers run on the (serial) scalar/vector units, making the
match cube linear in library width. This module removes the R factor the
way Hyperscan/RE2 set-matching and Aho-Corasick do: determinize the UNION
of all R NFAs into a single DFA whose states carry per-pattern accept
bitmask words, so the runtime cost per byte is one ``[B]`` state gather
plus one ``[B, W]`` output-word gather (W = ceil(R/32)) — independent of R.

Construction (extends the single-regex subset construction in dfa.py):

- each pattern's Thompson NFA (nfa.py, ``unanchored_prefix=False``) is
  merged into one arena; a shared union start state carries the any-byte
  self-loop, so every pattern restarts its matching at every position —
  ``Matcher.find`` containment semantics for all R patterns at once
  (AnalysisService.java:89-113);
- DFA states are (NFA-state subset, left-context) pairs exactly as in
  dfa.py; zero-width assertions resolve the same way;
- instead of a sticky MATCHED sink (impossible for a union — each pattern
  must latch independently), acceptance is reported as STICKY OUTPUT BITS
  read at runtime from the PRE-transition state: pattern i's bit is set in
  ``out2[state, rw]`` iff ``final_i`` is in the state's closure under
  right-context word-ness ``rw`` — the only right-context the closure
  conditions can depend on. Matches that complete at end-of-line surface
  through ``accept_words[final_state]`` (the state freezes at each line's
  true end, so reading it after the lockstep scan is exact).

Worst-case subset blowup is real (the union construction can multiply
per-pattern state counts), so the builder enforces ``max_states`` and the
caller packs regexes into as many union groups as the budget requires —
even a handful of groups beats R dense columns by orders of magnitude.
"""

from __future__ import annotations

import dataclasses
import re

import numpy as np

from log_parser_tpu.patterns.regex.nfa import Nfa, build_nfa
from log_parser_tpu.patterns.regex.parser import (
    ALL_BYTES,
    WORD_BYTES,
    parse_java_regex,
)

# left-context encoding inside a DFA state (same values as dfa.py)
_BEGIN, _NONWORD, _WORD = 0, 1, 2


class MultiDfaLimitError(ValueError):
    """Union state count exceeded the cap — caller must split the group."""


@dataclasses.dataclass
class CompiledMultiDfa:
    """Packed union DFA over ``n_patterns`` regexes.

    ``trans[state, byte_class[byte]] -> state``; ``out2[state * 2 + rw]``
    (uint32 words) are the patterns whose match completed strictly before
    the current byte given its word-ness ``rw``; ``accept_words[state]``
    are the patterns matched at end-of-input.
    """

    trans: np.ndarray  # int32 [n_states, n_classes]
    byte_class: np.ndarray  # int32 [256]
    cls_is_word: np.ndarray  # int32 [n_classes] 0/1
    out2: np.ndarray  # uint32 [n_states * 2, n_words]
    accept_words: np.ndarray  # uint32 [n_states, n_words]
    start: int
    n_states: int
    n_classes: int
    n_patterns: int
    n_words: int
    # pre-minimization state count (0 = unknown/not minimized) — surfaced
    # in the kernel-plan geometry so plane shrink is visible, not silent
    n_states_unmin: int = 0

    def matches(self, data: bytes) -> np.ndarray:
        """Reference executor: bool [n_patterns] containment flags."""
        hits = np.zeros(self.n_words, dtype=np.uint32)
        state = self.start
        for b in data:
            cls = self.byte_class[b]
            rw = self.cls_is_word[cls]
            hits |= self.out2[state * 2 + rw]
            state = self.trans[state, cls]
        hits |= self.accept_words[state]
        bits = np.zeros(self.n_patterns, dtype=bool)
        for i in range(self.n_patterns):
            bits[i] = (hits[i // 32] >> np.uint32(i % 32)) & np.uint32(1)
        return bits


def _merge_nfas(nfas: list[Nfa]) -> tuple[Nfa, list[int]]:
    """Offset-merge ``nfas`` into one arena with a shared unanchored start.
    Returns (merged, final_state_of_each_branch)."""
    eps: list[list[tuple[str | None, int]]] = [[]]
    trans: list[list[tuple[frozenset[int], int]]] = [[]]
    start = 0
    trans[start].append((ALL_BYTES, start))  # find(): restart at every byte
    finals: list[int] = []
    for nfa in nfas:
        off = len(eps)
        for s in range(nfa.n_states):
            eps.append([(c, d + off) for (c, d) in nfa.eps[s]])
            trans.append([(bs, d + off) for (bs, d) in nfa.trans[s]])
        eps[start].append((None, nfa.start + off))
        finals.append(nfa.final + off)
    return (
        Nfa(n_states=len(eps), start=start, final=-1, eps=eps, trans=trans),
        finals,
    )


def _byte_classes(nfa: Nfa) -> tuple[np.ndarray, list[int]]:
    """Partition 0..255 refining every byteset in the union NFA plus
    word-char membership (identical scheme to dfa.py:_byte_classes)."""
    bytesets = {bs for row in nfa.trans for (bs, _) in row}
    signatures: dict[tuple, int] = {}
    byte_class = np.zeros(256, dtype=np.int32)
    reps: list[int] = []
    for b in range(256):
        sig = tuple(b in bs for bs in bytesets) + (b in WORD_BYTES,)
        cls = signatures.get(sig)
        if cls is None:
            cls = len(signatures)
            signatures[sig] = cls
            reps.append(b)
        byte_class[b] = cls
    return byte_class, reps


def _closure(
    nfa: Nfa, states: frozenset[int], left: int, right_word: bool | None
) -> frozenset[int]:
    """Epsilon closure under assertion conditions (same rules as dfa.py)."""
    left_word = left == _WORD
    at_start = left == _BEGIN
    at_end = right_word is None
    rw = bool(right_word)

    out = set(states)
    stack = list(states)
    while stack:
        s = stack.pop()
        for cond, dst in nfa.eps[s]:
            if dst in out:
                continue
            if cond is None:
                ok = True
            elif cond == "^":
                ok = at_start
            elif cond == "$":
                ok = at_end
            elif cond == "b":
                ok = left_word != (False if at_end else rw)
            elif cond == "B":
                ok = left_word == (False if at_end else rw)
            else:  # pragma: no cover
                raise AssertionError(f"unknown assertion {cond}")
            if ok:
                out.add(dst)
                stack.append(dst)
    return frozenset(out)


def _bits_of(finals_in: frozenset[int], final_bit: dict[int, int], n_words: int):
    words = np.zeros(n_words, dtype=np.uint32)
    for f, bit in final_bit.items():
        if f in finals_in:
            words[bit // 32] |= np.uint32(1) << np.uint32(bit % 32)
    return words


def compile_union_nfas(
    nfas: list[Nfa], max_states: int = 8192, minimize: bool = True
) -> CompiledMultiDfa:
    """Determinize the union of ``nfas`` with per-pattern output bits.

    Uses the native (C++) union builder when available — it also minimizes
    (signature-partition Moore refinement), shrinking the packed tables —
    with this module's Python construction as the fallback. ``minimize``
    applies partition-refinement minimization + byte-class re-merge
    (minimize.py) to the result; the ``max_states`` budget is always
    checked on the UNMINIMIZED construction, so group packing decisions
    don't depend on the minimizer."""
    merged, finals = _merge_nfas(nfas)
    n_patterns = len(nfas)

    from log_parser_tpu.native.dfabuild import (
        DfaLimitExceeded,
        build_multi_dfa_native,
    )

    try:
        built = build_multi_dfa_native(merged, finals, max_states=max_states)
    except DfaLimitExceeded:
        raise MultiDfaLimitError(f"union DFA exceeded {max_states} states")
    if built is not None:
        trans, byte_class, cls_word, out2, accept_words, start = built
        md = CompiledMultiDfa(
            trans=trans,
            byte_class=byte_class,
            cls_is_word=cls_word,
            out2=out2,
            accept_words=accept_words,
            start=start,
            n_states=trans.shape[0],
            n_classes=trans.shape[1],
            n_patterns=n_patterns,
            n_words=max(1, -(-n_patterns // 32)),
        )
    else:
        md = _compile_union_python(merged, finals, n_patterns, max_states)
    if minimize:
        from log_parser_tpu.patterns.regex.minimize import minimize_multi_dfa

        md = minimize_multi_dfa(md)
    return md


def _compile_union_python(
    merged: Nfa, finals: list[int], n_patterns: int, max_states: int
) -> CompiledMultiDfa:
    final_bit = {f: i for i, f in enumerate(finals)}
    final_set = frozenset(finals)
    n_words = max(1, -(-n_patterns // 32))

    byte_class, reps = _byte_classes(merged)
    n_classes = len(reps)
    rep_is_word = [b in WORD_BYTES for b in reps]
    cls_is_word = np.asarray([1 if w else 0 for w in rep_is_word], np.int32)

    states: dict[tuple[frozenset[int], int], int] = {}
    trans_rows: list[list[int]] = []
    out_rows: list[tuple[np.ndarray, np.ndarray]] = []  # (nonword, word)
    accept_rows: list[np.ndarray] = []
    core_of: list[tuple[frozenset[int], int]] = []

    def intern(core: frozenset[int], left: int) -> int:
        key = (core, left)
        sid = states.get(key)
        if sid is None:
            sid = len(trans_rows)
            if sid >= max_states:
                raise MultiDfaLimitError(
                    f"union DFA exceeded {max_states} states"
                )
            states[key] = sid
            trans_rows.append([-1] * n_classes)
            out_rows.append((None, None))  # type: ignore[arg-type]
            accept_rows.append(None)  # type: ignore[arg-type]
            core_of.append(key)
        return sid

    start = intern(frozenset({merged.start}), _BEGIN)
    sid = start
    while sid < len(trans_rows):
        core, left = core_of[sid]
        closed_nw = _closure(merged, core, left, False)
        closed_w = _closure(merged, core, left, True)
        closed_end = _closure(merged, core, left, None)
        out_rows[sid] = (
            _bits_of(closed_nw & final_set, final_bit, n_words),
            _bits_of(closed_w & final_set, final_bit, n_words),
        )
        accept_rows[sid] = _bits_of(closed_end & final_set, final_bit, n_words)
        for cls in range(n_classes):
            rep = reps[cls]
            closed = closed_w if rep_is_word[cls] else closed_nw
            moved = frozenset(
                dst
                for s in closed
                for (bs, dst) in merged.trans[s]
                if rep in bs
            )
            trans_rows[sid][cls] = intern(
                moved, _WORD if rep_is_word[cls] else _NONWORD
            )
        sid += 1

    n_states = len(trans_rows)
    out2 = np.zeros((n_states * 2, n_words), dtype=np.uint32)
    for s, (nw, w) in enumerate(out_rows):
        out2[s * 2] = nw
        out2[s * 2 + 1] = w
    return CompiledMultiDfa(
        trans=np.asarray(trans_rows, dtype=np.int32),
        byte_class=byte_class,
        cls_is_word=cls_is_word,
        out2=out2,
        accept_words=np.asarray(accept_rows, dtype=np.uint32),
        start=start,
        n_states=n_states,
        n_classes=n_classes,
        n_patterns=n_patterns,
        n_words=n_words,
    )


def compile_union_regexes(
    entries: list[tuple[str, bool]],
    max_states: int = 8192,
    minimize: bool = True,
) -> CompiledMultiDfa:
    """``entries``: (regex, case_insensitive) in bit order."""
    nfas = [
        build_nfa(parse_java_regex(rx, ci), unanchored_prefix=False)
        for rx, ci in entries
    ]
    return compile_union_nfas(nfas, max_states=max_states, minimize=minimize)


# Regexes with unbounded gaps (``.*`` bridges, open-ended counted reps)
# multiply against EACH OTHER in a union subset construction — each
# contributes an independent "attempt in progress" flag, a 2^k factor —
# while gap-free patterns (literal alternations, bounded classes) union
# near-linearly. Packing sorts gap-free first so they fill large groups and
# gap regexes cluster into small ones.
_GAP = re.compile(r"\.\s*[*+]|\{\d+,[^0-9]|\[[^\]]*\][*+]")


def pack_union_groups(
    entries: list[tuple[object, str, bool]],
    max_states: int = 8192,
    max_group: int = 64,
    minimize: bool = True,
):
    """Greedily pack ``(key, regex, case_insensitive)`` entries into union
    groups under the state budget.

    Adaptive chunking: each group tries to absorb a chunk of pending
    entries in ONE build, doubling the chunk on success and bisecting on
    overflow, so the number of (cheap, budget-capped) native builds stays
    ~O(groups · log n) instead of O(n). Returns ``(groups, rejected)``
    where groups are ``(keys, CompiledMultiDfa)`` with bit *i* of the
    automaton = ``keys[i]``, and rejected entries exceeded the budget even
    alone (caller keeps them on another tier).

    Trial builds skip minimization (the ``max_states`` packing budget is
    defined over the raw subset construction, and minimizing every trial
    would multiply boot cost); each SEALED group is minimized once, so
    group membership is identical with or without ``minimize``.
    """
    pending = sorted(entries, key=lambda e: bool(_GAP.search(e[1])))
    groups: list[tuple[list[object], CompiledMultiDfa]] = []
    rejected: list[tuple[object, str, bool]] = []
    while pending:
        cur: list[tuple[object, str, bool]] = []
        built: CompiledMultiDfa | None = None
        chunk = min(48, max_group)
        while pending and len(cur) < max_group:
            chunk = max(1, min(chunk, len(pending), max_group - len(cur)))
            trial = cur + pending[:chunk]
            try:
                b = compile_union_regexes(
                    [(rx, ci) for _, rx, ci in trial],
                    max_states=max_states,
                    minimize=False,
                )
            except MultiDfaLimitError:
                if chunk == 1:
                    if not cur:
                        rejected.append(pending.pop(0))
                        chunk = min(48, max_group)
                        continue
                    break  # group full — seal it
                chunk //= 2
                continue
            cur = trial
            built = b
            pending = pending[chunk:]
            chunk *= 2
        if cur:
            assert built is not None
            if minimize:
                from log_parser_tpu.patterns.regex.minimize import (
                    minimize_multi_dfa,
                )

                built = minimize_multi_dfa(built)
            groups.append(([k for k, _, _ in cur], built))
    return groups, rejected
