"""Subset construction: NFA → byte-class-compressed DFA.

The DFA executes ``Matcher.find()`` boolean semantics in a single forward
pass over a line's bytes: one table lookup per byte, acceptance read from a
per-state bit at end-of-line. Zero-width assertions (``^`` ``$`` ``\\b``
``\\B``) are resolved during construction by tracking the class of the
previously consumed byte in the DFA state — no lookaround at runtime, which
is what makes the automaton executable as a ``lax.scan`` of gathers on TPU.

Matches become *sticky*: as soon as any substring match completes the DFA
enters an absorbing MATCHED state, so "final state is accepting" ⇔ "the
line contains a match" — the exact boolean the reference's hot loop needs
(AnalysisService.java:95).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from log_parser_tpu.patterns.regex.nfa import Nfa, build_nfa
from log_parser_tpu.patterns.regex.parser import (
    WORD_BYTES,
    Node,
    parse_java_regex,
)

# left-context encoding inside a DFA state
_BEGIN, _NONWORD, _WORD = 0, 1, 2


class DfaLimitError(ValueError):
    """State count exceeded the cap — caller must fall back to host regex."""

    # single decline cause, so the reason code is a class attribute; kept in
    # sync with reasons.DFA_TOO_LARGE (asserted in tests/test_patlint.py)
    code = "dfa-too-large"


@dataclasses.dataclass
class CompiledDfa:
    """A packed DFA: ``trans[state, byte_class[byte]] -> state``;
    ``accept_end[final_state]`` decides the match."""

    regex: str
    trans: np.ndarray  # int32 [n_states, n_classes]
    byte_class: np.ndarray  # int32 [256]
    accept_end: np.ndarray  # bool [n_states]
    start: int
    n_states: int
    n_classes: int

    def matches(self, data: bytes) -> bool:
        """Reference executor (used by tests and the host fallback)."""
        state = self.start
        trans = self.trans
        classes = self.byte_class
        for b in data:
            state = trans[state, classes[b]]
        return bool(self.accept_end[state])


def _closure(
    nfa: Nfa, states: frozenset[int], left: int, right_word: bool | None
) -> frozenset[int]:
    """Epsilon closure under assertion conditions.

    ``left``: class of the previously consumed byte (_BEGIN before any).
    ``right_word``: word-ness of the next byte, or None for end-of-input.
    """
    left_word = left == _WORD
    at_start = left == _BEGIN
    at_end = right_word is None
    rw = bool(right_word)

    out = set(states)
    stack = list(states)
    while stack:
        s = stack.pop()
        for cond, dst in nfa.eps[s]:
            if dst in out:
                continue
            if cond is None:
                ok = True
            elif cond == "^":
                ok = at_start
            elif cond == "$":
                ok = at_end
            elif cond == "b":
                ok = left_word != (False if at_end else rw)
            elif cond == "B":
                ok = left_word == (False if at_end else rw)
            else:  # pragma: no cover
                raise AssertionError(f"unknown assertion {cond}")
            if ok:
                out.add(dst)
                stack.append(dst)
    return frozenset(out)


def _byte_classes(nfa: Nfa) -> tuple[np.ndarray, list[int]]:
    """Partition 0..255 into equivalence classes that refine every byteset
    in the NFA plus word-char membership (assertions depend on it).
    Returns (byte→class map, one representative byte per class)."""
    bytesets = {bs for row in nfa.trans for (bs, _) in row}
    signatures: dict[tuple, int] = {}
    byte_class = np.zeros(256, dtype=np.int32)
    reps: list[int] = []
    for b in range(256):
        sig = tuple(b in bs for bs in bytesets) + (b in WORD_BYTES,)
        cls = signatures.get(sig)
        if cls is None:
            cls = len(signatures)
            signatures[sig] = cls
            reps.append(b)
        byte_class[b] = cls
    return byte_class, reps


def compile_nfa_to_dfa(nfa: Nfa, regex: str = "", max_states: int = 4096) -> CompiledDfa:
    byte_class, reps = _byte_classes(nfa)
    n_classes = len(reps)
    rep_is_word = [b in WORD_BYTES for b in reps]

    # state 0 = MATCHED sink (absorbing, accepting)
    MATCHED = 0
    states: dict[tuple[frozenset[int], int], int] = {}
    trans_rows: list[list[int]] = [[MATCHED] * n_classes]
    accept_end: list[bool] = [True]
    core_of: list[tuple[frozenset[int], int] | None] = [None]

    def intern(core: frozenset[int], left: int) -> int:
        key = (core, left)
        sid = states.get(key)
        if sid is None:
            sid = len(trans_rows)
            if sid > max_states:
                raise DfaLimitError(
                    f"DFA for {regex!r} exceeded {max_states} states"
                )
            states[key] = sid
            trans_rows.append([-1] * n_classes)
            accept_end.append(False)
            core_of.append(key)
        return sid

    start = intern(frozenset({nfa.start}), _BEGIN)
    # intern() assigns ids sequentially, so a simple id-order sweep processes
    # every state exactly once, including ones interned mid-sweep.
    sid = start
    while sid < len(trans_rows):
        core, left = core_of[sid]  # type: ignore[misc]
        # end-of-input acceptance
        accept_end[sid] = nfa.final in _closure(nfa, core, left, None)
        for cls in range(n_classes):
            rep = reps[cls]
            rw = rep_is_word[cls]
            closed = _closure(nfa, core, left, rw)
            if nfa.final in closed:
                # a match completed just before this byte — sticky
                trans_rows[sid][cls] = MATCHED
            else:
                moved = frozenset(
                    dst for s in closed for (bs, dst) in nfa.trans[s] if rep in bs
                )
                trans_rows[sid][cls] = intern(moved, _WORD if rw else _NONWORD)
        sid += 1

    return CompiledDfa(
        regex=regex,
        trans=np.asarray(trans_rows, dtype=np.int32),
        byte_class=byte_class,
        accept_end=np.asarray(accept_end, dtype=bool),
        start=start,
        n_states=len(trans_rows),
        n_classes=n_classes,
    )


def compile_regex_to_dfa(
    regex: str,
    case_insensitive: bool = False,
    max_states: int = 4096,
    node: Node | None = None,
    minimize: bool = True,
) -> CompiledDfa:
    """Java regex → packed DFA with ``find()`` substring semantics.

    Uses the native (C++) subset construction when available — it also
    minimizes, shrinking the packed device tables — with the Python builder
    as fallback; ``minimize`` applies the partition-refinement shrink
    (minimize.py) on the Python path so both builders hand back minimal
    automata (the ``max_states`` cap is checked on the raw construction
    either way). Raises :class:`RegexUnsupportedError` (dialect) or
    :class:`DfaLimitError` (state blowup); both mean "host fallback".
    ``node``: an already-parsed AST for this exact (regex, flags) pair,
    so boot paths that parsed for literal/sequence extraction don't pay
    the parse twice."""
    if node is None:
        node = parse_java_regex(regex, case_insensitive)
    nfa = build_nfa(node, unanchored_prefix=True)

    from log_parser_tpu.native.dfabuild import DfaLimitExceeded, build_dfa_native

    try:
        built = build_dfa_native(nfa, max_states=max_states)
    except DfaLimitExceeded:
        raise DfaLimitError(f"DFA for {regex!r} exceeded {max_states} states")
    if built is not None:
        trans, byte_class, accept, start = built
        return CompiledDfa(
            regex=regex,
            trans=trans,
            byte_class=byte_class,
            accept_end=accept,
            start=start,
            n_states=trans.shape[0],
            n_classes=trans.shape[1],
        )
    dfa = compile_nfa_to_dfa(nfa, regex=regex, max_states=max_states)
    if minimize:
        from log_parser_tpu.patterns.regex.minimize import minimize_dfa

        dfa = minimize_dfa(dfa)
    return dfa
