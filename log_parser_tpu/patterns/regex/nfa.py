"""Thompson NFA construction with zero-width assertion edges.

Epsilon edges optionally carry an assertion condition (``^`` ``$`` ``b``
``B``) that the subset construction resolves against the surrounding
character context — the standard technique for compiling word boundaries
into a DFA without lookaround.
"""

from __future__ import annotations

import dataclasses

from log_parser_tpu.patterns.regex.parser import (
    Alt,
    Assertion,
    Cat,
    Empty,
    Lit,
    Node,
    Rep,
)

# An NFA fragment is (start, end); the builder owns the global state store.
# eps[s] -> list of (cond, dst); cond None = unconditional.
# trans[s] -> list of (byteset, dst).


@dataclasses.dataclass
class Nfa:
    n_states: int
    start: int
    final: int
    eps: list[list[tuple[str | None, int]]]
    trans: list[list[tuple[frozenset[int], int]]]


class _Builder:
    # Repetition upper bound guard: {1,1000} would explode state count.
    MAX_COUNTED = 64

    def __init__(self) -> None:
        self.eps: list[list[tuple[str | None, int]]] = []
        self.trans: list[list[tuple[frozenset[int], int]]] = []

    def new_state(self) -> int:
        self.eps.append([])
        self.trans.append([])
        return len(self.eps) - 1

    def add_eps(self, src: int, dst: int, cond: str | None = None) -> None:
        self.eps[src].append((cond, dst))

    def add_trans(self, src: int, byteset: frozenset[int], dst: int) -> None:
        self.trans[src].append((byteset, dst))

    def build(self, node: Node) -> tuple[int, int]:
        if isinstance(node, Empty):
            s = self.new_state()
            e = self.new_state()
            self.add_eps(s, e)
            return s, e
        if isinstance(node, Lit):
            s = self.new_state()
            e = self.new_state()
            self.add_trans(s, node.byteset, e)
            return s, e
        if isinstance(node, Assertion):
            s = self.new_state()
            e = self.new_state()
            self.add_eps(s, e, node.kind)
            return s, e
        if isinstance(node, Cat):
            first_s, prev_e = self.build(node.parts[0])
            for part in node.parts[1:]:
                s, e = self.build(part)
                self.add_eps(prev_e, s)
                prev_e = e
            return first_s, prev_e
        if isinstance(node, Alt):
            s = self.new_state()
            e = self.new_state()
            for option in node.options:
                os, oe = self.build(option)
                self.add_eps(s, os)
                self.add_eps(oe, e)
            return s, e
        if isinstance(node, Rep):
            return self._build_rep(node)
        raise TypeError(f"unknown AST node {node!r}")

    def _build_rep(self, node: Rep) -> tuple[int, int]:
        from log_parser_tpu.patterns.regex import reasons
        from log_parser_tpu.patterns.regex.parser import RegexUnsupportedError

        lo, hi = node.lo, node.hi
        if hi is not None and hi > self.MAX_COUNTED:
            raise RegexUnsupportedError(
                f"counted repetition max {hi} too large",
                code=reasons.RX_REPEAT_TOO_LARGE,
            )
        if lo > self.MAX_COUNTED:
            raise RegexUnsupportedError(
                f"counted repetition min {lo} too large",
                code=reasons.RX_REPEAT_TOO_LARGE,
            )

        s = self.new_state()
        prev = s
        # lo mandatory copies
        for _ in range(lo):
            cs, ce = self.build(node.child)
            self.add_eps(prev, cs)
            prev = ce
        e = self.new_state()
        if hi is None:
            # Kleene tail: loop on one more copy
            cs, ce = self.build(node.child)
            self.add_eps(prev, cs)
            self.add_eps(ce, cs)
            self.add_eps(ce, e)
            self.add_eps(prev, e)
        else:
            self.add_eps(prev, e)
            for _ in range(hi - lo):
                cs, ce = self.build(node.child)
                self.add_eps(prev, cs)
                self.add_eps(ce, e)
                prev = ce
        return s, e


def build_nfa(node: Node, unanchored_prefix: bool = True) -> Nfa:
    """Build the NFA for ``find()`` (substring) semantics: an any-byte
    self-loop before the pattern lets a match start at every position
    (AnalysisService.java:95 uses ``Matcher.find``)."""
    from log_parser_tpu.patterns.regex.parser import ALL_BYTES

    b = _Builder()
    start = b.new_state()
    ps, pe = b.build(node)
    if unanchored_prefix:
        b.add_trans(start, ALL_BYTES, start)
    b.add_eps(start, ps)
    return Nfa(
        n_states=len(b.eps),
        start=start,
        final=pe,
        eps=b.eps,
        trans=b.trans,
    )
