"""Enumerated reason codes for regex-compilation declines.

Every way a pattern can fall off a device tier — a parser reject
(:class:`RegexUnsupportedError`), a determinization blowup
(:class:`DfaLimitError`), a bit-program reject
(:class:`BitUnsupportedError`) — carries one of these stable codes on the
exception's ``code`` attribute. The static analyzer
(:mod:`log_parser_tpu.analysis.tiers`) predicts tiers by catching the
SAME exceptions from the SAME compile entry points, so a predicted
reason and the build-time reason can never drift apart as free strings
would: both cite one registry entry.

Codes are grouped by the stage that emits them:

- ``rx-*``  — the Java-dialect parser (parser.py) / NFA builder (nfa.py);
  the pattern is host-only (``re`` fallback) unless noted;
- ``dfa-*`` — subset construction (dfa.py / native builder);
- ``bit-*`` — the bit-parallel program compiler (bitprog.py); the
  pattern stays on an automaton tier, it just cannot ride the
  gather-free bit engine.

``docs/PATTERNS.md`` carries the operator-facing table; the hygiene gate
(tools/hygiene.py) fails if a code exists here without a doc row.
"""

from __future__ import annotations

# --------------------------------------------------------- parser declines
RX_SYNTAX = "rx-syntax"
RX_LOOKAROUND = "rx-lookaround"
RX_BACKREFERENCE = "rx-backreference"
RX_POSSESSIVE = "rx-possessive"
RX_ATOMIC_GROUP = "rx-atomic-group"
RX_CLASS_INTERSECTION = "rx-class-intersection"
RX_CLASS_UNSUPPORTED = "rx-class-unsupported"
RX_INLINE_FLAGS = "rx-inline-flags"
RX_ESCAPE_UNSUPPORTED = "rx-escape-unsupported"
RX_QUOTED_QUANTIFIER = "rx-quoted-quantifier"
RX_REPEAT_TOO_LARGE = "rx-repeat-too-large"

# ----------------------------------------------------------- DFA declines
DFA_TOO_LARGE = "dfa-too-large"

# ------------------------------------------------------ bit-tier declines
BIT_EXPANSION_TOO_LARGE = "bit-expansion-too-large"
BIT_REPEAT_TOO_LARGE = "bit-repeat-too-large"
BIT_UNBOUNDED_GROUP = "bit-unbounded-group-repeat"
BIT_ASSERT_SHAPE = "bit-assert-shape"
BIT_EMPTY_MATCH = "bit-empty-match"
BIT_TOO_LONG = "bit-alt-too-long"
BIT_TOO_WIDE = "bit-too-wide"
BIT_UNSUPPORTED_NODE = "bit-unsupported-node"

# ------------------------------------------------------------ non-decline
SUPPORTED = "supported"

REASONS: dict[str, str] = {
    RX_SYNTAX: "regex syntax error (unbalanced group, dangling "
    "quantifier, bad escape, unterminated class)",
    RX_LOOKAROUND: "lookahead/lookbehind has no finite-automaton "
    "equivalent",
    RX_BACKREFERENCE: "backreferences (numbered or named) are not "
    "regular",
    RX_POSSESSIVE: "possessive quantifier semantics are refused, not "
    "approximated",
    RX_ATOMIC_GROUP: "atomic group semantics are refused, not "
    "approximated",
    RX_CLASS_INTERSECTION: "character-class intersection (&&) is "
    "unsupported",
    RX_CLASS_UNSUPPORTED: "character-class shape outside the byte "
    "dialect (nested class, non-ASCII member, \\b in class, bad range)",
    RX_INLINE_FLAGS: "inline flags beyond (?i) reshape the language",
    RX_ESCAPE_UNSUPPORTED: "escape outside the automaton dialect "
    "(octal, control, \\G, unknown \\p{...})",
    RX_QUOTED_QUANTIFIER: "quantifier after a multi-char \\Q..\\E run "
    "binds differently in Java",
    RX_REPEAT_TOO_LARGE: "counted repetition bound exceeds the NFA "
    "state guard",
    DFA_TOO_LARGE: "subset construction exceeded the DFA state cap",
    BIT_EXPANSION_TOO_LARGE: "alternative/assert expansion exceeds the "
    "bit-program cap",
    BIT_REPEAT_TOO_LARGE: "bounded repeat too large for the bit "
    "fragment",
    BIT_UNBOUNDED_GROUP: "unbounded repeat of a multi-position group "
    "is outside the bit fragment",
    BIT_ASSERT_SHAPE: "assertion placement the bit engine cannot gate "
    "exactly (mid-pattern anchor, assert on optional item, impure "
    "cascade, unsatisfiable assert)",
    BIT_EMPTY_MATCH: "alternative can match the empty string",
    BIT_TOO_LONG: "alternative exceeds the per-alternative position "
    "budget",
    BIT_TOO_WIDE: "program exceeds the per-column position budget",
    BIT_UNSUPPORTED_NODE: "AST node kind outside the bit fragment",
    SUPPORTED: "no decline — the construct set is fully supported",
}


def describe(code: str) -> str:
    return REASONS.get(code, "unknown reason code")
