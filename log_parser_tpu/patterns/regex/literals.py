"""Required-literal factor extraction.

For the AC-prefilter match path (Hyperscan architecture: prefilter +
verify), each regex needs a *required literal set*: a set of literals such
that **every** line matched by the regex contains at least one of them as a
substring. A combined Aho-Corasick pass then cheaply finds candidate
(line, pattern) pairs on device; only candidates are verified exactly.

Soundness rules (no match may escape the prefilter):

- a literal may be *case-folded* (matched insensitively) — that only widens
  the prefilter;
- a literal may be *truncated* — any substring of a required literal is
  itself required;
- alternation requires factors from **all** branches (union);
- a ``Rep`` with ``lo == 0`` contributes nothing (it can match empty);
- when in doubt, return ``None`` → the pattern is unfactorable and falls
  back to the exact DFA / host path.
"""

from __future__ import annotations

import dataclasses

from log_parser_tpu.patterns.regex.parser import (
    Alt,
    Assertion,
    Cat,
    Empty,
    Lit,
    Node,
    Rep,
)

# BUMP when extraction output changes shape or content: the whole-library
# bank snapshot (patterns/libcache.py) stores extracted literals and
# exact sequences, and invalidates on this constant
LITERALS_VERSION = 2

MAX_LITERALS = 64  # per pattern: larger sets filter poorly anyway
MAX_LITERAL_LEN = 24  # truncation keeps the required property


@dataclasses.dataclass(frozen=True)
class Literal:
    """A concrete byte string; ``ci`` means match case-insensitively
    (stored case-folded to lowercase)."""

    text: bytes
    ci: bool = False

    def fold(self) -> "Literal":
        return Literal(self.text.lower(), True)


def _case_pair(bs: frozenset[int]) -> int | None:
    """byteset == {lower, upper} of one ASCII letter → the lowercase byte."""
    if len(bs) == 2:
        a, b = sorted(bs)
        if chr(b).isascii() and chr(b).islower() and ord(chr(b).upper()) == a:
            return b
    return None


def _single(bs: frozenset[int]) -> int | None:
    if len(bs) == 1:
        return next(iter(bs))
    return None


def _score(lits: frozenset[Literal]) -> tuple[int, int]:
    """Bigger is better: (shortest literal length, -set size)."""
    return (min(len(l.text) for l in lits), -len(lits))


def _truncate(lit: Literal) -> Literal:
    if len(lit.text) <= MAX_LITERAL_LEN:
        return lit
    return Literal(lit.text[:MAX_LITERAL_LEN], lit.ci)


def extract_literals(node: Node) -> frozenset[Literal] | None:
    """Best required-literal set for ``node``, or None if unfactorable."""
    result = _extract(node)
    if result is None:
        return None
    return frozenset(_truncate(l) for l in result)


def _extract(node: Node) -> frozenset[Literal] | None:
    if isinstance(node, (Empty, Assertion)):
        return None
    if isinstance(node, Lit):
        b = _single(node.byteset)
        if b is not None:
            return frozenset({Literal(bytes([b]))})
        folded = _case_pair(node.byteset)
        if folded is not None:
            return frozenset({Literal(bytes([folded]), ci=True)})
        return None  # wide class: useless single-byte factor
    if isinstance(node, Rep):
        if node.lo >= 1:
            return _extract(node.child)
        return None
    if isinstance(node, Alt):
        union: set[Literal] = set()
        for option in node.options:
            sub = _extract(option)
            if sub is None:
                return None
            union.update(sub)
            if len(union) > MAX_LITERALS:
                return None
        return frozenset(union)
    if isinstance(node, Cat):
        return _extract_cat(node)
    raise TypeError(f"unknown AST node {node!r}")


def _extract_cat(node: Cat) -> frozenset[Literal] | None:
    """Concatenation: merge runs of fixed single-byte (or case-pair) parts
    into long literals; otherwise fall back to the best single child factor."""
    candidates: list[frozenset[Literal]] = []

    run: list[tuple[int, bool]] = []  # (lowercased byte, ci)

    def flush_run() -> None:
        if run:
            text = bytes(b for b, _ in run)
            ci = any(ci for _, ci in run)
            candidates.append(
                frozenset({Literal(text.lower(), True) if ci else Literal(text)})
            )
            run.clear()

    for part in node.parts:
        if isinstance(part, (Assertion, Empty)):
            # zero-width: does not interrupt adjacency of bytes (Empty
            # appears where the lenient parser dropped a lookaround/\G —
            # both sides stay contiguous in every true match)
            continue
        piece = part
        # a{n,m} with n>=1 contributes at least one child occurrence
        if isinstance(piece, Rep) and piece.lo >= 1 and isinstance(piece.child, Lit):
            piece = piece.child
            appended_rep = True
        else:
            appended_rep = False
        if isinstance(piece, Lit):
            b = _single(piece.byteset)
            if b is not None:
                run.append((b, False))
                if appended_rep:
                    flush_run()  # repetition count unknown beyond 1 occurrence
                continue
            folded = _case_pair(piece.byteset)
            if folded is not None:
                run.append((folded, True))
                if appended_rep:
                    flush_run()
                continue
        # non-literal part: close the run, consider the child's own factor
        flush_run()
        sub = _extract(part)
        if sub is not None:
            candidates.append(sub)
    flush_run()

    if not candidates:
        return None
    return max(candidates, key=_score)


# ---- exact fixed-length sequences (the Shift-Or fast path) ----------------

MAX_EXACT_SEQS = 16  # alternative sequences per regex
# sequences over 32 positions ride Shift-Or's cross-word carry chains
# (ops/shiftor.py); 64 bounds a chain to two words
MAX_EXACT_LEN = 64


def exact_sequences(node: Node) -> tuple[tuple[frozenset[int], ...], ...] | None:
    """When the regex is equivalent to "line contains a substring matching
    one of these fixed-length byte-class sequences", return the sequences;
    else None. Unlike :func:`extract_literals` (a *necessary* condition for
    the prefilter), this is an exact characterization: bit-parallel
    Shift-Or over these sequences IS the regex's find() answer, no DFA or
    verification needed.

    Handled: byte classes, concatenation, alternation, and counted
    repetition with a fixed count. Rejected: assertions (``^`` ``$``
    ``\\b``), variable repetition, empty-matchable parts, and anything
    exceeding the sequence-count/length caps.
    """
    seqs = _exact(node)
    if seqs is None or not seqs:
        return None
    if len(seqs) > MAX_EXACT_SEQS:
        return None
    if any(not 1 <= len(s) <= MAX_EXACT_LEN for s in seqs):
        return None
    return tuple(seqs)


def _exact(node: Node) -> list[tuple[frozenset[int], ...]] | None:
    if isinstance(node, Lit):
        return [(node.byteset,)]
    if isinstance(node, Alt):
        out: list[tuple[frozenset[int], ...]] = []
        for option in node.options:
            sub = _exact(option)
            if sub is None:
                return None
            out.extend(sub)
            if len(out) > MAX_EXACT_SEQS:
                return None
        return out
    if isinstance(node, Cat):
        acc: list[tuple[frozenset[int], ...]] = [()]
        for part in node.parts:
            sub = _exact(part)
            if sub is None:
                return None
            acc = [a + s for a in acc for s in sub]
            if len(acc) > MAX_EXACT_SEQS or any(
                len(a) > MAX_EXACT_LEN for a in acc
            ):
                return None
        return acc
    if isinstance(node, Rep):
        if node.hi is None or node.lo != node.hi or node.lo < 1:
            return None  # variable length breaks fixed-position bit packing
        sub = _exact(node.child)
        if sub is None:
            return None
        acc = [()]
        for _ in range(node.lo):
            acc = [a + s for a in acc for s in sub]
            if len(acc) > MAX_EXACT_SEQS or any(
                len(a) > MAX_EXACT_LEN for a in acc
            ):
                return None
        return acc
    # Assertion, Empty: position-dependent / empty-matchable -> not exact
    return None
