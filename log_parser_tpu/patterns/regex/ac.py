"""Combined Aho-Corasick automaton over extracted literals.

One goto-complete AC automaton (dense next-state table, fail links folded
in) scans every log line once — a single gather per byte on TPU, regardless
of how many patterns the library holds. Outputs are bitmasks over literal
ids packed into uint32 words; each node's mask is pre-OR'd along its fail
chain so the runtime never walks links.

An automaton is *pure*: all its literals share one case mode. Case-sensitive
literals scan raw bytes; case-insensitive ones are stored folded and scan a
case-folded copy of the line (mixing modes in one trie conflates edges and
can drop matches — the matcher bank builds one automaton per mode instead).

Byte-class compression keeps the table narrow: only bytes that occur in
some literal get a class; everything else shares one "other" column.
"""

from __future__ import annotations

import hashlib
import logging
from collections import deque

import numpy as np

log = logging.getLogger(__name__)

AC_VERSION = 1


class AhoCorasick:
    """Multi-literal matcher over byte strings.

    ``literals``: the byte strings, id = list index. Matching is exact on
    bytes — for case-insensitive behavior, fold the literals before
    construction and fold the input before scanning.

    ``groups``: optional group id per literal (e.g. the owning matcher
    column); output bitmasks are then over groups, so several literals of
    one column OR into a single bit and duplicated strings across columns
    simply share trie nodes. Default: each literal is its own group.
    """

    def __init__(self, literals: list[bytes], groups: list[int] | None = None):
        self.literals = literals
        n = len(literals)
        self.n_literals = n
        if groups is None:
            groups = list(range(n))
        assert len(groups) == n
        self.groups = groups
        self.n_groups = (max(groups) + 1) if groups else 0
        self.n_words = max(1, (self.n_groups + 31) // 32)

        if self._build_native(literals, groups):
            return

        # --- trie -----------------------------------------------------------
        children: list[dict[int, int]] = [{}]
        out: list[set[int]] = [set()]
        for lid, text in enumerate(literals):
            node = 0
            for b in text:
                nxt = children[node].get(b)
                if nxt is None:
                    children.append({})
                    out.append(set())
                    nxt = len(children) - 1
                    children[node][b] = nxt
                node = nxt
            out[node].add(lid)
        n_nodes = len(children)

        # --- byte classes ---------------------------------------------------
        used = sorted({b for ch in children for b in ch})
        byte_class = np.zeros(256, dtype=np.int32)  # 0 = "other"
        for cls, b in enumerate(used, start=1):
            byte_class[b] = cls
        n_classes = len(used) + 1
        class_byte = [0] + used

        # --- goto-complete automaton via BFS fail links ---------------------
        goto = np.zeros((n_nodes, n_classes), dtype=np.int32)
        fail = np.zeros(n_nodes, dtype=np.int32)
        queue: deque[int] = deque()
        for cls in range(1, n_classes):
            child = children[0].get(class_byte[cls])
            if child is not None:
                goto[0, cls] = child
                queue.append(child)
        while queue:
            node = queue.popleft()
            out[node] |= out[fail[node]]
            for cls in range(1, n_classes):
                child = children[node].get(class_byte[cls])
                if child is not None:
                    fail[child] = goto[fail[node], cls]
                    goto[node, cls] = child
                    queue.append(child)
                else:
                    goto[node, cls] = goto[fail[node], cls]

        # --- packed outputs (bits are GROUP ids) ----------------------------
        out_words = np.zeros((n_nodes, self.n_words), dtype=np.uint32)
        for node in range(n_nodes):
            for lid in out[node]:
                gid = groups[lid]
                out_words[node, gid // 32] |= np.uint32(1 << (gid % 32))

        self.n_nodes = n_nodes
        self.n_classes = n_classes
        self.goto = goto
        self.byte_class = byte_class
        self.out_words = out_words
        self.has_out = out_words.any(axis=1)

    def _build_native(self, literals: list[bytes], groups: list[int]) -> bool:
        """Native trie/BFS build (same algorithm, C++): the Python BFS is
        ~1.6 s of a 10k-library cold boot.  False -> Python fallback."""
        import ctypes

        from log_parser_tpu.native import get_lib

        lib = get_lib()
        if lib is None:
            return False
        blob = np.frombuffer(b"".join(literals) or b"\0", dtype=np.uint8)
        offs = np.zeros(len(literals) + 1, dtype=np.int64)
        np.cumsum([len(t) for t in literals], out=offs[1:])
        groups_a = np.asarray(groups or [0], dtype=np.int32)

        def p(arr, ct):
            return arr.ctypes.data_as(ctypes.POINTER(ct))

        out_nodes = ctypes.c_int32(0)
        out_classes = ctypes.c_int32(0)
        out_nwords = ctypes.c_int32(0)
        handle = lib.lpn_ac_build(
            p(blob, ctypes.c_uint8), p(offs, ctypes.c_int64),
            p(groups_a, ctypes.c_int32), len(literals), self.n_groups,
            ctypes.byref(out_nodes), ctypes.byref(out_classes),
            ctypes.byref(out_nwords),
        )
        if not handle:
            return False
        try:
            nn, nc = out_nodes.value, out_classes.value
            if out_nwords.value != self.n_words:
                # native/Python word-count disagreement (e.g. a stale
                # prebuilt .so): fall back rather than size-mismatch the
                # read below — never an assert, which -O would strip
                # right in front of a native-sized memcpy
                return False
            goto = np.zeros((nn, nc), dtype=np.int32)
            byte_class = np.zeros(256, dtype=np.int32)
            out_words = np.zeros((nn, self.n_words), dtype=np.uint32)
            has_out = np.zeros(nn, dtype=np.uint8)
            lib.lpn_ac_read(
                handle,
                p(goto, ctypes.c_int32), p(byte_class, ctypes.c_int32),
                p(out_words, ctypes.c_uint32), p(has_out, ctypes.c_uint8),
            )
        finally:
            lib.lpn_ac_free(handle)
        self.n_nodes = nn
        self.n_classes = nc
        self.goto = goto
        self.byte_class = byte_class
        self.out_words = out_words
        self.has_out = has_out.astype(bool)
        return True

    # ---------------------------------------------------------- disk cache

    @classmethod
    def build_cached(
        cls, literals: list[bytes], groups: list[int] | None = None
    ) -> "AhoCorasick":
        """Construct with an on-disk snapshot of the built tables, keyed
        by literal/group content. The Python BFS trie build dominates a
        10k-library MatcherBanks boot (~3 s); the snapshot turns a warm
        boot into one npz read. Same containment as the DFA cache:
        corrupt entries are ignored and rebuilt, writes publish
        atomically."""
        from log_parser_tpu.patterns.regex.cache import cache_subdir

        d = cache_subdir("ac")
        if d is None:
            return cls(literals, groups)
        h = hashlib.sha256()
        h.update(f"ac-v{AC_VERSION}|".encode())
        gs = groups if groups is not None else range(len(literals))
        for lit, g in zip(literals, gs):
            h.update(f"{g}:{len(lit)}:".encode())
            h.update(lit)
        path = d / f"{h.hexdigest()}.npz"

        if path.exists():
            try:
                with np.load(path, allow_pickle=False) as z:
                    self = cls.__new__(cls)
                    self.literals = literals
                    self.n_literals = len(literals)
                    self.groups = list(groups) if groups is not None else list(
                        range(len(literals))
                    )
                    self.n_groups = int(z["n_groups"])
                    self.n_words = int(z["n_words"])
                    self.n_nodes = int(z["n_nodes"])
                    self.n_classes = int(z["n_classes"])
                    self.goto = z["goto"]
                    self.byte_class = z["byte_class"]
                    self.out_words = z["out_words"]
                    self.has_out = z["has_out"]
                    return self
            except Exception as exc:
                log.warning("Ignoring corrupt AC cache entry %s: %s",
                            path.name, exc)

        ac = cls(literals, groups)
        from log_parser_tpu.patterns.regex.cache import atomic_publish

        atomic_publish(
            d,
            path.name,
            lambda f: np.savez(
                f,
                n_groups=np.int64(ac.n_groups),
                n_words=np.int64(ac.n_words),
                n_nodes=np.int64(ac.n_nodes),
                n_classes=np.int64(ac.n_classes),
                goto=ac.goto,
                byte_class=ac.byte_class,
                out_words=ac.out_words,
                has_out=ac.has_out,
            ),
        )
        return ac

    # ---------------------------------------------------------------- scans

    def scan(self, data: bytes) -> set[int]:
        """Host reference: literal ids hit anywhere in ``data``."""
        node = 0
        hits: set[int] = set()
        for b in data:
            node = int(self.goto[node, self.byte_class[b]])
            if self.has_out[node]:
                words = self.out_words[node]
                for w in range(self.n_words):
                    bits = int(words[w])
                    while bits:
                        low = bits & -bits
                        hits.add(w * 32 + low.bit_length() - 1)
                        bits ^= low
        return hits

    def scan_lines(self, lines_u8: np.ndarray, lengths: np.ndarray) -> np.ndarray:
        """Vectorized numpy scan of a padded [B, T] uint8 line matrix.

        Returns hit masks uint32 [B, n_words]. Positions ≥ length are
        masked out, so padding byte values never produce hits.
        """
        B, T = lines_u8.shape
        states = np.zeros(B, dtype=np.int32)
        hits = np.zeros((B, self.n_words), dtype=np.uint32)
        for t in range(T):
            cls = self.byte_class[lines_u8[:, t]]
            nxt = self.goto[states, cls]
            active = t < lengths
            states = np.where(active, nxt, states)
            hits |= np.where(active[:, None], self.out_words[states], np.uint32(0))
        return hits


def fold_bytes(data: bytes) -> bytes:
    """ASCII case folding (matches Java's CASE_INSENSITIVE default)."""
    return data.lower()


def fold_lines_u8(lines_u8: np.ndarray) -> np.ndarray:
    """Vectorized ASCII lowercase of a uint8 matrix."""
    is_upper = (lines_u8 >= ord("A")) & (lines_u8 <= ord("Z"))
    return np.where(is_upper, lines_u8 + 32, lines_u8).astype(np.uint8)
