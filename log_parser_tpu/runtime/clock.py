"""Public surface of the swappable process clock.

The implementation lives in :mod:`log_parser_tpu._clock` — a zero-dependency
top-level module so that ``golden/``, ``models/`` and ``obs/`` (which
``runtime.engine`` itself imports) can use the seam without creating an
import cycle through ``runtime/__init__``.  This module is the documented
import path for the simulator and tests::

    from log_parser_tpu.runtime import clock
    clock.install(my_virtual_clock)

Both paths share one switchboard: ``install`` here and ``install`` on
``log_parser_tpu._clock`` mutate the same global.
"""

from log_parser_tpu._clock import (  # noqa: F401
    Clock,
    SystemClock,
    active,
    install,
    installed,
    mono,
    sleep,
    wait,
    wall,
)

__all__ = [
    "Clock",
    "SystemClock",
    "active",
    "install",
    "installed",
    "mono",
    "sleep",
    "wait",
    "wall",
]
