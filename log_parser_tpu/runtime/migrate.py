"""Crash-safe tenant live migration + the health-driven drain supervisor.

A tenant used to have exactly two states in this process: resident or
evicted. Eviction (runtime/tenancy.py) folds the WAL and drops the
engine, but the tenant's *traffic* has nowhere to go — rolling restarts,
SLO-driven placement and drain-before-upgrade all need a third verb:
**move a live tenant to another serving process without dropping its
requests or forking its frequency history**. This module composes the
primitives that already exist — the reload quiesce gate
(``AnalysisEngine._request_scope``), the namespaced CRC-framed WAL
(runtime/journal.py), the warm bank rebuild (patterns/libcache.py), the
streaming carry (``host_carry()``) — into that verb.

Protocol (one migration ``mid``, every step a CRC-framed, fsync'd record
in a per-migration journal under ``<state>/_migrate/``):

source ``<mid>.src.wal``::

    BEGIN → QUIESCE → EXPORT(sha) → IMPORT_ACK → CUTOVER → COMPLETE
                                               ^^^^^^^
                                    the single commit point

target ``<mid>.dst.wal``::

    STAGE(sha) → STAGED → ACTIVATE → APPLIED

Ownership invariant — *exactly one owner at every instant, across
``kill -9`` on either side at any record boundary*:

- the source serves the tenant until its CUTOVER record is durable;
  after CUTOVER it 307-forwards the tenant (``Location`` +
  ``Retry-After``) until callers re-resolve;
- the target refuses to apply an import until its ACTIVATE record is
  durable; a staged-but-not-activated import is **discarded on boot**
  (covering the window where the target acked but the source died
  before CUTOVER — the source recovers as owner, so the target's copy
  must die);
- a source journal that ends before CUTOVER recovers to ABORT: the
  source still owns the tenant, nothing moved;
- a source journal that ends at CUTOVER (no COMPLETE) recovers by
  re-installing the forward and — given a target — resuming the
  import/activate from the still-on-disk bundle. The bundle file is
  deleted only at COMPLETE/ABORT, so resumption never needs the dead
  process's memory.

The exported bundle is versioned JSON with a sha256 sidecar: the bank's
content hash (``patterns/libcache.library_key`` — the target rebuilds
the bank warm from its own config and *verifies* it hashes identically),
the frequency snapshot (portable ages) + journal epoch, parked mined
candidates, and open-stream session carries. Frequency restore rides
``DurableFrequencyTracker.restore`` (a journaled barrier), so the
migrated state is durable on the target the instant it is applied and
scores replay bit-identically to the no-migration run
(tests/test_migrate.py pins the full crash × transport matrix).

On top sits :class:`DrainSupervisor` (``--drain`` admin + SIGTERM):
flip the admission gate (readiness 503, ``/q/health`` shows a DRAINING
check), migrate every resident tenant out under ``--drain-deadline-s``
— re-basing or explicitly error-framing open stream sessions rather
than waiting forever — then finalize *every* resident engine (fold each
tenant WAL, flush each batcher, dump the OTLP span file) and let
shutdown complete. An optional health watch triggers the same drain
when SLO burn or the device breaker crosses a threshold
(``--drain-on-burn``).

Fault sites (tools/chaos_sweep.py --group migrate; tools/hygiene.py
check 18 pins them): ``migrate_export`` (bundle export, source),
``migrate_import`` (bundle verify/stage, target), ``migrate_cutover``
(the commit point, source — a fault here aborts with the source still
owner).
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import logging
import os
import struct
import threading
import time
import zlib

from log_parser_tpu import _clock as pclock
from log_parser_tpu.runtime import faults, pressure
from log_parser_tpu.runtime.journal import _atomic_write
from log_parser_tpu.runtime.tenancy import DEFAULT_TENANT, TenantForwarded

log = logging.getLogger(__name__)

BUNDLE_VERSION = 1

# the migration chaos vocabulary — tools/hygiene.py check 18 pins every
# key to a docs/OPS.md row AND to a live faults.fire site
FAULT_SITES = {
    "migrate_export": "bundle export under quiesce (source, Migrator)",
    "migrate_import": "bundle verify + warm stage (target, stage_import)",
    "migrate_cutover": "ownership commit point (source, pre-CUTOVER)",
}

# frame header shared with runtime/journal.py: payload length + CRC32
_FRAME = struct.Struct("<II")
_MAX_PAYLOAD = 64 << 20

MIGRATE_DIR = "_migrate"

# source-side protocol order (the crash-matrix axis in tests)
SOURCE_RECORDS = ("begin", "quiesce", "export", "import_ack", "cutover",
                  "complete")
TARGET_RECORDS = ("stage", "staged", "activate", "applied")


class MigrationError(Exception):
    """A refused or aborted migration. ``status`` maps onto HTTP
    (409 protocol conflict, 400 bad request, 404 unknown tenant)."""

    def __init__(self, reason: str, status: int = 409):
        super().__init__(reason)
        self.reason = reason
        self.status = status


class MigrationCrash(RuntimeError):
    """Raised by the ``crash_after`` test hook immediately after the
    named record became durable — and before ANY cleanup. Because every
    journal append is fsync'd and no abort record is written, the
    process state this leaves behind is byte-for-byte what ``kill -9``
    at that boundary leaves behind (the same rationale as
    ``FrequencyJournal.abandon``)."""


class MigrationJournal:
    """Append-only CRC-framed record log for ONE migration. Every
    append is write+flush+fsync — migration records are rare and each
    one is a protocol state transition, so durability-per-record is the
    point, not a cost."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._fp = open(path, "ab")

    def append(self, kind: str, **fields) -> None:
        payload = dict(fields)
        payload["k"] = kind
        raw = json.dumps(payload, separators=(",", ":")).encode("utf-8")
        frame = _FRAME.pack(len(raw), zlib.crc32(raw)) + raw
        self._fp.write(frame)
        self._fp.flush()
        os.fsync(self._fp.fileno())

    def close(self) -> None:
        fp, self._fp = self._fp, None
        if fp is not None:
            try:
                fp.close()
            except OSError:  # pragma: no cover
                pass

    @staticmethod
    def replay(path: str) -> list[dict]:
        """Whole frames only. A torn tail (a crash mid-append) is
        quarantined to ``.torn`` and truncated away, exactly like the
        frequency WAL: the record that tore never became durable, so
        the protocol state is the last whole record."""
        if not os.path.exists(path):
            return []
        with open(path, "rb") as f:
            raw = f.read()
        out: list[dict] = []
        off = 0
        while off + _FRAME.size <= len(raw):
            length, crc = _FRAME.unpack_from(raw, off)
            start = off + _FRAME.size
            if length > _MAX_PAYLOAD or start + length > len(raw):
                break
            payload = raw[start:start + length]
            if zlib.crc32(payload) != crc:
                break
            try:
                out.append(json.loads(payload.decode("utf-8")))
            except ValueError:
                break
            off = start + length
        if off < len(raw):
            try:
                with open(path + ".torn", "ab") as f:
                    f.write(raw[off:])
                with open(path, "r+b") as f:
                    f.truncate(off)
            except OSError:  # pragma: no cover - quarantine is best-effort
                log.exception("failed to quarantine torn migration journal")
        return out


def _frame_records(records: list[dict]) -> bytes:
    out = []
    for payload in records:
        raw = json.dumps(payload, separators=(",", ":")).encode("utf-8")
        out.append(_FRAME.pack(len(raw), zlib.crc32(raw)) + raw)
    return b"".join(out)


def compact_journal(path: str) -> bool:
    """Truncate ONE terminal migration journal past its decision records.

    Migration journals are append-only and never expire (forwards live
    in them, nowhere else), so a long-lived node accretes every record
    of every migration it ever ran. Past the terminal record only the
    *decision* matters: a source journal compacts to
    ``[meta, cutover, complete]`` (or ``[meta, abort]``), a target
    journal to ``[meta, applied]`` (or ``[meta, discard]``) — exactly
    the records :meth:`Migrator.recover` consults. Non-terminal
    journals (a migration still running, or one recover() must still
    converge) are left untouched, which also keeps compaction safe
    against the open ``_dst_journals`` handles: only *closed* journals
    carry a terminal record.

    The rewrite is atomic (tmp + fsync + ``os.replace``) and preserves
    the file's mtime — recover() arbitrates ownership verdicts between
    a tenant's src and dst journals BY mtime, so compaction must not
    promote a stale verdict to newest. A crash before the replace
    leaves the original intact (the ``.compact`` tmp is swept on the
    next pass); a crash after leaves the already-valid compacted form.
    """
    records = MigrationJournal.replay(path)
    if len(records) < 2:
        return False
    kinds = [r.get("k") for r in records]
    meta = records[0]
    if path.endswith(".src.wal"):
        terminal = next(
            (k for k in ("complete", "abort") if k in kinds), None
        )
        if terminal is None:
            return False
        keep = [meta]
        if terminal == "complete":
            cutover = next(
                (r for r in records if r.get("k") == "cutover"), None
            )
            if cutover is not None and cutover is not meta:
                keep.append(cutover)
        keep.append(next(r for r in records if r.get("k") == terminal))
    elif path.endswith(".dst.wal"):
        terminal = next(
            (k for k in ("applied", "discard") if k in kinds), None
        )
        if terminal is None:
            return False
        keep = [meta, next(r for r in records if r.get("k") == terminal)]
    else:
        return False
    if len(keep) >= len(records):
        return False  # already compact — idempotent
    try:
        st = os.stat(path)
    except OSError:
        return False
    tmp = path + ".compact"
    with open(tmp, "wb") as f:
        f.write(_frame_records(keep))
        f.flush()
        os.fsync(f.fileno())
    os.utime(tmp, (st.st_atime, st.st_mtime))
    os.replace(tmp, path)
    try:
        dfd = os.open(os.path.dirname(path) or ".", os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:  # pragma: no cover - platform-specific directory fsync
        pass
    return True


def canonical_bundle_bytes(bundle: dict) -> bytes:
    """The hashed wire form: key-sorted compact JSON. Source and target
    canonicalize independently, so the sha survives any transport
    re-encoding in between."""
    return json.dumps(
        bundle, sort_keys=True, separators=(",", ":")
    ).encode("utf-8")


@contextlib.contextmanager
def _quiesced(engine, timeout_s: float):
    """The reload quiesce gate, reused verbatim for migration: block new
    top-level requests, wait for in-flight ones to drain, hold the gate
    for the export, release on exit. Mirrors ``apply_library``'s
    critical section without swapping anything."""
    deadline = pclock.mono() + timeout_s
    with engine._quiesce_cv:
        if engine._swap_pending:
            raise MigrationError("a reload or migration is already quiescing")
        engine._swap_pending = True
        try:
            while engine._active_requests > 0:
                remaining = deadline - pclock.mono()
                if remaining <= 0:
                    raise MigrationError(
                        f"migration quiesce timed out after {timeout_s:g}s "
                        f"({engine._active_requests} request(s) in flight)"
                    )
                engine._quiesce_cv.wait(remaining)
        except BaseException:
            engine._swap_pending = False
            engine._quiesce_cv.notify_all()
            raise
    try:
        yield
    finally:
        with engine._quiesce_cv:
            engine._swap_pending = False
            engine._quiesce_cv.notify_all()


class LocalTarget:
    """In-process migration target: drives the destination
    :class:`Migrator` directly. This is the placement-move form of the
    protocol (``TenantPlacement.move`` composes with it) and the only
    target kind that can ADOPT live stream sessions — the session
    object re-bases onto the destination engine mid-session instead of
    being error-framed."""

    can_adopt_sessions = True

    def __init__(self, migrator: "Migrator", url: str = "local://peer"):
        self.migrator = migrator
        self.url = url

    def stage(self, bundle: dict, sha: str) -> dict:
        return self.migrator.stage_import(bundle, sha)

    def activate(self, mid: str) -> dict:
        return self.migrator.activate(mid)

    def adopt_session(self, tenant_id: str, sess) -> bool:
        from log_parser_tpu.runtime.stream import shared_manager

        # internal resolution: on a round-trip the destination may still
        # hold its stale outbound forward until activation clears it
        ctx = self.migrator.registry.resolve(tenant_id, ignore_forward=True)
        try:
            shared_manager(ctx.engine).adopt(sess)
            return True
        except Exception:
            log.exception("session adopt failed; falling back to close")
            return False
        finally:
            ctx.unpin()


class HttpTarget:
    """Cross-process migration target: drives the destination's
    ``/admin/migrate/import`` + ``/admin/migrate/activate`` endpoints.
    Live stream sessions cannot ride an HTTP connection to another
    process, so they are closed with an explicit ``error`` frame naming
    this target (the drain-or-rebase contract's bounded branch)."""

    can_adopt_sessions = False

    def __init__(self, url: str, timeout_s: float = 60.0):
        self.url = url.rstrip("/")
        self.timeout_s = float(timeout_s)

    def _post(self, path: str, payload: dict) -> dict:
        import urllib.error
        import urllib.request

        body = json.dumps(payload).encode("utf-8")
        req = urllib.request.Request(
            self.url + path, data=body,
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
                return json.loads(resp.read().decode("utf-8"))
        except urllib.error.HTTPError as exc:
            detail = exc.read().decode("utf-8", "replace")[:512]
            raise MigrationError(
                f"target {self.url}{path} -> {exc.code}: {detail}"
            ) from exc
        except (urllib.error.URLError, OSError) as exc:
            raise MigrationError(
                f"target {self.url}{path} unreachable: {exc}"
            ) from exc

    def stage(self, bundle: dict, sha: str) -> dict:
        return self._post("/admin/migrate/import",
                          {"bundle": bundle, "sha": sha})

    def activate(self, mid: str) -> dict:
        return self._post("/admin/migrate/activate", {"mid": mid})

    def adopt_session(self, tenant_id: str, sess) -> bool:
        return False


# /metrics view over Migrator.stats() — registered against the default
# engine's obs bundle at construction (log_parser_tpu/obs); hygiene
# check 18 pins the logparser_migration_* families to OPS.md rows
METRIC_SAMPLES = (
    ("completed", "logparser_migration_total", {"outcome": "completed"}),
    ("aborted", "logparser_migration_total", {"outcome": "aborted"}),
    ("staged", "logparser_migration_total", {"outcome": "staged"}),
    ("activated", "logparser_migration_total", {"outcome": "activated"}),
    ("recoveredResumed", "logparser_migration_total",
     {"outcome": "recovered_resumed"}),
    ("recoveredDiscarded", "logparser_migration_total",
     {"outcome": "recovered_discarded"}),
    ("sessionsMoved", "logparser_migration_total",
     {"outcome": "session_moved"}),
    ("sessionsClosed", "logparser_migration_total",
     {"outcome": "session_closed"}),
    ("active", "logparser_migration_active", {}),
    ("forwards", "logparser_migration_forwards", {}),
)


class Migrator:
    """Both halves of the migration protocol for one process: the
    source side (:meth:`migrate`), the target side
    (:meth:`stage_import` / :meth:`activate`), and boot-time
    :meth:`recover` that drives every partially-run journal back to a
    single-owner state.

    ``crash_after`` (tests only): a set of record kinds; the protocol
    raises :class:`MigrationCrash` immediately after appending a listed
    record — no cleanup, no abort record — simulating ``kill -9`` at
    exactly that boundary."""

    def __init__(
        self,
        registry,
        *,
        state_root: str,
        node_url: str = "",
        quiesce_timeout_s: float = 30.0,
        clock=pclock.mono,
        crash_after=None,
    ):
        self.registry = registry
        self.dir = os.path.join(str(state_root), MIGRATE_DIR)
        os.makedirs(self.dir, exist_ok=True)
        self.node_url = node_url
        self.quiesce_timeout_s = float(quiesce_timeout_s)
        self.clock = clock
        self.crash_after = frozenset(crash_after or ())
        self._lock = threading.Lock()
        self._migrating: set[str] = set()  # tenant ids with a live protocol
        self._staged: dict[str, dict] = {}  # mid -> bundle (target side)
        self._dst_journals: dict[str, MigrationJournal] = {}
        self._seq = len(os.listdir(self.dir))
        # counters (GET /trace/last `migration` block + /metrics)
        self.started = 0
        self.completed = 0
        self.aborted = 0
        self.staged = 0
        self.activated = 0
        self.recovered_resumed = 0
        self.recovered_discarded = 0
        self.sessions_moved = 0
        self.sessions_closed = 0
        self.compacted = 0  # terminal journals truncated (boot + soft pressure)
        # composition-root hooks: on_release is called as
        # (tenant_id, location) whenever a durable verdict says the tenant
        # moved off this node — wired to Replicator.release_tenant so the
        # standby stops warming a tenant this node no longer owns (else a
        # later promotion resurrects it); on_adopt is called as
        # (tenant_id,) when a verdict says ownership came back, voiding
        # any standing release
        self.on_release = None
        self.on_adopt = None
        # on_primacy_check is called with no args before accepting an
        # import; wired to Replicator.verify_primacy so a stale primary
        # (standby promoted, demotion not yet observed) refuses the
        # bundle pre-cutover instead of discovering the fence mid-adopt
        self.on_primacy_check = None
        obs = getattr(registry.default_engine, "obs", None)
        if obs is not None:
            obs.add_stats_collector("migrate", self.stats, METRIC_SAMPLES)

    # ------------------------------------------------------------- helpers

    def _crash(self, kind: str) -> None:
        if kind in self.crash_after:
            raise MigrationCrash(f"injected crash after {kind!r} record")

    def _notify_release(self, tenant_id: str, location: str) -> None:
        if self.on_release is None or not tenant_id or not location:
            return
        try:
            self.on_release(tenant_id, location)
        except Exception:  # pragma: no cover - hook must not break cutover
            log.exception(
                "release hook failed for %r -> %r", tenant_id, location
            )

    def _notify_adopt(self, tenant_id: str) -> None:
        if self.on_adopt is None or not tenant_id:
            return
        try:
            self.on_adopt(tenant_id)
        except Exception:  # pragma: no cover - hook must not break import
            log.exception("adopt hook failed for %r", tenant_id)

    def _spans(self):
        obs = getattr(self.registry.default_engine, "obs", None)
        return None if obs is None else obs.spans

    def _src_path(self, mid: str) -> str:
        return os.path.join(self.dir, f"{mid}.src.wal")

    def _dst_path(self, mid: str) -> str:
        return os.path.join(self.dir, f"{mid}.dst.wal")

    def _bundle_path(self, mid: str) -> str:
        return os.path.join(self.dir, f"{mid}.bundle.json")

    def _read_bundle(self, mid: str) -> dict:
        path = self._bundle_path(mid)
        with open(path, "rb") as f:
            raw = f.read()
        try:
            with open(path + ".sum", "r", encoding="utf-8") as f:
                want = f.read().strip()
        except OSError:
            want = None
        if want is not None and hashlib.sha256(raw).hexdigest() != want:
            raise MigrationError(f"bundle {mid!r} failed its sha256 sidecar")
        return json.loads(raw.decode("utf-8"))

    def _drop_bundle(self, mid: str) -> None:
        for suffix in ("", ".sum"):
            try:
                os.remove(self._bundle_path(mid) + suffix)
            except OSError:
                pass

    # ------------------------------------------------------------- source

    def migrate(
        self,
        tenant_id: str,
        target,
        *,
        retry_after_s: int = 5,
        timeout_s: float | None = None,
        mid: str | None = None,
    ) -> dict:
        """Run the full source side of the protocol for ``tenant_id``.
        Returns a summary dict; raises :class:`MigrationError` on any
        pre-CUTOVER failure (the tenant stays owned here, an ABORT
        record closes the journal). Failures *after* CUTOVER leave a
        resumable journal — ownership has already moved."""
        if not tenant_id or tenant_id == DEFAULT_TENANT:
            raise MigrationError("cannot migrate the default tenant", 400)
        timeout_s = self.quiesce_timeout_s if timeout_s is None else timeout_s
        with self._lock:
            if tenant_id in self._migrating:
                raise MigrationError(
                    f"tenant {tenant_id!r} is already migrating"
                )
            self._migrating.add(tenant_id)
        ctx = None
        try:
            if self.registry.forward_for(tenant_id) is not None:
                raise MigrationError(
                    f"tenant {tenant_id!r} has already been migrated", 409
                )
            ctx = self.registry.context_if_resident(tenant_id)
            if ctx is not None:
                ctx.pin()
            else:
                try:
                    # a cold tenant still migrates: build it warm from disk
                    # so its folded state travels (resolve pins for us)
                    ctx = self.registry.resolve(tenant_id)
                except TenantForwarded as exc:
                    # a fence (demoted node) or forward installed outside
                    # the migration plane: this node cannot export what it
                    # does not own — same refusal as the forward_for guard
                    raise MigrationError(
                        f"tenant {tenant_id!r} is not owned here"
                        f" ({exc})", 409
                    ) from exc
            return self._migrate_pinned(
                tenant_id, ctx, target, retry_after_s, timeout_s, mid
            )
        finally:
            with self._lock:
                self._migrating.discard(tenant_id)

    def _migrate_pinned(self, tenant_id, ctx, target, retry_after_s,
                        timeout_s, mid) -> dict:
        with self._lock:
            self._seq += 1
            mid = mid or f"m{self._seq:06d}-{tenant_id}"
        t0 = pclock.mono()
        self.started += 1
        jr = MigrationJournal(self._src_path(mid))
        eng = ctx.engine
        spans = self._spans()
        trace = f"migrate:{mid}"
        try:
            jr.append("begin", mid=mid, tenant=tenant_id, target=target.url)
            self._crash("begin")
            with _quiesced(eng, timeout_s):
                jr.append("quiesce")
                self._crash("quiesce")
                et0 = time.perf_counter()
                bundle, sha = self._export_bundle(mid, tenant_id, eng)
                jr.append("export", sha=sha)
                self._crash("export")
                if spans is not None:
                    spans.annotate(
                        trace, "migrate_export", time.perf_counter() - et0,
                        attrs={"sha": sha[:12],
                               "bytes": len(canonical_bundle_bytes(bundle))},
                    )
            it0 = time.perf_counter()
            ack = target.stage(bundle, sha)
            if not isinstance(ack, dict) or ack.get("sha") != sha:
                raise MigrationError(
                    f"target acked the wrong bundle hash: {ack!r}"
                )
            jr.append("import_ack", sha=sha)
            self._crash("import_ack")
            if spans is not None:
                spans.annotate(trace, "migrate_import",
                               time.perf_counter() - it0,
                               attrs={"target": target.url})
            ct0 = time.perf_counter()
            faults.fire("migrate_cutover")  # conlint: contained-by-caller (aborts pre-cutover; the source keeps serving)
            jr.append("cutover", location=target.url,
                      retryAfterS=int(retry_after_s))
            self._crash("cutover")
        except MigrationCrash:
            raise
        except MigrationError as exc:
            self._abort(jr, mid, tenant_id, ctx, exc, t0)
            raise
        except BaseException as exc:
            self._abort(jr, mid, tenant_id, ctx, exc, t0)
            raise MigrationError(f"migration aborted: {exc!r}") from exc
        # ---- past the commit point: ownership has moved. Everything
        # below must converge even if it fails here — recover() finishes
        # the same steps from the journal + bundle.
        self.registry.set_forward(tenant_id, target.url, int(retry_after_s))
        # release at the commit point, not at COMPLETE: ownership moved
        # with the CUTOVER record, and a crash anywhere between here and
        # COMPLETE must not leave the standby believing the tenant is
        # still pair-owned (a later promotion would resurrect it empty)
        self._notify_release(tenant_id, target.url)
        ctx.unpin()
        moved, closed = self._hand_off_sessions(tenant_id, eng, target)
        if spans is not None:
            spans.annotate(trace, "migrate_cutover",
                           time.perf_counter() - ct0,
                           attrs={"location": target.url,
                                  "sessionsMoved": moved,
                                  "sessionsClosed": closed})
        target.activate(mid)
        detached = self.registry.detach(tenant_id)
        if detached is not None:
            detached.close()
        jr.append("complete")
        jr.close()
        self._drop_bundle(mid)
        self.completed += 1
        if spans is not None:
            spans.end_trace(
                trace, duration_s=pclock.mono() - t0, tenant=tenant_id,
                name="migration",
                attrs={"outcome": "completed", "target": target.url,
                       "sessionsMoved": moved, "sessionsClosed": closed},
                force=True,
            )
        return {
            "mid": mid,
            "tenant": tenant_id,
            "target": target.url,
            "outcome": "completed",
            "sessionsMoved": moved,
            "sessionsClosed": closed,
        }

    def _abort(self, jr, mid, tenant_id, ctx, exc, t0) -> None:
        """Pre-CUTOVER failure: the source keeps the tenant. Durable
        ABORT record, bundle dropped, context unpinned — the engine
        serves on exactly as if the migration never started."""
        try:
            jr.append("abort", reason=repr(exc)[:512])
        except OSError:  # pragma: no cover - abort is best-effort
            pass
        jr.close()
        self._drop_bundle(mid)
        ctx.unpin()
        self.aborted += 1
        log.warning("migration %s of %r aborted: %r", mid, tenant_id, exc)
        spans = self._spans()
        if spans is not None:
            spans.end_trace(
                f"migrate:{mid}", duration_s=pclock.mono() - t0,
                tenant=tenant_id, name="migration",
                attrs={"outcome": "aborted", "reason": repr(exc)[:128]},
                force=True,
            )

    def _export_bundle(self, mid, tenant_id, eng) -> tuple[dict, str]:
        """Build + atomically persist the migration bundle. Caller holds
        the quiesce gate: no request is in flight, so the WAL fold, the
        frequency snapshot and the session carries are one consistent
        cut of the tenant's state."""
        from log_parser_tpu.patterns.bank import CONTEXT_REGEXES
        from log_parser_tpu.patterns.libcache import library_key

        faults.fire("migrate_export")  # conlint: contained-by-caller (migrate() aborts pre-cutover)
        journal = getattr(eng, "journal", None)
        epoch = 0
        if journal is not None:
            # fold the WAL into a sealed snapshot: the bundle's ages and
            # the on-disk state dir now agree, so either side of a crash
            # recovers the same frequency history
            journal.snapshot_now()
            journal.flush()
            epoch = journal.epoch
        with eng.state_lock:
            ages = eng.frequency.snapshot()
        pending = []
        miner = getattr(eng, "miner", None)
        if miner is not None:
            with miner.lock:
                pending = [dict(e) for e in miner._pending.values()]
        carries = []
        mgr = getattr(eng, "stream_manager", None)
        if mgr is not None:
            with mgr._lock:
                sessions = list(mgr._sessions.values())
            carries = [s.export_carry() for s in sessions]
        bundle = {
            "version": BUNDLE_VERSION,
            "mid": mid,
            "tenant": tenant_id,
            "libraryKey": library_key(eng.bank.pattern_sets, CONTEXT_REGEXES),
            "frequency": {"ages": ages, "epoch": epoch},
            "pending": pending,
            "sessions": carries,
        }
        raw = canonical_bundle_bytes(bundle)
        try:
            pressure.disk_write_guard("bundle_write")
            _atomic_write(self._bundle_path(mid), raw)
        except OSError as exc:
            # contained by migrate(): the protocol seals ABORT and the
            # tenant stays owned here — a full disk refuses the move, it
            # never strands the tenant half-exported
            pressure.note_write_error(exc, "bundle_write")
            raise
        return bundle, hashlib.sha256(raw).hexdigest()

    def _hand_off_sessions(self, tenant_id, eng, target) -> tuple[int, int]:
        """Post-CUTOVER session disposition: a live session either MOVES
        (LocalTarget adopts the object and re-bases it onto the new
        engine) or is closed with an explicit ``error`` frame naming the
        new owner — it never pins the old process open."""
        mgr = getattr(eng, "stream_manager", None)
        if mgr is None:
            return 0, 0
        with mgr._lock:
            sessions = list(mgr._sessions.values())
        moved = closed = 0
        for sess in sessions:
            if target.can_adopt_sessions and target.adopt_session(
                tenant_id, sess
            ):
                moved += 1
            else:
                sess.kill(
                    "migrated",
                    message=(
                        f"tenant {tenant_id!r} migrated to {target.url}; "
                        "re-resolve and reconnect there"
                    ),
                )
                closed += 1
        self.sessions_moved += moved
        self.sessions_closed += closed
        return moved, closed

    # ------------------------------------------------------------- target

    def stage_import(self, bundle: dict, sha: str) -> dict:
        """Target half, step one: verify the bundle hash, warm-build the
        tenant bank and verify its content hash matches the source's,
        persist the bundle, ack. NOTHING is applied yet — a staged
        import that never activates is discarded on boot."""
        if not isinstance(bundle, dict):
            raise MigrationError("bundle must be a JSON object", 400)
        mid = str(bundle.get("mid") or "")
        tenant_id = str(bundle.get("tenant") or "")
        if not mid or not tenant_id:
            raise MigrationError("bundle missing mid/tenant", 400)
        dst_path = self._dst_path(mid)
        if os.path.exists(dst_path):
            kinds = {r.get("k") for r in MigrationJournal.replay(dst_path)}
            if "applied" in kinds:
                # a re-sent handoff: the source crashed after our APPLIED
                # record and is resuming from its journal. The import is
                # already live — possibly with traffic served since — so
                # ack idempotently and NEVER re-apply the stale bundle
                return {"mid": mid, "tenant": tenant_id, "sha": sha,
                        "alreadyApplied": True}
            if "discard" in kinds:
                # a previous attempt at this mid died pre-activation and
                # was sealed on boot: this re-stage is a fresh attempt,
                # not a continuation of a dead journal
                os.unlink(dst_path)
        if self.registry.fence_for() is not None:
            # a fenced process (demoted replica) is stale by definition:
            # importing a tenant onto it would hand ownership to a node
            # that 307s every request. Refuse pre-cutover — the source
            # keeps the tenant and aborts cleanly.
            raise MigrationError(
                "target is fenced (demoted replica): refusing import", 409
            )
        if self.on_primacy_check is not None:
            try:
                primary = bool(self.on_primacy_check())
            except Exception:  # pragma: no cover - probe must not 500 stage
                log.exception("primacy probe failed; accepting import")
                primary = True
            if not primary:
                # stale (peer promoted — the probe demoted us), or the
                # peer is unreachable so primacy is unconfirmable: either
                # way refuse before the source cuts over; the tenant
                # stays at the (healthy, servable) source
                raise MigrationError(
                    "target cannot confirm pair primacy:"
                    " refusing import", 409
                )
        jr = MigrationJournal(self._dst_path(mid))
        jr.append("stage", mid=mid, tenant=tenant_id, sha=sha)
        self._crash("stage")
        t0 = time.perf_counter()
        try:
            faults.fire("migrate_import")  # conlint: contained-by-caller (the source aborts pre-cutover on a failed stage)
            if bundle.get("version") != BUNDLE_VERSION:
                raise MigrationError(
                    f"unsupported bundle version {bundle.get('version')!r}",
                    400,
                )
            raw = canonical_bundle_bytes(bundle)
            have = hashlib.sha256(raw).hexdigest()
            if have != sha:
                raise MigrationError(
                    f"bundle hash mismatch: want {sha[:12]}…, got {have[:12]}…"
                )
            self._verify_bank(tenant_id, bundle.get("libraryKey"))
            try:
                pressure.disk_write_guard("bundle_write")
                _atomic_write(self._bundle_path(mid), raw)
            except OSError as exc:
                pressure.note_write_error(exc, "bundle_write")
                raise
            jr.append("staged", sha=sha)
            self._crash("staged")
        except MigrationCrash:
            raise
        except MigrationError as exc:
            jr.append("discard", reason=exc.reason[:512])
            jr.close()
            raise
        except BaseException as exc:
            jr.append("discard", reason=repr(exc)[:512])
            jr.close()
            raise MigrationError(f"stage failed: {exc!r}") from exc
        with self._lock:
            self._staged[mid] = bundle
            self._dst_journals[mid] = jr
        self.staged += 1
        spans = self._spans()
        if spans is not None:
            spans.end_trace(
                f"migrate:{mid}:dst", duration_s=time.perf_counter() - t0,
                tenant=tenant_id, name="migrate_import",
                attrs={"phase": "staged", "sha": sha[:12]}, force=True,
            )
        return {"mid": mid, "tenant": tenant_id, "sha": sha}

    def _verify_bank(self, tenant_id: str, want_key) -> None:
        """Rebuild the tenant bank warm (patterns/libcache) and check it
        hashes to the same library the source served — a config drift
        between the two processes would silently change scores, so it
        fails the stage instead."""
        from log_parser_tpu.patterns.bank import CONTEXT_REGEXES
        from log_parser_tpu.patterns.libcache import library_key

        # ignore_forward: on a round-trip the target may still hold its
        # own stale outbound forward for this tenant; verification is an
        # internal resolution, not traffic routing
        was_resident = self.registry.context_if_resident(tenant_id) is not None
        ctx = self.registry.resolve(tenant_id, ignore_forward=True)
        try:
            have_key = library_key(
                ctx.engine.bank.pattern_sets, CONTEXT_REGEXES
            )
            if want_key and have_key and want_key != have_key:
                raise MigrationError(
                    f"bank content hash mismatch for {tenant_id!r}: the "
                    "target's pattern config differs from the source's"
                )
        finally:
            ctx.unpin()
        if not was_resident:
            # the verify build must not leave the tenant resident before
            # ACTIVATE: ownership hasn't moved yet, and a source crash
            # here would otherwise strand a warm, EMPTY engine on the
            # target accepting whatever traffic reaches it directly
            detached = self.registry.detach(tenant_id)
            if detached is not None:
                detached.close()

    def activate(self, mid: str) -> dict:
        """Target half, step two (runs only after the source's CUTOVER
        is durable): write ACTIVATE, apply the bundle — frequency
        restore through the journaled barrier, parked candidates,
        session carries — then APPLIED. Idempotent per journal: a crash
        between ACTIVATE and APPLIED re-applies on boot."""
        with self._lock:
            bundle = self._staged.pop(mid, None)
            jr = self._dst_journals.pop(mid, None)
        if bundle is None:
            path = self._dst_path(mid)
            if os.path.exists(path):
                records = MigrationJournal.replay(path)
                if any(r.get("k") == "applied" for r in records):
                    # idempotent ack for a resumed handoff (see
                    # stage_import): the import already went live here
                    return {"mid": mid,
                            "tenant": records[0].get("tenant"),
                            "alreadyApplied": True}
            raise MigrationError(f"no staged import {mid!r}", 404)
        if jr is None:  # pragma: no cover - staged and journal travel together
            jr = MigrationJournal(self._dst_path(mid))
        jr.append("activate")
        self._crash("activate")
        self._apply_bundle(bundle)
        jr.append("applied")
        jr.close()
        self._drop_bundle(mid)
        self.activated += 1
        spans = self._spans()
        if spans is not None:
            spans.end_trace(
                f"migrate:{mid}:dst", duration_s=0.0,
                tenant=str(bundle.get("tenant")), name="migrate_import",
                attrs={"phase": "activated"}, force=True,
            )
        return {"mid": mid, "tenant": bundle.get("tenant"),
                "outcome": "activated"}

    def _apply_bundle(self, bundle: dict) -> None:
        tenant_id = str(bundle.get("tenant"))
        # a round-trip (A -> B -> A) lands here with A still holding its
        # own stale forward from the outbound leg; becoming the owner
        # supersedes it — clear before resolve, which would otherwise
        # answer 307 for a tenant this process now owns. The adopt hook
        # durably voids any standing replication release the same way.
        self.registry.clear_forward(tenant_id)
        self._notify_adopt(tenant_id)
        ctx = self.registry.resolve(tenant_id)
        try:
            eng = ctx.engine
            ages = (bundle.get("frequency") or {}).get("ages") or {}
            with eng.state_lock:
                # DurableFrequencyTracker.restore appends a journal
                # barrier: the migrated history is durable in THIS
                # tenant's WAL the moment it lands
                eng.frequency.restore(
                    {str(pid): [float(a) for a in ages_list]
                     for pid, ages_list in ages.items()}
                )
            journal = getattr(eng, "journal", None)
            if journal is not None:
                journal.flush()
            miner = getattr(eng, "miner", None)
            if miner is not None and bundle.get("pending"):
                miner.adopt_pending(bundle["pending"])
            carries = bundle.get("sessions") or ()
            if carries:
                from log_parser_tpu.runtime.stream import shared_manager

                mgr = shared_manager(eng)
                for carry in carries:
                    sid = str(carry.get("sessionId") or "")
                    with mgr._lock:
                        live = sid in mgr._sessions
                    if live:
                        # the live object already moved over (LocalTarget
                        # adoption keeps its id); the carry is that
                        # session's crash-recovery shadow — restoring it
                        # too would double the session and its admission
                        # slot
                        continue
                    try:
                        mgr.adopt_carry(carry)
                    except Exception:
                        log.exception(
                            "session carry %r failed to restore; the "
                            "client must reconnect",
                            carry.get("sessionId"),
                        )
        finally:
            ctx.unpin()

    # --------------------------------------------------------- compaction

    def compact(self) -> int:
        """Truncate every terminal migration journal past its decision
        records (see :func:`compact_journal`) and sweep stale ``.compact``
        tmps from an interrupted pass. Run at boot (after recover) and
        on entry into soft disk pressure; returns how many journals
        shrank."""
        try:
            names = sorted(os.listdir(self.dir))
        except OSError:
            return 0
        n = 0
        for name in names:
            path = os.path.join(self.dir, name)
            if name.endswith(".compact"):
                try:
                    os.remove(path)
                except OSError:
                    pass
                continue
            if not name.endswith((".src.wal", ".dst.wal")):
                continue
            try:
                if compact_journal(path):
                    n += 1
            except OSError:
                log.exception("compacting migration journal %s failed", path)
        if n:
            with self._lock:
                self.compacted += n
            log.info("compacted %d terminal migration journal(s)", n)
        return n

    # ----------------------------------------------------------- recovery

    def recover(self, targets: dict | None = None) -> dict:
        """Boot-time convergence: walk every migration journal in the
        state dir and drive it to a terminal, single-owner state.

        - source journal without CUTOVER → ABORT (we still own the
          tenant; the half-written bundle is dropped);
        - source journal with CUTOVER but no COMPLETE → re-install the
          forward; with a reachable target (``targets`` maps target URL
          → target object) re-stage + activate from the on-disk bundle
          and COMPLETE, else leave it pending-but-forwarded;
        - source journal with COMPLETE → re-install the forward
          (forwards live in the journal, nowhere else);
        - target journal without ACTIVATE → DISCARD the staged bundle
          (the source recovered as owner);
        - target journal with ACTIVATE but no APPLIED → re-apply the
          bundle (restore is a full-state barrier, so replay-after-
          partial-apply converges), then APPLIED.

        Forwards are NOT installed per-journal: a tenant that round-
        tripped (out via an old src journal, back via a newer dst
        journal) has both a CUTOVER and an APPLIED on disk, and the
        journals never expire. Each journal instead votes an ownership
        *verdict* stamped with its pre-recovery mtime, and only the
        latest verdict per tenant is applied — an APPLIED that post-
        dates a CUTOVER clears the stale forward instead of losing to
        journal replay order. (Mid order can't arbitrate: sequence
        numbers are per-node.) This also makes recover() idempotent
        under a double boot: re-running it converges to the same
        forwards and appends nothing new to an already-sealed journal.
        """
        summary = {"forwards": [], "resumed": [], "discarded": [],
                   "pending": [], "owned": []}
        try:
            names = sorted(os.listdir(self.dir))
        except OSError:
            return summary
        verdicts: dict[str, tuple[float, str, str, int]] = {}
        for name in names:
            path = os.path.join(self.dir, name)
            try:
                # the decisive record's age — read BEFORE recovery appends
                # its own seal (abort/discard/complete) and bumps it
                mtime = os.path.getmtime(path)
            except OSError:
                continue
            if name.endswith(".src.wal"):
                verdict = self._recover_source(path, targets or {}, summary)
            elif name.endswith(".dst.wal"):
                verdict = self._recover_target(path, summary)
            else:
                continue
            if verdict is None:
                continue
            tenant_id, kind, location, retry_after = verdict
            if os.environ.get("LOG_PARSER_TPU_SIM_BUG_FORWARD_RESURRECTION"):
                # regression lever for the simulator ONLY: reintroduce the
                # pre-fix behaviour — forwards installed per-journal in
                # replay order with no latest-verdict arbitration, so an
                # A→B→A round trip plus a reboot resurrects the stale
                # forward (the PR 17 fix-3 bug)
                if kind == "forward":
                    self.registry.set_forward(tenant_id, location, retry_after)
                    summary["forwards"].append(tenant_id)
                continue
            prev = verdicts.get(tenant_id)
            if prev is None or mtime >= prev[0]:
                verdicts[tenant_id] = (mtime, kind, location, retry_after)
        for tenant_id in sorted(verdicts):
            _mtime, kind, location, retry_after = verdicts[tenant_id]
            if kind == "forward":
                self.registry.set_forward(tenant_id, location, retry_after)
                summary["forwards"].append(tenant_id)
            else:
                # this node re-imported the tenant after forwarding it
                # out: ownership came back, the old forward is stale
                self.registry.clear_forward(tenant_id)
                summary["owned"].append(tenant_id)
            # NOTE: no release/adopt hooks here — boot-time verdicts are
            # replayed by the composition root AFTER the replicator
            # recovers (with ship deferred), so recover() never runs the
            # epoch handshake mid-replay
        return summary

    def _recover_source(
        self, path, targets, summary
    ) -> tuple[str, str, str, int] | None:
        """Converge one source journal; returns the ownership verdict
        ``(tenant, "forward", location, retry_after_s)`` when the
        journal proves the tenant moved out, else ``None``."""
        records = MigrationJournal.replay(path)
        if not records:
            return None
        kinds = [r.get("k") for r in records]
        meta = records[0]
        mid = str(meta.get("mid") or os.path.basename(path).split(".")[0])
        tenant_id = str(meta.get("tenant") or "")
        if "abort" in kinds:
            return None
        cutover = next((r for r in records if r.get("k") == "cutover"), None)
        if cutover is None:
            # crash anywhere before the commit point: the tenant never
            # left. Seal the journal with ABORT; the next resolve serves
            # from the (still-folded) local state.
            jr = MigrationJournal(path)
            jr.append("abort", reason="recovered: no cutover record")
            jr.close()
            self._drop_bundle(mid)
            self.recovered_discarded += 1
            summary["discarded"].append(mid)
            log.info(
                "migration %s recovered to ABORT (no cutover); tenant %r "
                "stays owned here", mid, tenant_id,
            )
            return None
        location = str(cutover.get("location") or "")
        retry_after = int(cutover.get("retryAfterS") or 5)
        verdict = (
            (tenant_id, "forward", location, retry_after)
            if tenant_id
            else None
        )
        if "complete" in kinds:
            return verdict
        # CUTOVER durable, COMPLETE missing: ownership moved but the
        # handoff didn't finish. Resume it if we can reach the target.
        target = targets.get(location)
        if target is None:
            summary["pending"].append(mid)
            log.warning(
                "migration %s is past cutover but incomplete and no target "
                "for %r was supplied; tenant %r stays forwarded",
                mid, location, tenant_id,
            )
            return verdict
        try:
            bundle = self._read_bundle(mid)
            sha = hashlib.sha256(canonical_bundle_bytes(bundle)).hexdigest()
            target.stage(bundle, sha)
            target.activate(mid)
        except (MigrationError, OSError, ValueError) as exc:
            summary["pending"].append(mid)
            log.error("migration %s resume failed: %s", mid, exc)
            return verdict
        detached = self.registry.detach(tenant_id)
        if detached is not None:
            detached.close()
        jr = MigrationJournal(path)
        jr.append("complete")
        jr.close()
        self._drop_bundle(mid)
        self.recovered_resumed += 1
        summary["resumed"].append(mid)
        return verdict

    def _recover_target(
        self, path, summary
    ) -> tuple[str, str, str, int] | None:
        """Converge one target journal; returns the ownership verdict
        ``(tenant, "owned", "", 0)`` when the journal proves the tenant
        was imported here, else ``None``."""
        records = MigrationJournal.replay(path)
        if not records:
            return None
        kinds = [r.get("k") for r in records]
        meta = records[0]
        mid = str(meta.get("mid") or os.path.basename(path).split(".")[0])
        tenant_id = str(meta.get("tenant") or "")
        if "discard" in kinds:
            return None
        if "applied" in kinds:
            return (tenant_id, "owned", "", 0) if tenant_id else None
        if "activate" not in kinds:
            # staged (acked or not) but never activated: the source may
            # have recovered as owner — this copy must die
            jr = MigrationJournal(path)
            jr.append("discard", reason="recovered: never activated")
            jr.close()
            self._drop_bundle(mid)
            self.recovered_discarded += 1
            summary["discarded"].append(mid)
            log.info("staged import %s discarded on boot (never activated)",
                     mid)
            return None
        # ACTIVATE durable, APPLIED missing: finish the apply. restore()
        # is a full-state barrier, so a partial first attempt converges.
        try:
            bundle = self._read_bundle(mid)
        except (MigrationError, OSError, ValueError) as exc:
            summary["pending"].append(mid)
            log.error("activated import %s lost its bundle: %s", mid, exc)
            return None
        self._apply_bundle(bundle)
        jr = MigrationJournal(path)
        jr.append("applied")
        jr.close()
        self._drop_bundle(mid)
        self.recovered_resumed += 1
        summary["resumed"].append(mid)
        return (tenant_id, "owned", "", 0) if tenant_id else None

    # -------------------------------------------------------------- stats

    def stats(self) -> dict:
        with self._lock:
            active = len(self._migrating)
            staged_now = len(self._staged)
        return {
            "started": self.started,
            "completed": self.completed,
            "aborted": self.aborted,
            "staged": self.staged,
            "activated": self.activated,
            "recoveredResumed": self.recovered_resumed,
            "recoveredDiscarded": self.recovered_discarded,
            "sessionsMoved": self.sessions_moved,
            "sessionsClosed": self.sessions_closed,
            "compacted": self.compacted,
            "active": active,
            "stagedNow": staged_now,
            "forwards": self.registry.forward_count(),
        }


# /metrics view over DrainSupervisor.stats()
DRAIN_METRIC_SAMPLES = (
    ("draining", "logparser_migration_draining", {}),
    ("tenantsClosed", "logparser_migration_total",
     {"outcome": "drain_closed"}),
    ("tenantsMigrated", "logparser_migration_total",
     {"outcome": "drain_migrated"}),
)


class DrainSupervisor:
    """Migrate-everything-out-then-stop, under a bounded deadline.

    Triggered by the ``/admin/drain`` endpoint, by SIGTERM (wired as
    ``install_drain_handlers``'s ``on_drained`` hook), or by the
    optional health watch (SLO burn / device breaker). One pass:

    1. flip the shared admission gate (readiness 503; ``/q/health``
       reports a DRAINING check) — new work is refused while in-flight
       migrations complete;
    2. for every resident non-default tenant, migrate to
       ``target`` under what remains of ``deadline_s``; with no target
       (or past the deadline, or on a failed migration) fall back to a
       bounded local close: open stream sessions get an explicit
       ``error`` frame — never an indefinite hang — and the tenant's
       WAL folds;
    3. finalize EVERY remaining engine: fold each tenant WAL, flush
       each batcher, flush the default journal, dump the OTLP span
       file. (Pre-PR-16 shutdown finalized only the default engine;
       tests/test_migrate.py pins the multi-tenant fix.)
    """

    def __init__(
        self,
        registry,
        migrator: Migrator | None = None,
        *,
        gate=None,
        target=None,
        deadline_s: float = 30.0,
        retry_after_s: int = 5,
        span_dump_path: str | None = None,
        clock=pclock.mono,
    ):
        self.registry = registry
        self.migrator = migrator
        self.gate = gate
        self.target = target
        self.deadline_s = float(deadline_s)
        self.retry_after_s = int(retry_after_s)
        self.span_dump_path = span_dump_path
        self.clock = clock
        self._lock = threading.Lock()
        self._draining = False
        self._watch_stop = threading.Event()
        self._watch_thread: threading.Thread | None = None
        # counters (GET /trace/last `migration` block)
        self.drains = 0
        self.tenants_migrated = 0
        self.tenants_closed = 0
        self.sessions_closed = 0
        obs = getattr(registry.default_engine, "obs", None)
        if obs is not None:
            obs.add_stats_collector("drain", self.stats,
                                    DRAIN_METRIC_SAMPLES)

    @property
    def draining(self) -> bool:
        with self._lock:
            return self._draining

    # --------------------------------------------------------------- drain

    def drain(self, reason: str = "admin") -> dict:
        """One full drain pass (idempotent: a second call while draining
        returns immediately). Never raises — SIGTERM must always reach
        shutdown."""
        with self._lock:
            if self._draining:
                return {"alreadyDraining": True}
            self._draining = True
            self.drains += 1
        t0 = self.clock()
        deadline = t0 + self.deadline_s
        if self.gate is not None and not self.gate.draining:
            self.gate.begin_drain()
        migrated: list[str] = []
        closed: list[str] = []
        for tid in self.registry.resident():
            if tid == DEFAULT_TENANT:
                continue
            remaining = deadline - self.clock()
            if (
                self.migrator is not None
                and self.target is not None
                and remaining > 0
            ):
                try:
                    self.migrator.migrate(
                        tid, self.target,
                        retry_after_s=self.retry_after_s,
                        timeout_s=max(1.0, remaining),
                    )
                    migrated.append(tid)
                    continue
                except Exception:
                    log.exception(
                        "drain: migrating %r failed; falling back to a "
                        "bounded local close", tid,
                    )
            self._close_tenant(tid)
            closed.append(tid)
        self.tenants_migrated += len(migrated)
        self.tenants_closed += len(closed)
        self.finalize_all()
        obs = getattr(self.registry.default_engine, "obs", None)
        if obs is not None:
            obs.spans.end_trace(
                f"drain:{self.drains}",
                duration_s=max(0.0, self.clock() - t0),
                name="drain",
                attrs={"reason": reason, "migrated": len(migrated),
                       "closed": len(closed),
                       "deadlineS": self.deadline_s},
                force=True,
            )
        return {"reason": reason, "migrated": migrated, "closed": closed,
                "elapsedS": round(max(0.0, self.clock() - t0), 3)}

    def _close_tenant(self, tid: str) -> None:
        """Bounded local drain of one tenant: no target to move to, so
        open sessions are error-framed (the client is told to re-resolve)
        and the WAL folds. This path also covers a stream-pinned tenant
        past the drain deadline — it must never hang SIGTERM."""
        ctx = self.registry.detach(tid)
        if ctx is None:
            return
        mgr = getattr(ctx.engine, "stream_manager", None)
        if mgr is not None:
            with mgr._lock:
                sessions = list(mgr._sessions.values())
            for sess in sessions:
                sess.kill(
                    "draining",
                    message="server draining; re-resolve and reconnect",
                )
                self.sessions_closed += 1
        try:
            ctx.close()
        except Exception:
            log.exception("drain: closing tenant %r failed", tid)

    def finalize_all(self) -> dict:
        """Multi-tenant shutdown finalization: fold the WAL and flush
        the batcher of EVERY still-resident tenant, flush the default
        engine's journal and batcher, and dump the OTLP span file — not
        just the default engine's state.

        Every writer here can hit a full disk, and none of them may
        mask the drain outcome: each is contained per-writer (logged
        once), the drain completes regardless, and the summary carries
        an accurate ``writerErrors``/``writersSkipped`` tally so the
        exit status can be nonzero-but-honest instead of an exception
        half-way through finalization."""
        folded: list[str] = []
        errors = 0
        skipped = 0

        def _fold(journal, who: str) -> None:
            nonlocal errors, skipped
            if journal is None:
                return
            if pressure.writes_paused():
                # hard pressure: the skip is the contract — the journal
                # is degraded and rearm() owns the recovery barrier
                skipped += 1
                return
            try:
                if not journal.snapshot_now():
                    errors += 1  # contained inside; the WAL keeps its tail
                journal.flush()
            except Exception:
                errors += 1
                log.exception("drain: journal fold for %s failed", who)

        for tid in self.registry.resident():
            if tid == DEFAULT_TENANT:
                continue
            ctx = self.registry.context_if_resident(tid)
            if ctx is None:
                continue
            eng = ctx.engine
            if getattr(eng, "batcher", None) is not None:
                try:
                    eng.batcher.flush_now()
                except Exception:
                    errors += 1
                    log.exception("drain: batcher flush for %r failed", tid)
            _fold(getattr(eng, "journal", None), repr(tid))
            folded.append(tid)
        default_eng = self.registry.default_engine
        _fold(getattr(default_eng, "journal", None), "default engine")
        obs = getattr(default_eng, "obs", None)
        if obs is not None and self.span_dump_path:
            try:
                if not obs.spans.dump(self.span_dump_path):
                    skipped += 1
            except OSError as exc:
                errors += 1
                pressure.note_write_error(exc, "otlp_dump")
                log.exception("drain: span dump failed")
        return {
            "folded": folded,
            "spanDump": self.span_dump_path,
            "writerErrors": errors,
            "writersSkipped": skipped,
        }

    # --------------------------------------------------------- health watch

    def watch_health(self, check, poll_s: float = 5.0) -> threading.Thread:
        """Start the health-driven trigger: ``check()`` returns a reason
        string when the process should evacuate (SLO burn over
        threshold, breaker stuck open) or None while healthy. The first
        non-None verdict runs one drain pass and the watch exits."""

        def _loop():
            while not pclock.wait(self._watch_stop, poll_s):
                if self.draining:
                    return
                try:
                    reason = check()
                except Exception:
                    log.exception("drain health check failed")
                    continue
                if reason:
                    log.warning("health watch triggering drain: %s", reason)
                    self.drain(reason=f"health:{reason}")
                    return

        with self._lock:
            if self._watch_thread is not None:
                return self._watch_thread
            self._watch_thread = threading.Thread(
                target=_loop, name="drain-health-watch", daemon=True
            )
        self._watch_thread.start()
        return self._watch_thread

    def stop_watch(self) -> None:
        self._watch_stop.set()
        t = self._watch_thread
        if t is not None:
            t.join(timeout=2.0)

    # --------------------------------------------------------------- stats

    def stats(self) -> dict:
        with self._lock:
            draining = self._draining
        return {
            "draining": int(draining),
            "deadlineS": self.deadline_s,
            "drains": self.drains,
            "tenantsMigrated": self.tenants_migrated,
            "tenantsClosed": self.tenants_closed,
            "sessionsClosed": self.sessions_closed,
            "target": getattr(self.target, "url", None),
        }
