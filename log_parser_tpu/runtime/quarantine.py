"""Poison-request quarantine and per-pattern shadow breakers — the
blast-radius-isolation layer between one pathological request and the
rest of the fleet.

The golden fallback (runtime/engine.py) answers *this* request when the
device step dies, but it does nothing about the NEXT arrival of the same
request: a poison pill replayed by a retrying client re-enters the device
step every time, re-trips the watchdog breaker (punishing innocent
traffic with host-path latency), and — under micro-batching — keeps
sinking whole flushes. "Lost in Translation?" (PAPERS.md, arxiv
2506.19539) shows translated regex semantics drift exactly in the corner
cases production traffic finds first; CelerLog (arxiv 2605.26005) shows
the fix is dynamic routing of hard inputs, not trust-at-build-time.

Three cooperating pieces:

- :class:`QuarantineTable` — request fingerprints (sha256 of the
  normalized log blob + its power-of-two shape bucket) accumulate a
  *strike* whenever their device step raises an organic (non-injected)
  device error. At ``--quarantine-strikes`` strikes the fingerprint is
  quarantined for ``--quarantine-ttl-s``: repeats are routed straight to
  the golden host path without ever touching the device step, and only
  when golden ALSO fails does the caller see a structured 429 +
  Retry-After (:class:`QuarantineRejected`). The table is LRU-capped so
  an attacker rotating payloads can only evict other suspects, never
  grow memory.
- batch bisection (runtime/batcher.py) — feeds this table: a faulted
  fused flush is split log₂-wise to isolate the poison row(s), the
  healthy majority is served on-device, and only the culprits strike.
- :class:`PatternBreakerBoard` — per-pattern circuit breakers driven by
  online shadow verification (runtime/engine.py ``ShadowVerifier``). A
  device-vs-golden score divergence on pattern P opens P's breaker: P's
  columns are served from the exact host regex (a cube override —
  surgical containment) while every other pattern stays on-device.
  After ``cooldown_s`` the breaker goes half-open: overrides lift and
  the next shadow comparison either closes it or re-opens it.
"""

from __future__ import annotations

import hashlib
import threading
import time
from collections import OrderedDict
from typing import Callable

from log_parser_tpu import _clock as pclock
from log_parser_tpu.native.ingest import normalize_blob

DEFAULT_STRIKES = 2
DEFAULT_TTL_S = 300.0
DEFAULT_CAPACITY = 4096
DEFAULT_BREAKER_COOLDOWN_S = 30.0


def fingerprint(logs: str) -> str:
    """sha256 over the normalized log blob plus its shape bucket.

    Normalization IS the ingest path's (``normalize_blob`` —
    native/ingest.py, shared with the line cache), so two byte-wise
    different payloads that encode to the same device batch share a
    fingerprint. The power-of-two line bucket keeps a prefix of a poison
    corpus (same bytes, different padded shape → different compiled
    program) from aliasing the full one."""
    blob = normalize_blob(logs)
    n_lines = blob.count(b"\n") + 1
    bucket = 1
    while bucket < n_lines:
        bucket <<= 1
    h = hashlib.sha256(blob)
    h.update(b"|rows=%d" % bucket)
    return h.hexdigest()


class QuarantineRejected(RuntimeError):
    """A quarantined request that the golden host path could not serve
    either — the caller gets a structured 429 with Retry-After instead of
    another crack at the device step."""

    def __init__(self, fp: str, retry_after_s: float):
        super().__init__(
            f"request fingerprint {fp[:12]}… is quarantined and the host "
            "path failed; retry after TTL expiry"
        )
        self.fingerprint = fp
        self.retry_after_s = max(1.0, float(retry_after_s))
        self.status = 429
        self.reason = "quarantined"


class _Entry:
    __slots__ = ("strikes", "quarantined_at")

    def __init__(self):
        self.strikes = 0
        self.quarantined_at: float | None = None


class QuarantineTable:
    """Strike ledger + active-quarantine set, LRU-capped.

    Thread-safe; the clock is injectable so TTL expiry is testable
    without sleeping. All counters are lifetime totals surfaced on
    ``GET /trace/last`` (the ``quarantine`` block)."""

    def __init__(
        self,
        strikes: int = DEFAULT_STRIKES,
        ttl_s: float = DEFAULT_TTL_S,
        capacity: int = DEFAULT_CAPACITY,
        clock: Callable[[], float] = pclock.mono,
    ):
        self.threshold = max(1, int(strikes))
        self.ttl_s = float(ttl_s)
        self.capacity = max(1, int(capacity))
        self.clock = clock
        self._lock = threading.Lock()
        self._table: OrderedDict[str, _Entry] = OrderedDict()
        # lifetime counters (guarded by _lock)
        self.strike_count = 0
        self.quarantined_count = 0
        self.served_golden = 0
        self.rejected_count = 0
        self.readmitted_count = 0
        self.evicted_count = 0

    def strike(self, fp: str) -> bool:
        """Record one strike against ``fp``; True when this strike crosses
        the threshold and the fingerprint enters quarantine."""
        with self._lock:
            entry = self._table.get(fp)
            if entry is None:
                entry = _Entry()
                self._table[fp] = entry
                while len(self._table) > self.capacity:
                    self._table.popitem(last=False)
                    self.evicted_count += 1
            else:
                self._table.move_to_end(fp)
            self.strike_count += 1
            entry.strikes += 1
            if entry.quarantined_at is None and entry.strikes >= self.threshold:
                entry.quarantined_at = self.clock()
                self.quarantined_count += 1
                return True
            return False

    def check(self, fp: str) -> bool:
        """True while ``fp`` is actively quarantined. A TTL-expired entry
        is dropped entirely (strikes included) and the fingerprint is
        re-admitted to the device path with a clean slate."""
        with self._lock:
            entry = self._table.get(fp)
            if entry is None or entry.quarantined_at is None:
                return False
            if self.ttl_s > 0 and self.clock() - entry.quarantined_at >= self.ttl_s:
                del self._table[fp]
                self.readmitted_count += 1
                return False
            self._table.move_to_end(fp)
            return True

    def retry_after(self, fp: str) -> float:
        """Seconds until ``fp``'s quarantine expires (the Retry-After a
        429 carries when even the host path cannot serve it)."""
        with self._lock:
            entry = self._table.get(fp)
            if entry is None or entry.quarantined_at is None:
                return 1.0
            if self.ttl_s <= 0:
                return 1.0
            return max(1.0, self.ttl_s - (self.clock() - entry.quarantined_at))

    def note_served(self) -> None:
        with self._lock:
            self.served_golden += 1

    def note_rejected(self) -> None:
        with self._lock:
            self.rejected_count += 1

    def stats(self) -> dict:
        with self._lock:
            active = sum(
                1 for e in self._table.values() if e.quarantined_at is not None
            )
            return {
                "threshold": self.threshold,
                "ttlS": self.ttl_s,
                "capacity": self.capacity,
                "tracked": len(self._table),
                "active": active,
                "strikes": self.strike_count,
                "quarantined": self.quarantined_count,
                "servedGolden": self.served_golden,
                "rejected": self.rejected_count,
                "readmitted": self.readmitted_count,
                "evicted": self.evicted_count,
            }


class PatternBreakerBoard:
    """Per-pattern circuit breakers: open on shadow divergence, half-open
    after a cool-down, closed by a clean shadow comparison.

    While a pattern's breaker is OPEN, the engine serves that pattern's
    columns from the exact host regex (``AnalysisEngine._overrides``) —
    the rest of the bank stays on-device, so one mistranslated pattern
    never degrades the whole engine. HALF-OPEN lifts the override and
    forces shadow sampling; the next comparison on a request decides:
    divergence on the pattern re-opens (cool-down re-arms), a clean run
    closes it."""

    def __init__(
        self,
        cooldown_s: float = DEFAULT_BREAKER_COOLDOWN_S,
        clock: Callable[[], float] = pclock.mono,
    ):
        self.cooldown_s = max(0.0, float(cooldown_s))
        self.clock = clock
        self._lock = threading.Lock()
        self._open: dict[str, float] = {}  # pattern id -> opened_at
        self._half_open: set[str] = set()
        self.trip_count = 0
        self.reopen_count = 0
        self.close_count = 0

    def trip(self, pattern_id: str) -> bool:
        """Open (or re-open, from half-open) ``pattern_id``'s breaker.
        True when this call changed the state."""
        with self._lock:
            was_half = pattern_id in self._half_open
            self._half_open.discard(pattern_id)
            already_open = pattern_id in self._open
            self._open[pattern_id] = self.clock()
            if was_half:
                self.reopen_count += 1
                return True
            if not already_open:
                self.trip_count += 1
                return True
            return False

    def overridden_patterns(self) -> set[str]:
        """Pattern ids whose columns must be served from the host regex
        right now. Cool-down expiry transitions open → half-open here
        (the next device batch serves the pattern natively again, under
        forced shadow observation)."""
        with self._lock:
            now = self.clock()
            for pid in [
                p
                for p, opened in self._open.items()
                if self.cooldown_s > 0 and now - opened >= self.cooldown_s
            ]:
                del self._open[pid]
                self._half_open.add(pid)
            return set(self._open)

    def probe_pending(self) -> bool:
        """True while any breaker is half-open — the shadow sampler
        forces a comparison so the probe actually resolves."""
        with self._lock:
            return bool(self._half_open)

    def resolve(self, seen: set[str], diverged: set[str]) -> None:
        """Feed one shadow-comparison outcome to the half-open breakers:
        a half-open pattern SEEN in the comparison (it matched on this
        request) without diverging closes. Divergent patterns are
        re-opened via :meth:`trip` by the verifier; half-open patterns
        absent from the request stay half-open — a corpus that never
        exercises the pattern proves nothing."""
        with self._lock:
            for pid in list(self._half_open):
                if pid in seen and pid not in diverged:
                    self._half_open.discard(pid)
                    self.close_count += 1

    def any_active(self) -> bool:
        with self._lock:
            return bool(self._open or self._half_open)

    def stats(self) -> dict:
        with self._lock:
            return {
                "open": sorted(self._open),
                "halfOpen": sorted(self._half_open),
                "trips": self.trip_count,
                "reopens": self.reopen_count,
                "closes": self.close_count,
            }
