"""Warm-standby replication: continuous WAL shipping + fenced failover.

A primary ships each tenant's frequency WAL (runtime/journal.py) to a
configured standby as it is fsynced; the standby applies the frames
through the SAME replay semantic boot recovery uses
(:func:`~log_parser_tpu.runtime.journal.apply_record`) and keeps a warm,
journaled bank per tenant — promotion is O(activate), not O(rebuild).

Protocol shape (per tenant):

- :class:`ReplicaSender` tails the journal with
  :meth:`FrequencyJournal.wal_feed`: a snapshot **barrier** first (live
  tracker state read under the engine state lock, paired with the WAL
  (epoch, size) sampled under the journal mutex, so the barrier and the
  resume offset are one consistent cut), then incremental CRC-framed
  records, each batch acked by byte offset. Reconnect uses exponential
  backoff + jitter and resumes from the last acked offset; when the
  primary has rotated (snapshot + truncate) past it, the sender falls
  back to a fresh barrier.
- The receiver (:meth:`Replicator.feed`, served as POST
  /admin/replica/feed and the framed-shim ``ReplicaFeed`` method)
  verifies every frame — length, CRC, JSON — and rejects a batch WHOLE
  on any anomaly, keeping its acked offset so the sender re-sends;
  a partial record is never applied. Verified batches apply to the
  tenant's ages and land in the standby engine via the journaled
  ``DurableFrequencyTracker.restore`` path, so a standby crash recovers
  from its own WAL.

Failover is fenced by a monotonically increasing **ownership epoch**
persisted in a CRC-framed protocol journal (``_replica/epoch.wal``,
reusing :class:`~log_parser_tpu.runtime.migrate.MigrationJournal`) on
BOTH sides. The :class:`FailoverSupervisor` on the standby probes the
primary's ``/q/health``; after ``--failover-after-s`` of consecutive
failures (or an explicit POST /admin/promote) it journals
PROMOTE(epoch+1), activates every replicated tenant, and lifts the
registry fence. A primary that comes back with a stale epoch sees the
higher epoch in the first feed response, journals DEMOTE, fences itself
(tenancy.set_fence → 307 for every tenant, default included), and
becomes the standby. Exactly-one-owner holds across a crash at every
protocol boundary: each transition is journaled-then-acted (the
``crash_after`` hook fires right after the fsync'd record, PR 16
style), and :meth:`Replicator.recover` replays the journal to converge.

Fault sites (LOG_PARSER_TPU_FAULTS): ``replica_send`` (a WAL batch ship
fails — contained: the sender counts the error and backs off, the
primary keeps serving), ``replica_apply`` (the standby's verify+apply
refuses the batch — contained: 503 to the sender, which re-sends; the
acked offset never moves), ``promote`` (the promotion aborts before the
PROMOTE record is journaled — contained: the standby stays fenced and
the supervisor retries on its next probe).
"""

from __future__ import annotations

import base64
import json
import logging
import os
import random
import threading
import time
import urllib.error
import urllib.request
import zlib
from typing import Callable

from log_parser_tpu import _clock as pclock
from log_parser_tpu.runtime import faults, pressure
from log_parser_tpu.runtime.journal import _FRAME, _MAX_PAYLOAD, apply_record
from log_parser_tpu.runtime.migrate import MigrationJournal, _frame_records
from log_parser_tpu.runtime.tenancy import DEFAULT_TENANT

log = logging.getLogger(__name__)

FAULT_SITES = {
    "replica_send": "WAL batch ship to the standby fails (sender backs off "
                    "and re-sends from the last acked offset)",
    "replica_apply": "standby verify+apply refuses the batch (503; acked "
                     "offset keeps its value, sender re-sends)",
    "promote": "promotion aborts before the PROMOTE record is journaled "
               "(standby stays fenced; the supervisor retries)",
}

REPLICA_DIR = "_replica"
EPOCH_JOURNAL = "epoch.wal"

# protocol journal record kinds, in the order a failover writes them —
# the crash-matrix axis in tests/test_replicate.py ("release" is written
# by either side when a tenant migrates off the replication pair;
# "adopt" voids a standing release when the tenant migrates back on)
PROTOCOL_RECORDS = ("epoch", "promote", "demote", "release", "adopt")

_MAX_BATCH_BYTES = 8 << 20
_BACKOFF_BASE_S = 0.25
_BACKOFF_CAP_S = 15.0


class ReplicationError(Exception):
    """A refused feed/promotion. ``status`` maps onto HTTP directly;
    ``extra`` carries the receiver's protocol position (ownership
    ``epoch``, per-tenant ``acked`` offset + ``walEpoch``, owner
    ``location``) so the sender can re-sync or demote from the error
    alone."""

    def __init__(self, reason: str, status: int = 409, **extra):
        super().__init__(reason)
        self.reason = reason
        self.status = int(status)
        self.extra = dict(extra)

    def to_json(self) -> dict:
        doc = {"error": self.reason}
        doc.update(self.extra)
        return doc


class ReplicaCrash(RuntimeError):
    """Injected kill -9 for the crash matrix: raised right AFTER the
    named protocol record is fsynced, before any in-memory state
    changes — tests rebuild fresh objects over the same state dir and
    recover()."""


def split_frames(data: bytes) -> tuple[list[dict], int]:
    """Parse whole verified frames off ``data``.

    Returns ``(payloads, consumed)`` where ``consumed`` is the byte
    length of the verified whole-frame prefix. The walk stops at the
    first anomaly — short header, over-long or truncated payload, CRC
    mismatch, non-JSON — exactly the boot-replay rule, so sender and
    receiver agree byte-for-byte on what a "whole frame" is.
    """
    out: list[dict] = []
    off = 0
    while off + _FRAME.size <= len(data):
        length, crc = _FRAME.unpack_from(data, off)
        start = off + _FRAME.size
        if length > _MAX_PAYLOAD or start + length > len(data):
            break
        payload = data[start:start + length]
        if zlib.crc32(payload) != crc:
            break
        try:
            out.append(json.loads(payload.decode("utf-8")))
        except ValueError:
            break
        off = start + length
    return out, off


# ------------------------------------------------------------------ targets


class LocalReplicaTarget:
    """In-process standby — tests and single-process drills. Feeds go
    straight into the peer :class:`Replicator`; rejections come back as
    (status, doc) exactly like the HTTP target reports them."""

    def __init__(self, replicator: "Replicator", url: str = "local://standby"):
        self.replicator = replicator
        self.url = url

    def feed(self, body: dict) -> tuple[int, dict]:
        try:
            return 200, self.replicator.feed(body)
        except ReplicationError as exc:
            return exc.status, exc.to_json()


class HttpReplicaTarget:
    """POST /admin/replica/feed on a real standby. Transport failures
    (unreachable, timeout) raise :class:`ReplicationError` with status
    0 so the sender backs off; protocol rejections return the standby's
    (status, body) for re-sync/demote handling."""

    def __init__(self, url: str, timeout_s: float = 30.0):
        self.url = url.rstrip("/")
        self.timeout_s = float(timeout_s)

    def feed(self, body: dict) -> tuple[int, dict]:
        data = json.dumps(body).encode("utf-8")
        req = urllib.request.Request(
            self.url + "/admin/replica/feed", data=data,
            headers={"Content-Type": "application/json"}, method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
                return resp.status, json.loads(resp.read().decode("utf-8"))
        except urllib.error.HTTPError as exc:
            try:
                doc = json.loads(exc.read().decode("utf-8"))
            except Exception:
                doc = {"error": f"HTTP {exc.code}"}
            return exc.code, doc if isinstance(doc, dict) else {"error": str(doc)}
        except (urllib.error.URLError, OSError, ValueError) as exc:
            raise ReplicationError(
                f"standby unreachable: {exc}", status=0
            ) from exc


# ------------------------------------------------------------------- sender


class ReplicaSender:
    """Ships ONE tenant's frequency WAL to the standby.

    ``pump()`` is synchronous and does one bounded round — the
    replicator's pump thread loops it; tests call it directly. State
    machine: un-seeded → ship a barrier (consistent live-snapshot +
    WAL-offset cut) → seeded, then incremental whole-frame batches from
    the acked offset. A WAL rotation (journal epoch change, or the
    acked offset past the truncated size) falls back to a fresh
    barrier; a rejection carrying the receiver's position re-syncs; a
    response carrying a HIGHER ownership epoch demotes this whole
    process.
    """

    def __init__(
        self,
        replicator: "Replicator",
        tenant_id: str,
        engine,
        target,
        *,
        rng: random.Random | None = None,
    ):
        self.replicator = replicator
        self.tenant_id = tenant_id
        self.engine = engine
        self.journal = engine.journal
        self.target = target
        self.rng = rng or random.Random(zlib.crc32(tenant_id.encode("utf-8")))
        self.seeded = False
        self.acked_offset = 0
        self.wal_epoch = -1
        # lag gauges (standby's view lags these by one in-flight batch)
        self.lag_records = 0
        self.lag_bytes = 0
        self.lag_seconds = 0.0
        # counters
        self.shipped_batches = 0
        self.shipped_records = 0
        self.reseeds = 0
        self.resyncs = 0
        self.send_errors = 0
        self.last_error = ""
        self._failures = 0
        self._next_try = 0.0

    # one replication round; returns the outcome for tests/logging
    def pump(self) -> str:
        rep = self.replicator
        if rep.role != "primary":
            return "standby"
        if pressure.durability_degraded():
            # local hard disk pressure: our own WAL is a degraded ring,
            # so there is nothing trustworthy to ship — pause until the
            # ladder re-arms (the next pump after recovery reseeds from
            # a fresh barrier if the WAL rotated underneath us)
            return "paused"
        now = rep.clock()
        if now < self._next_try:
            return "backoff"
        if self._failures > 0:
            # this attempt is a retry after a failure: it costs a retry
            # token. An exhausted budget sheds for a full backoff cap
            # instead of joining a synchronized retry storm.
            budget = pressure.retry_budget()
            dest = f"replica:{getattr(self.target, 'url', '?')}"
            if budget is not None and not budget.allow(dest):
                self.last_error = "retry budget exhausted"
                self._next_try = now + _BACKOFF_CAP_S
                return "shed"
        try:
            outcome = self._seed() if not self.seeded else self._ship()
        except faults.InjectedFault as exc:
            return self._note_error(f"injected: {exc}", now)
        except ReplicationError as exc:
            return self._note_error(str(exc), now)
        if outcome in ("seeded", "shipped", "idle", "resync"):
            self._failures = 0
            self._next_try = 0.0
            budget = pressure.retry_budget()
            if budget is not None:
                budget.note_request(
                    f"replica:{getattr(self.target, 'url', '?')}"
                )
        return outcome

    def _note_error(self, reason: str, now: float) -> str:
        self.send_errors += 1
        self.last_error = reason[:256]
        self._failures += 1
        backoff = min(_BACKOFF_CAP_S, _BACKOFF_BASE_S * (2.0 ** min(self._failures, 10)))
        self._next_try = now + backoff * (0.5 + self.rng.random() / 2.0)
        return "error"

    def backoff_s(self) -> float:
        return max(0.0, self._next_try - self.replicator.clock())

    def _seed(self) -> str:
        eng = self.engine
        # one consistent cut: appends happen under the engine state lock
        # (journal.py thread contract), so a snapshot read + WAL size
        # sampled while holding it bound exactly the same record prefix
        with eng.state_lock:
            ages = eng.frequency.snapshot()
            wall = self.replicator.wall()
            epoch, size, _ = self.journal.wal_feed(0, max_bytes=0)
        body = {
            "barrier": {"k": "b", "ages": ages, "w": wall},
            "walEpoch": epoch,
            "offset": size,
            "frames": "",
        }
        status, doc = self._send(body)
        if status == 200:
            self.seeded = True
            self.wal_epoch = epoch
            self.acked_offset = int(doc.get("acked", size))
            self.reseeds += 1
            self.lag_records = 0
            self.lag_bytes = 0
            self.lag_seconds = 0.0
            return "seeded"
        return self._handle_reject(status, doc)

    def _ship(self) -> str:
        epoch, size, data = self.journal.wal_feed(
            self.acked_offset, _MAX_BATCH_BYTES
        )
        if epoch != self.wal_epoch or self.acked_offset > size:
            # the primary rotated (snapshot + truncate) past the resume
            # point: incremental frames are gone, fall back to a barrier
            self.seeded = False
            return self._seed()
        payloads, consumed = split_frames(data)
        self._note_lag(size, payloads, consumed)
        if consumed == 0:
            if data:
                if os.environ.get("LOG_PARSER_TPU_SIM_BUG_MISALIGNED_WEDGE"):
                    # regression lever for the simulator ONLY: reintroduce
                    # the pre-fix behaviour (misaligned resume reports
                    # "idle" forever instead of reseeding) so sim sweeps
                    # can prove they rediscover the historical wedge
                    return "idle"
                # bytes are pending but no whole frame parses at our
                # resume point: the offset is misaligned (a corrupt ack
                # bookkeeping, never a torn append — the journal writes
                # whole frames under the same mutex wal_feed reads
                # under). An incremental resume can't recover; reseed.
                self.seeded = False
                return self._seed()
            return "idle"
        body = {
            "barrier": None,
            "walEpoch": epoch,
            "offset": self.acked_offset,
            "frames": base64.b64encode(data[:consumed]).decode("ascii"),
        }
        status, doc = self._send(body)
        if status == 200:
            self.acked_offset = int(doc.get("acked", self.acked_offset + consumed))
            self.shipped_batches += 1
            self.shipped_records += len(payloads)
            self.lag_bytes = max(0, size - self.acked_offset)
            if self.lag_bytes == 0:
                self.lag_records = 0
                self.lag_seconds = 0.0
            return "shipped"
        return self._handle_reject(status, doc)

    def _note_lag(self, size: int, payloads: list[dict], consumed: int) -> None:
        self.lag_bytes = max(0, size - self.acked_offset)
        self.lag_records = len(payloads)
        oldest = min(
            (float(p.get("w", 0.0)) for p in payloads if "w" in p),
            default=None,
        )
        self.lag_seconds = (
            max(0.0, self.replicator.wall() - oldest) if oldest is not None else 0.0
        )

    def _send(self, body: dict) -> tuple[int, dict]:
        faults.fire(  # conlint: contained-by-caller (pump counts the error and backs off)
            "replica_send", key=self.tenant_id
        )
        rep = self.replicator
        body["tenant"] = self.tenant_id
        body["epoch"] = rep.epoch
        body["wall"] = rep.wall()
        status, doc = self.target.feed(body)
        if not isinstance(doc, dict):
            doc = {}
        return status, doc

    def _handle_reject(self, status: int, doc: dict) -> str:
        rep = self.replicator
        try:
            peer_epoch = int(doc.get("epoch", -1))
        except (TypeError, ValueError):
            peer_epoch = -1
        if peer_epoch > rep.epoch:
            # the standby owns a HIGHER epoch: we are the stale side of a
            # split brain — step down before another write is accepted
            rep.demote(
                peer_epoch,
                str(doc.get("location") or getattr(self.target, "url", "")),
            )
            return "demoted"
        if status == 409 and "acked" in doc:
            # receiver told us its position: re-sync without a backoff
            try:
                peer_wal_epoch = int(doc.get("walEpoch", -1))
                peer_acked = int(doc["acked"])
            except (TypeError, ValueError):
                raise ReplicationError(f"malformed reject: {doc!r}")
            if peer_wal_epoch != self.wal_epoch or peer_wal_epoch < 0:
                self.seeded = False
            else:
                self.acked_offset = peer_acked
            self.resyncs += 1
            return "resync"
        raise ReplicationError(
            f"feed rejected ({status}): {doc.get('error', '?')}", status=status
        )

    def stats(self) -> dict:
        return {
            "acked": self.acked_offset,
            "walEpoch": self.wal_epoch,
            "seeded": self.seeded,
            "lagRecords": self.lag_records,
            "lagBytes": self.lag_bytes,
            "lagSeconds": round(self.lag_seconds, 6),
            "shipped": self.shipped_batches,
            "records": self.shipped_records,
            "reseeds": self.reseeds,
            "resyncs": self.resyncs,
            "errors": self.send_errors,
            "backoffS": round(self.backoff_s(), 3),
        }


class _TenantFeed:
    """Receiver-side position + warm state for one replicated tenant."""

    __slots__ = ("wal_epoch", "acked", "ages", "wall", "records", "barriers",
                 "rejects")

    def __init__(self):
        self.wal_epoch = -1
        self.acked = 0
        self.ages: dict[str, list[float]] = {}
        self.wall = 0.0
        self.records = 0
        self.barriers = 0
        self.rejects = 0


# --------------------------------------------------------------- replicator


class Replicator:
    """Both halves of the replication channel plus the fenced ownership
    state machine, for one process.

    Role ``primary``: senders pump; feeds are refused (409 + own epoch,
    which demotes a stale peer that tries to ship here). Role
    ``standby``: the registry is fenced (every client resolve 307s to
    the peer), feeds apply, the :class:`FailoverSupervisor` may be
    armed. ``promote``/``demote`` journal the transition BEFORE acting
    on it; ``recover()`` replays the journal so a crash at any boundary
    converges to exactly one owner.
    """

    def __init__(
        self,
        registry,
        *,
        state_root: str,
        node_url: str = "",
        peer_url: str | None = None,
        target=None,
        clock: Callable[[], float] = pclock.mono,
        wall: Callable[[], float] = pclock.wall,
        crash_after=None,
        pump_interval_s: float = 0.2,
    ):
        self.registry = registry
        self.node_url = node_url
        self.peer_url = peer_url or ""
        self.target = target
        self.clock = clock
        self.wall = wall
        self.crash_after = frozenset(crash_after or ())
        self.pump_interval_s = float(pump_interval_s)
        self.role = "standby" if peer_url else "primary"
        self.epoch = 0
        self.dir = os.path.join(str(state_root), REPLICA_DIR)
        self._journal = MigrationJournal(os.path.join(self.dir, EPOCH_JOURNAL))
        self._lock = threading.RLock()
        self._senders: dict[str, ReplicaSender] = {}
        self._feeds: dict[str, _TenantFeed] = {}
        self._known_tenants: set[str] = set()
        # tenants that migrated OFF this replication pair: location of the
        # new owner, journaled on both sides so a promotion installs a
        # forward instead of resurrecting the departed tenant's stale
        # state (the cross-plane migration x failover hazard)
        self._released: dict[str, str] = {}
        self._release_pending: dict[str, str] = {}  # primary: awaiting ship
        self._adopt_pending: set[str] = set()  # un-releases awaiting ship
        self.releases = 0
        self.supervisor: FailoverSupervisor | None = None
        # counters
        self.applied_batches = 0
        self.applied_records = 0
        self.rejected_batches = 0
        self.promotions = 0
        self.demotions = 0
        self.adoptions = 0
        self.epoch_compactions = 0
        self._stop_evt = threading.Event()
        self._thread: threading.Thread | None = None
        obs = getattr(registry.default_engine, "obs", None)
        if obs is not None:
            obs.registry.register_collector("replication", self._metric_samples)

    # ------------------------------------------------------------ plumbing

    def _crash(self, kind: str) -> None:
        if kind in self.crash_after:
            raise ReplicaCrash(f"injected crash after {kind}")

    def _spans(self):
        obs = getattr(self.registry.default_engine, "obs", None)
        return getattr(obs, "spans", None) if obs is not None else None

    def attach_sender(self, tenant_id: str, engine) -> ReplicaSender | None:
        """Start shipping one tenant's WAL (called from the serve layer's
        ``engine_setup`` hook as tenant engines come up). No-op without
        a target or a journal — a pure standby attaches no senders."""
        if self.target is None or getattr(engine, "journal", None) is None:
            return None
        with self._lock:
            # a tenant coming (back) up locally is owned here again: any
            # standing release is void — durably (ADOPT record), or a
            # reboot would replay the stale release forward — and the
            # resumed shipping stream clears it on the standby too
            # (feed-side un-release)
            self._adopt_locked(tenant_id)
            sender = self._senders.get(tenant_id)
            if sender is None:
                sender = ReplicaSender(self, tenant_id, engine, self.target)
                self._senders[tenant_id] = sender
                self._known_tenants.add(tenant_id)
            return sender

    def _adopt_locked(self, tenant_id: str) -> bool:
        """Void a standing release for ``tenant_id`` (caller holds
        ``_lock``). Journals an ADOPT record so the un-release survives a
        reboot and queues the notice for the standby (whose own journal
        still says released); returns True when a release stood."""
        self._release_pending.pop(tenant_id, None)
        if self._released.pop(tenant_id, None) is None:
            return False
        self._journal.append("adopt", epoch=self.epoch, tenant=tenant_id)
        self._crash("adopt")
        if self.target is not None:
            self._adopt_pending.add(tenant_id)
        return True

    def adopt_tenant(self, tenant_id: str, *, ship: bool = True) -> None:
        """The tenant is owned here again (migrated back, or a boot-time
        ownership verdict said so): durably void any standing release and
        drop its forward. Idempotent; wired to ``Migrator.on_adopt`` by
        the composition root. ``ship=False`` defers the standby notice to
        the next pump round (boot-time verdict replay must not run the
        epoch handshake mid-recover)."""
        if not tenant_id:
            return
        with self._lock:
            if not self._adopt_locked(tenant_id):
                return
        if tenant_id != DEFAULT_TENANT:
            self.registry.clear_forward(tenant_id)
        if ship:
            self._ship_releases()

    def release_tenant(self, tenant_id: str, location: str, *,
                       ship: bool = True) -> None:
        """The tenant migrated off this node: stop shipping its WAL and
        tell the standby durably (journal-then-ship) so a later promotion
        installs a forward to ``location`` instead of resurrecting the
        departed tenant's stale replica state. Idempotent; wired to
        ``Migrator.on_release`` by the composition root. ``ship=False``
        defers the standby notice to the next pump round (boot-time
        verdict replay must not run the epoch handshake mid-recover)."""
        if not tenant_id or tenant_id == DEFAULT_TENANT or not location:
            return
        with self._lock:
            if self._released.get(tenant_id) != location:
                self._journal.append(
                    "release", epoch=self.epoch, tenant=tenant_id,
                    location=location,
                )
                self._crash("release")
                self._released[tenant_id] = location
                self.releases += 1
            self._senders.pop(tenant_id, None)
            self._feeds.pop(tenant_id, None)
            self._known_tenants.discard(tenant_id)
            self._adopt_pending.discard(tenant_id)
            if self.target is not None:
                self._release_pending[tenant_id] = location
        if ship:
            # ship the notice NOW, not on the next pump round: the window
            # between cutover and the standby learning of it is exactly
            # the window a promotion resurrects the departed tenant.
            # Best-effort — an unreachable standby leaves it pending for
            # the pump to retry.
            self._ship_releases()

    def verify_primacy(self) -> bool:
        """Confirm with the standby that this process is still the pair
        primary before an *elective* ownership change (wired to
        ``Migrator.on_primacy_check`` so a stale primary refuses a
        migration import pre-cutover instead of discovering the
        promotion mid-adopt). Deliberately CP: in a two-node pair an
        unreachable standby is indistinguishable from a promoted one, so
        an unanswered probe refuses — the tenant stays at the (healthy,
        servable) source. Live traffic never pays this: only ownership
        changes require a confirmed epoch. When the probe surfaces a
        higher epoch the stale primary demotes on the spot."""
        if self.role != "primary":
            return False
        if self.target is None:
            return True  # unpaired node: nothing to be stale against
        body = {"tenant": DEFAULT_TENANT, "epoch": self.epoch,
                "probe": True, "wall": self.wall()}
        try:
            status, doc = self.target.feed(body)
        except ReplicationError:
            return False  # unreachable: primacy unconfirmable, refuse
        if status == 200:
            return True
        if not isinstance(doc, dict):
            return False
        try:
            peer_epoch = int(doc.get("epoch", -1))
        except (TypeError, ValueError):
            peer_epoch = -1
        if peer_epoch > self.epoch:
            self.demote(
                peer_epoch,
                str(doc.get("location") or getattr(self.target, "url", "")),
            )
        return False

    def _ship_releases(self) -> dict[str, str]:
        """Push pending release/adopt notices to the standby (retried on
        every pump round until acked; the receiver is idempotent)."""
        if self.target is None or self.role != "primary":
            return {}
        with self._lock:
            notices = [(tid, loc) for tid, loc
                       in sorted(self._release_pending.items())]
            notices += [(tid, None) for tid in sorted(self._adopt_pending)]
        out: dict[str, str] = {}
        for tid, loc in notices:
            body = {"tenant": tid, "epoch": self.epoch, "wall": self.wall()}
            if loc is None:
                body["adopt"] = True
            else:
                body["release"] = loc
            try:
                status, doc = self.target.feed(body)
            except ReplicationError as exc:
                out[tid] = f"error: {exc.reason[:80]}"
                continue
            if not isinstance(doc, dict):
                doc = {}
            if status == 200:
                with self._lock:
                    if loc is None:
                        self._adopt_pending.discard(tid)
                    else:
                        self._release_pending.pop(tid, None)
                out[tid] = "adopted" if loc is None else "released"
                continue
            try:
                peer_epoch = int(doc.get("epoch", -1))
            except (TypeError, ValueError):
                peer_epoch = -1
            if peer_epoch > self.epoch:
                # the standby promoted meanwhile: we are stale — step
                # down; the notice is already durable in our journal and
                # the new primary's own replay governs from here
                self.demote(
                    peer_epoch,
                    str(doc.get("location")
                        or getattr(self.target, "url", "")),
                )
                out[tid] = "demoted"
                break
            out[tid] = f"rejected ({status})"
        return out

    # ------------------------------------------------------------ receiver

    def feed(self, body: dict) -> dict:
        """Verify + apply one shipped batch. Raises
        :class:`ReplicationError` on any refusal; the error body carries
        the receiver's position so the sender can re-sync, or its
        (higher) epoch so a stale primary demotes itself."""
        if not isinstance(body, dict):
            raise ReplicationError("feed body must be a JSON object", status=400)
        tenant = body.get("tenant")
        if not isinstance(tenant, str) or not tenant:
            raise ReplicationError("feed missing tenant", status=400)
        try:
            feed_epoch = int(body.get("epoch", -1))
            wal_epoch = int(body.get("walEpoch", -1))
            offset = int(body.get("offset", -1))
        except (TypeError, ValueError):
            raise ReplicationError("malformed feed fields", status=400)
        try:
            faults.fire("replica_apply", key=tenant)
        except faults.InjectedFault as exc:
            raise ReplicationError(
                f"injected apply fault: {exc}", status=503, epoch=self.epoch
            ) from exc
        if pressure.durability_degraded():
            # hard disk pressure on the standby: applying would claim
            # durability this side cannot provide (the re-journal would
            # divert to a ring). Distinct 409 reason; the sender backs
            # off and re-sends once we recover — acked never moves.
            raise ReplicationError(
                "durability degraded: standby cannot journal feeds",
                status=409, epoch=self.epoch, reason="degraded",
                location=self.node_url,
            )
        with self._lock:
            if body.get("probe"):
                # primacy probe (no payload): answer with our epoch so a
                # stale primary demotes BEFORE acting on the belief that
                # it still owns the pair (e.g. accepting a migration)
                if feed_epoch < self.epoch:
                    raise ReplicationError(
                        "stale ownership epoch", status=409,
                        epoch=self.epoch, location=self.node_url,
                    )
                return {"epoch": self.epoch, "role": self.role}
            if feed_epoch < self.epoch:
                raise ReplicationError(
                    "stale ownership epoch", status=409,
                    epoch=self.epoch, location=self.node_url,
                )
            if self.role != "standby":
                raise ReplicationError(
                    "not a standby", status=409,
                    epoch=self.epoch, location=self.node_url,
                )
            if feed_epoch > self.epoch:
                # the fleet moved on while we were dark (e.g. this is a
                # re-provisioned standby): adopt the primary's epoch,
                # durably, BEFORE applying anything under it
                self._journal.append("epoch", epoch=feed_epoch)
                self._crash("epoch")
                self.epoch = feed_epoch
                self.adoptions += 1
            release = body.get("release")
            if release is not None:
                # the tenant migrated off the primary: journal the new
                # owner's location and drop the warm replica, so a later
                # promotion forwards instead of resurrecting stale state
                if not isinstance(release, str) or not release:
                    raise ReplicationError("malformed release", status=400)
                if self._released.get(tenant) != release:
                    self._journal.append(
                        "release", epoch=self.epoch, tenant=tenant,
                        location=release,
                    )
                    self._crash("release")
                    self._released[tenant] = release
                    self.releases += 1
                self._feeds.pop(tenant, None)
                self._known_tenants.discard(tenant)
                if tenant != DEFAULT_TENANT:
                    self.registry.set_forward(tenant, release)
                    detached = self.registry.detach(tenant)
                    if detached is not None:
                        detached.close()
                return {"released": tenant, "epoch": self.epoch}
            if body.get("adopt"):
                # the tenant migrated back onto the primary: durably void
                # the release and point its forward back at the pair
                # primary (the blanket standby stance), not the stale
                # migrated-to location
                self._adopt_locked(tenant)
                self._known_tenants.add(tenant)
                self._refence_tenant(tenant)
                return {"adopted": tenant, "epoch": self.epoch}
            st = self._feeds.setdefault(tenant, _TenantFeed())
            self._known_tenants.add(tenant)
            # a live feed for a previously-released tenant implies the
            # adopt: void the release durably, else a standby reboot
            # replays the stale forward
            if self._adopt_locked(tenant):
                self._refence_tenant(tenant)
            t0 = time.perf_counter()
            now = self.wall()
            barrier = body.get("barrier")
            if barrier is not None:
                if not isinstance(barrier, dict):
                    raise ReplicationError("malformed barrier", status=400)
                state: dict[str, list[float]] = {}
                apply_record(state, barrier, now)
                st.ages = state
                st.wal_epoch = wal_epoch
                st.acked = max(0, offset)
                st.wall = now
                st.barriers += 1
                applied = 0
            else:
                if wal_epoch != st.wal_epoch or offset != st.acked:
                    st.rejects += 1
                    self.rejected_batches += 1
                    raise ReplicationError(
                        "offset mismatch", status=409, epoch=self.epoch,
                        acked=st.acked, walEpoch=st.wal_epoch,
                        location=self.node_url,
                    )
                try:
                    data = base64.b64decode(body.get("frames") or "", validate=True)
                except (TypeError, ValueError):
                    raise ReplicationError("bad frame encoding", status=400)
                payloads, consumed = split_frames(data)
                if not payloads or consumed != len(data):
                    # torn or CRC-corrupt frame ANYWHERE in the batch:
                    # reject it whole, keep the acked offset — a partial
                    # record must never apply (mirror of the WAL
                    # torn-tail rule)
                    st.rejects += 1
                    self.rejected_batches += 1
                    raise ReplicationError(
                        "torn or corrupt frame in batch", status=409,
                        epoch=self.epoch, acked=st.acked,
                        walEpoch=st.wal_epoch, location=self.node_url,
                    )
                # age the warm state forward to 'now', then apply — an
                # all-or-nothing staged copy, same arithmetic a local
                # replay of the identical prefix performs
                drift = max(0.0, now - st.wall) if st.wall else 0.0
                # clamp stored ages too: a seed snapshot cut while the wall
                # clock was stepped back can carry a negative age, which
                # would otherwise become a future timestamp on promote
                staged = {
                    pid: [max(0.0, a) + drift for a in ages]
                    for pid, ages in st.ages.items()
                }
                for payload in payloads:
                    apply_record(staged, payload, now)
                st.ages = staged
                st.acked = offset + consumed
                st.wall = now
                st.records += len(payloads)
                applied = len(payloads)
            self._warm_apply(tenant, st)
            self.applied_batches += 1
            self.applied_records += applied
            if tenant != DEFAULT_TENANT:
                # standby answers client traffic for this tenant with the
                # primary's address even if the registry-wide fence is
                # lifted by an operator
                self.registry.set_forward(tenant, self.peer_url or self.node_url)
            spans = self._spans()
            if spans is not None:
                spans.end_trace(
                    f"replicate:{tenant}:{self.applied_batches}",
                    duration_s=time.perf_counter() - t0, tenant=tenant,
                    name="replicate",
                    attrs={"records": applied, "acked": st.acked,
                           "barrier": barrier is not None},
                    force=True,
                )
            return {"acked": st.acked, "walEpoch": st.wal_epoch,
                    "epoch": self.epoch}

    def _warm_apply(self, tenant: str, st: _TenantFeed) -> None:
        """Push the fed state into the standby's OWN tenant engine via
        the journaled restore path: the bank stays warm (promotion is
        O(activate)) and the state is durable in the standby's own WAL,
        so a standby crash re-warms from disk, not from the primary."""
        tid = None if tenant == DEFAULT_TENANT else tenant
        try:
            ctx = self.registry.resolve(tid, ignore_forward=True)
        except Exception as exc:
            raise ReplicationError(
                f"standby cannot host tenant {tenant!r}: {exc}", status=404,
                epoch=self.epoch,
            ) from exc
        try:
            eng = ctx.engine
            pressure.disk_write_guard("replica_rejournal")
            with eng.state_lock:
                eng.frequency.restore(st.ages)
        except OSError as exc:
            # the re-journal write path refused (ENOSPC): 503 so the
            # sender re-sends later. st.ages keeps the batch; the next
            # successful _warm_apply restores the FULL state (restore is
            # a barrier), so nothing is lost by the missed round.
            pressure.note_write_error(exc, "replica_rejournal")
            raise ReplicationError(
                f"standby re-journal failed: {exc}", status=503,
                epoch=self.epoch,
            ) from exc
        finally:
            ctx.unpin()

    # ------------------------------------------------------------ failover

    def promote(self, reason: str = "admin") -> dict:
        """Take ownership: journal PROMOTE(epoch+1), then activate every
        replicated tenant and lift the fence. Idempotent when already
        primary."""
        with self._lock:
            if self.role == "primary":
                return {"status": "primary", "epoch": self.epoch}
            try:
                faults.fire("promote", key=reason)
            except faults.InjectedFault as exc:
                raise ReplicationError(
                    f"injected promote fault: {exc}", status=503,
                    epoch=self.epoch,
                ) from exc
            t0 = self.clock()
            new_epoch = self.epoch + 1
            tenants = sorted(
                (self._known_tenants | set(self._feeds))
                - set(self._released)
            )
            self._journal.append(
                "promote", epoch=new_epoch, reason=reason, tenants=tenants
            )
            self._crash("promote")
            self.epoch = new_epoch
            self.role = "primary"
            self.promotions += 1
            self._activate(tenants)
            log.warning(
                "PROMOTED to primary at epoch %d (%s): %d tenant(s) live",
                new_epoch, reason, len(tenants),
            )
            spans = self._spans()
            if spans is not None:
                spans.end_trace(
                    f"promote:{new_epoch}",
                    duration_s=max(0.0, self.clock() - t0), name="promote",
                    attrs={"epoch": new_epoch, "reason": reason,
                           "tenants": len(tenants)},
                    force=True,
                )
            return {"status": "promoted", "epoch": new_epoch,
                    "reason": reason, "tenants": tenants}

    def demote(self, new_epoch: int, location: str) -> dict:
        """Step down: journal DEMOTE, fence the registry toward
        ``location``, install reverse forwards. Called when any feed
        response carries a higher ownership epoch (stale-primary
        split-brain heal), or by recover() replaying a DEMOTE record."""
        with self._lock:
            if self.role == "standby" and new_epoch <= self.epoch:
                return {"status": "standby", "epoch": self.epoch}
            t0 = self.clock()
            tenants = sorted(
                (self._known_tenants | set(self._feeds) | set(self._senders))
                - set(self._released)
            )
            self._journal.append(
                "demote", epoch=int(new_epoch), location=location,
                tenants=tenants,
            )
            self._crash("demote")
            self.epoch = max(self.epoch, int(new_epoch))
            self.role = "standby"
            self.demotions += 1
            if location:
                self.peer_url = location
            self._fence_all(tenants)
            log.warning(
                "DEMOTED to standby at epoch %d: owner is %s", self.epoch,
                location or "(unknown)",
            )
            spans = self._spans()
            if spans is not None:
                spans.end_trace(
                    f"demote:{self.epoch}",
                    duration_s=max(0.0, self.clock() - t0), name="demote",
                    attrs={"epoch": self.epoch, "location": location,
                           "tenants": len(tenants)},
                    force=True,
                )
            return {"status": "demoted", "epoch": self.epoch,
                    "location": location}

    def _activate(self, tenants: list[str]) -> None:
        """Make every replicated tenant live on this (now-primary)
        process: lift the fence, drop reverse forwards, resolve each
        tenant so its engine (and journaled warm bank) is up, and flush
        its journal so the promoted state is durable. Idempotent — the
        recover() walk re-runs it after a crash mid-activation."""
        reg = self.registry
        reg.clear_fence()
        for tid in tenants:
            if tid != DEFAULT_TENANT:
                reg.clear_forward(tid)
        for tid in tenants:
            try:
                ctx = reg.resolve(
                    None if tid == DEFAULT_TENANT else tid, ignore_forward=True
                )
            except Exception:
                log.exception("promote: tenant %r failed to activate", tid)
                continue
            try:
                journal = getattr(ctx.engine, "journal", None)
                if journal is not None:
                    journal.flush()
            finally:
                ctx.unpin()

    def _fence_all(self, tenants: list[str]) -> None:
        if self.peer_url:
            self.registry.set_fence(self.peer_url)
        for tid in tenants:
            if tid != DEFAULT_TENANT and self.peer_url:
                self.registry.set_forward(tid, self.peer_url)

    def _refence_tenant(self, tid: str) -> None:
        """Restore the blanket standby stance for one re-adopted tenant:
        forward to the pair primary (replacing a stale release forward)."""
        if tid == DEFAULT_TENANT:
            return
        if self.role == "standby" and self.peer_url:
            self.registry.set_forward(tid, self.peer_url)
        else:
            self.registry.clear_forward(tid)

    def arm_failover(
        self, primary_url: str, *, after_s: float, poll_s: float = 1.0
    ) -> "FailoverSupervisor":
        self.supervisor = FailoverSupervisor(
            self, primary_url, after_s=after_s, poll_s=poll_s, clock=self.clock
        )
        return self.supervisor

    # ------------------------------------------------------------ recovery

    def recover(self) -> dict:
        """Boot-time convergence: replay the protocol journal. The
        highest journaled epoch wins; the LAST promote/demote record
        decides the role, and its side effects are re-run idempotently
        (a crash between the record and the activation/fencing leaves
        the record as the single source of truth)."""
        records = MigrationJournal.replay(self._journal.path)
        role_rec: dict | None = None
        released: dict[str, str] = {}
        adopted: set[str] = set()
        for rec in records:
            try:
                e = int(rec.get("epoch", 0))
            except (TypeError, ValueError):
                continue
            if e > self.epoch:
                self.epoch = e
            if rec.get("k") in ("promote", "demote"):
                role_rec = rec
            if rec.get("k") == "release":
                tid = str(rec.get("tenant") or "")
                loc = str(rec.get("location") or "")
                if tid and loc:
                    released[tid] = loc
                    adopted.discard(tid)
            if rec.get("k") == "adopt":
                tid = str(rec.get("tenant") or "")
                if tid:
                    released.pop(tid, None)
                    adopted.add(tid)
            for tid, loc in (rec.get("releases") or {}).items():
                released[str(tid)] = str(loc)
                adopted.discard(str(tid))
            for tid in rec.get("tenants") or ():
                self._known_tenants.add(str(tid))
        # strip released tenants BEFORE re-running role side effects:
        # neither activation nor peer-fencing may touch a tenant that
        # migrated off the pair
        for tid in released:
            self._known_tenants.discard(tid)
            self._feeds.pop(tid, None)
            self._senders.pop(tid, None)
        self._released.update(released)
        if role_rec is not None:
            if role_rec.get("k") == "promote":
                self.role = "primary"
                self._activate(sorted(self._known_tenants))
            else:
                self.role = "standby"
                loc = str(role_rec.get("location") or "")
                if loc:
                    self.peer_url = loc
                self._fence_all(sorted(self._known_tenants))
        elif self.role == "standby":
            # never promoted/demoted: a boot-time standby fences until
            # it is promoted
            self._fence_all(sorted(self._known_tenants))
        # released tenants forward to their migrated-to owner — applied
        # AFTER the role side effects so the release forward wins over
        # the standby's blanket peer forwards; a recovered primary also
        # re-queues the notice (the receiver is idempotent)
        for tid, loc in sorted(released.items()):
            if tid != DEFAULT_TENANT:
                self.registry.set_forward(tid, loc)
            if self.target is not None:
                self._release_pending[tid] = loc
        if self.target is not None:
            # re-queue the adopt notices too: the standby's journal may
            # still say released (the notice never shipped before the
            # crash); over-notifying is idempotent on the receiver
            self._adopt_pending.update(adopted - set(released))
        summary = {
            "role": self.role,
            "epoch": self.epoch,
            "records": len(records),
            "tenants": sorted(self._known_tenants),
            "released": sorted(released),
        }
        log.info("replication recover: %s", summary)
        return summary

    def compact_epoch_journal(self) -> int:
        """Truncate ``_replica/epoch.wal`` past its terminal state.

        The protocol journal grows by one record per epoch adoption and
        per promote/demote, forever. recover() only needs three facts —
        the max epoch, the LAST promote/demote record (role + peer
        location), and the union of every record's tenant list — so the
        whole history compacts to ONE record carrying exactly those,
        and replaying it converges to the identical role/epoch/tenants.
        Runs at boot and on the soft-pressure trigger; returns 1 when
        the journal shrank. The open append handle is closed around an
        atomic rewrite (tmp + fsync + ``os.replace``) and reopened, all
        under ``_lock`` so no append races the swap; a crash before the
        replace leaves the original, a crash after leaves the valid
        compacted form.
        """
        with self._lock:
            path = self._journal.path
            records = MigrationJournal.replay(path)
            if len(records) <= 1:
                return 0
            max_epoch = self.epoch
            role_rec: dict | None = None
            tenants: set[str] = set()
            released: dict[str, str] = {}
            for rec in records:
                try:
                    e = int(rec.get("epoch", 0))
                except (TypeError, ValueError):
                    continue
                max_epoch = max(max_epoch, e)
                if rec.get("k") in ("promote", "demote"):
                    role_rec = rec
                if rec.get("k") == "release":
                    tid = str(rec.get("tenant") or "")
                    loc = str(rec.get("location") or "")
                    if tid and loc:
                        released[tid] = loc
                if rec.get("k") == "adopt":
                    released.pop(str(rec.get("tenant") or ""), None)
                for tid, loc in (rec.get("releases") or {}).items():
                    released[str(tid)] = str(loc)
                for tid in rec.get("tenants") or ():
                    tenants.add(str(tid))
            terminal: dict = {
                "k": role_rec.get("k") if role_rec else "epoch",
                "epoch": max_epoch,
                "tenants": sorted(tenants),
            }
            if released:
                terminal["releases"] = dict(sorted(released.items()))
            if role_rec is not None:
                if role_rec.get("location"):
                    terminal["location"] = role_rec["location"]
                if role_rec.get("reason"):
                    terminal["reason"] = role_rec["reason"]
            self._journal.close()
            tmp = path + ".compact"
            try:
                with open(tmp, "wb") as f:
                    f.write(_frame_records([terminal]))
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, path)
            except OSError:
                log.exception("epoch journal compaction failed")
                self._journal = MigrationJournal(path)
                return 0
            self._journal = MigrationJournal(path)
            self.epoch_compactions += 1
            log.info(
                "compacted epoch journal: %d record(s) -> 1 (epoch %d)",
                len(records), max_epoch,
            )
            return 1

    # ----------------------------------------------------------- lifecycle

    def start(self) -> None:
        """Begin the pump loop (primary side) and the failover watch
        (standby side, when armed)."""
        if self._thread is None and self.target is not None:
            self._thread = threading.Thread(
                target=self._pump_loop, name="replica-pump", daemon=True
            )
            self._thread.start()
        if self.supervisor is not None:
            self.supervisor.start()

    def _pump_loop(self) -> None:
        while not pclock.wait(self._stop_evt, self.pump_interval_s):
            try:
                self._ship_releases()
            except Exception:
                log.exception("release ship round failed")
            for sender in list(self._senders.values()):
                try:
                    sender.pump()
                except Exception:
                    log.exception(
                        "replica pump failed for %r", sender.tenant_id
                    )

    def pump_all(self) -> dict[str, str]:
        """One synchronous round over every sender (tests, drills) —
        pending release notices ship first, so the standby stops warming
        a tenant before its successor state ships a single frame."""
        out: dict[str, str] = {
            tid: f"release:{status}"
            for tid, status in self._ship_releases().items()
        }
        out.update(
            {tid: s.pump() for tid, s in list(self._senders.items())}
        )
        return out

    def stop(self) -> None:
        self._stop_evt.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
        if self.supervisor is not None:
            self.supervisor.stop()
        self._journal.close()

    # --------------------------------------------------------------- stats

    def stats(self) -> dict:
        with self._lock:
            senders = {tid: s.stats() for tid, s in self._senders.items()}
            feeds = {
                tid: {"acked": st.acked, "walEpoch": st.wal_epoch,
                      "records": st.records, "barriers": st.barriers,
                      "rejects": st.rejects}
                for tid, st in self._feeds.items()
            }
            doc = {
                "role": self.role,
                "epoch": self.epoch,
                "peer": self.peer_url,
                "tenants": sorted(self._known_tenants),
                "lagRecords": sum(s.lag_records for s in self._senders.values()),
                "lagBytes": sum(s.lag_bytes for s in self._senders.values()),
                "lagSeconds": round(
                    max(
                        (s.lag_seconds for s in self._senders.values()),
                        default=0.0,
                    ), 6,
                ),
                "shippedBatches": sum(
                    s.shipped_batches for s in self._senders.values()
                ),
                "shippedRecords": sum(
                    s.shipped_records for s in self._senders.values()
                ),
                "reseeds": sum(s.reseeds for s in self._senders.values()),
                "sendErrors": sum(s.send_errors for s in self._senders.values()),
                "appliedBatches": self.applied_batches,
                "appliedRecords": self.applied_records,
                "rejectedBatches": self.rejected_batches,
                "promotions": self.promotions,
                "demotions": self.demotions,
                "adoptions": self.adoptions,
                "epochCompactions": self.epoch_compactions,
                "senders": senders,
                "feeds": feeds,
            }
            if self.supervisor is not None:
                doc["failover"] = self.supervisor.stats()
            return doc

    def _metric_samples(self):
        """Raw collector for the per-tenant ``logparser_replication_*``
        families (obs/registry.py drops undeclared names and swallows
        errors, so this can never take down /metrics)."""
        with self._lock:
            out = [
                ("logparser_replication_epoch", {"role": self.role},
                 float(self.epoch)),
                ("logparser_replication_promotions_total",
                 {"kind": "promote"}, float(self.promotions)),
                ("logparser_replication_promotions_total",
                 {"kind": "demote"}, float(self.demotions)),
                ("logparser_replication_total", {"outcome": "shipped"},
                 float(sum(s.shipped_batches for s in self._senders.values()))),
                ("logparser_replication_total", {"outcome": "reseed"},
                 float(sum(s.reseeds for s in self._senders.values()))),
                ("logparser_replication_total", {"outcome": "send_error"},
                 float(sum(s.send_errors for s in self._senders.values()))),
                ("logparser_replication_total", {"outcome": "applied"},
                 float(self.applied_batches)),
                ("logparser_replication_total", {"outcome": "rejected"},
                 float(self.rejected_batches)),
            ]
            for tid, s in self._senders.items():
                labels = {"tenant": tid, "side": "sender"}
                out.append(
                    ("logparser_replication_lag_records", labels,
                     float(s.lag_records))
                )
                out.append(
                    ("logparser_replication_lag_bytes", labels,
                     float(s.lag_bytes))
                )
                out.append(
                    ("logparser_replication_lag_seconds", labels,
                     float(s.lag_seconds))
                )
                out.append(
                    ("logparser_replication_acked_offset", labels,
                     float(s.acked_offset))
                )
            for tid, st in self._feeds.items():
                out.append(
                    ("logparser_replication_acked_offset",
                     {"tenant": tid, "side": "receiver"}, float(st.acked))
                )
        return out


# --------------------------------------------------------------- supervisor


class FailoverSupervisor:
    """Standby-side health watch with consecutive-failure counting
    (unlike DrainSupervisor.watch_health's one-shot verdict): probe the
    primary's ``/q/health`` every ``poll_s``; once it has been down for
    ``after_s`` CONSECUTIVE seconds, promote. One successful probe
    resets the clock — a flapping primary never trips a promotion."""

    def __init__(
        self,
        replicator: Replicator,
        primary_url: str,
        *,
        after_s: float,
        poll_s: float = 1.0,
        clock: Callable[[], float] = pclock.mono,
        probe: Callable[[], bool] | None = None,
    ):
        self.replicator = replicator
        self.primary_url = primary_url.rstrip("/")
        self.after_s = float(after_s)
        self.poll_s = float(poll_s)
        self.clock = clock
        self.probe = probe or self._http_probe
        self.probes = 0
        self.failures = 0
        self._down_since: float | None = None
        self._stop_evt = threading.Event()
        self._thread: threading.Thread | None = None

    def _http_probe(self) -> bool:
        try:
            req = urllib.request.Request(self.primary_url + "/q/health")
            with urllib.request.urlopen(req, timeout=max(1.0, self.poll_s)) as r:
                return 200 <= r.status < 300
        except (urllib.error.URLError, OSError, ValueError):
            return False

    def check_once(self) -> str | None:
        """One probe; returns "promoted" when the failover fired."""
        if self.replicator.role == "primary":
            return None
        now = self.clock()
        self.probes += 1
        if self.probe():
            self._down_since = None
            return None
        self.failures += 1
        if self._down_since is None:
            self._down_since = now
        if now - self._down_since >= self.after_s:
            try:
                self.replicator.promote(reason="health")
            except ReplicationError as exc:
                log.warning("failover promote refused: %s", exc)
                return None
            return "promoted"
        return None

    def start(self) -> threading.Thread:
        if self._thread is None:
            def loop():
                while not pclock.wait(self._stop_evt, self.poll_s):
                    try:
                        if self.check_once() == "promoted":
                            return
                    except Exception:
                        log.exception("failover probe failed")

            self._thread = threading.Thread(
                target=loop, name="failover-watch", daemon=True
            )
            self._thread.start()
        return self._thread

    def stop(self) -> None:
        self._stop_evt.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)

    def stats(self) -> dict:
        down_s = 0.0
        if self._down_since is not None:
            down_s = max(0.0, self.clock() - self._down_since)
        return {
            "armed": self._thread is not None and self._thread.is_alive(),
            "primary": self.primary_url,
            "afterS": self.after_s,
            "probes": self.probes,
            "failures": self.failures,
            "downS": round(down_s, 3),
        }
