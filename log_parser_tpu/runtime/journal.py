"""Durable frequency state: write-ahead journal + atomic snapshots.

The only state the engine *evolves* across requests is the per-pattern
frequency window feeding the seven-factor score (FrequencyTrackingService
in the reference; ``GoldenFrequencyTracker`` here). PR 2 made the on-disk
*caches* crash-safe; this module makes the engine state itself crash-safe:

- every frequency mutation appends one CRC-framed record to
  ``journal.wal`` (write+flush per record so the bytes reach the OS page
  cache immediately — ``kill -9`` semantics lose nothing — with *group*
  fsync on a configurable interval so durability-to-platter does not sit
  on the request path);
- a background snapshotter periodically writes ``snapshot.json``
  atomically (tmp + fsync + ``os.replace``; sha256 sidecar; mismatch
  quarantined to ``.corrupt`` — the same discipline as patterns/libcache)
  and truncates the journal;
- on boot :class:`FrequencyJournal` restores the snapshot and replays the
  journal tail, tolerating a torn final record (the torn bytes are
  quarantined to ``journal.wal.torn`` and the file truncated to the last
  whole frame — a crash mid-``write`` is an expected event, not
  corruption).

Records carry wall-clock time so replay is portable across processes:
each match record is aged exactly like :meth:`GoldenFrequencyTracker
.snapshot` ages live entries. The frequency window is *hours* wide, so
the seconds of skew a crash/restart introduces cannot move a timestamp
across the window boundary in any realistic deployment — windowed counts,
and therefore scores, replay bit-identically.

Fault sites (LOG_PARSER_TPU_FAULTS): ``journal`` (an append fails —
contained: the request is still served, the journal marks itself
unhealthy and /q/health degrades), ``journal_torn`` (the append writes a
deliberately torn frame and the journal wedges so the torn frame stays
final — the recovery drill), ``snapshot`` (the snapshotter aborts without
truncating the journal — no state is lost, the journal just keeps
growing).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import struct
import threading
import time
import zlib
from collections import deque
from typing import Callable

from log_parser_tpu import _clock as pclock
from log_parser_tpu.config import ScoringConfig
from log_parser_tpu.golden.engine import GoldenFrequencyTracker
from log_parser_tpu.runtime import faults, pressure

log = logging.getLogger(__name__)

# frame header: little-endian payload length + CRC32 of the payload
_FRAME = struct.Struct("<II")
# sanity bound on a single record (a barrier carries a full snapshot)
_MAX_PAYLOAD = 64 << 20

SNAPSHOT_NAME = "snapshot.json"
JOURNAL_NAME = "journal.wal"


def apply_record(state: dict[str, list[float]], payload: dict, now: float) -> None:
    """Apply one WAL record payload to a portable ages state, in place.

    This IS the replay semantic — boot recovery (:meth:`FrequencyJournal._apply`)
    and the replication receiver (runtime/replicate.py) both go through it,
    so a standby fed shipped frames converges to exactly what a local replay
    of the same prefix would produce. Ages are relative to ``now``; unknown
    kinds are skipped so a newer writer's records never brick an older
    reader.
    """
    kind = payload.get("k")
    if kind == "m":  # match: n timestamps at wall-clock w
        pid = payload.get("id")
        n = int(payload.get("n", 0))
        if not pid or n <= 0:
            return
        age = max(0.0, now - float(payload.get("w", now)))
        state.setdefault(str(pid), []).extend([age] * n)
    elif kind == "r":  # reset one id (entry kept, emptied) or all
        pid = payload.get("id")
        if pid is None:
            state.clear()
        elif pid in state:
            state[pid] = []
    elif kind == "b":  # barrier: full-state replace (admin restore,
        # rollback) — replay converges here regardless of the tail above
        ages = payload.get("ages")
        if not isinstance(ages, dict):
            return
        drift = max(0.0, now - float(payload.get("w", now)))
        state.clear()
        for pid, ages_list in ages.items():
            state[str(pid)] = [max(0.0, float(a)) + drift for a in ages_list]


def _atomic_write(path: str, data: bytes) -> None:
    """tmp + fsync + rename, then the sha256 sidecar (same publish
    discipline as patterns/libcache — the sidecar window is two fsyncs
    wide; recovery treats a mismatch as quarantine, never a crash)."""
    directory = os.path.dirname(path) or "."
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    sum_tmp = path + ".sum.tmp"
    with open(sum_tmp, "w", encoding="utf-8") as f:
        f.write(hashlib.sha256(data).hexdigest() + "\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(sum_tmp, path + ".sum")
    try:
        dfd = os.open(directory, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:  # pragma: no cover - platform-specific directory fsync
        pass


class FrequencyJournal:
    """CRC-framed WAL + snapshot pair under one state directory.

    Thread contract: mutation appends happen under the engine state lock
    (the tracker is only ever mutated there), so appends are serialized;
    the maintenance thread synchronizes with appenders on ``_mu`` and
    takes the engine state lock (``source_lock``) only to read a snapshot.
    """

    def __init__(
        self,
        state_dir: str,
        *,
        fsync_ms: float = 50.0,
        snapshot_every: int = 512,
        wall: Callable[[], float] = pclock.wall,
    ):
        self.state_dir = str(state_dir)
        self.fsync_ms = float(fsync_ms)
        self.snapshot_every = int(snapshot_every)
        self._wall = wall
        os.makedirs(self.state_dir, exist_ok=True)
        self._snap_path = os.path.join(self.state_dir, SNAPSHOT_NAME)
        self._wal_path = os.path.join(self.state_dir, JOURNAL_NAME)

        self._mu = threading.Lock()
        self.healthy = True
        self.epoch = 0
        self.records = 0  # appended this process
        self.replayed = 0  # records replayed at boot
        self.fsyncs = 0
        self.snapshots = 0
        self.write_errors = 0
        self.snapshot_errors = 0
        self.torn_tails = 0  # torn final records quarantined at boot
        self.snapshot_corrupt = 0  # snapshots quarantined at boot
        self._dirty = False
        self._since_snapshot = 0
        self._wedged = False  # a journal_torn fault leaves the torn frame final
        # hard disk pressure: appends divert to this bounded ring — an
        # observability echo of state the live tracker already holds, so
        # overflow loses nothing rearm()'s barrier would not recover
        self.degraded = False
        self.degraded_records = 0
        self.snapshot_skips = 0  # snapshots skipped while writes paused
        self._degraded_ring: deque | None = None

        self.recovered_ages: dict[str, list[float]] = self._recover()

        self._fp = open(self._wal_path, "ab")
        self._source: Callable[[], dict[str, list[float]]] | None = None
        self._source_lock: threading.Lock | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # ------------------------------------------------------------ recovery

    def _recover(self) -> dict[str, list[float]]:
        now = self._wall()
        state = self._load_snapshot(now)
        for payload in self._replay_wal():
            self._apply(state, payload, now)
            self.replayed += 1
        return state

    def _load_snapshot(self, now: float) -> dict[str, list[float]]:
        if not os.path.exists(self._snap_path):
            return {}
        try:
            with open(self._snap_path, "rb") as f:
                raw = f.read()
            with open(self._snap_path + ".sum", "r", encoding="utf-8") as f:
                want = f.read().strip()
            if hashlib.sha256(raw).hexdigest() != want:
                raise ValueError("sha256 mismatch")
            doc = json.loads(raw.decode("utf-8"))
            ages = doc["ages"]
            if not isinstance(ages, dict):
                raise ValueError("snapshot ages must be a mapping")
            self.epoch = int(doc.get("epoch", 0))
            wall = float(doc.get("wall", now))
            drift = max(0.0, now - wall)
            return {
                str(pid): [max(0.0, float(a)) + drift for a in ages_list]
                for pid, ages_list in ages.items()
            }
        except (OSError, ValueError, KeyError, TypeError) as exc:
            self.snapshot_corrupt += 1
            log.error("quarantining corrupt snapshot %s: %s", self._snap_path, exc)
            try:
                os.replace(self._snap_path, self._snap_path + ".corrupt")
            except OSError:  # pragma: no cover - quarantine is best-effort
                pass
            try:
                os.remove(self._snap_path + ".sum")
            except OSError:
                pass
            return {}

    def _replay_wal(self) -> list[dict]:
        """Parse whole frames; a torn tail (short header, short payload, or
        CRC mismatch on the FINAL frame) is quarantined and truncated away.
        Corruption *before* the final frame also lands here: everything
        from the first bad frame on is unreadable by construction, so the
        honest move is the same quarantine + truncate."""
        if not os.path.exists(self._wal_path):
            return []
        with open(self._wal_path, "rb") as f:
            raw = f.read()
        out: list[dict] = []
        off = 0
        while off + _FRAME.size <= len(raw):
            length, crc = _FRAME.unpack_from(raw, off)
            start = off + _FRAME.size
            if length > _MAX_PAYLOAD or start + length > len(raw):
                break
            payload = raw[start:start + length]
            if zlib.crc32(payload) != crc:
                break
            try:
                out.append(json.loads(payload.decode("utf-8")))
            except ValueError:
                break
            off = start + length
        if off < len(raw):
            self.torn_tails += 1
            torn = raw[off:]
            log.warning(
                "journal %s: torn tail of %d byte(s) after %d good record(s); "
                "quarantining to .torn", self._wal_path, len(torn), len(out),
            )
            try:
                with open(self._wal_path + ".torn", "ab") as f:
                    f.write(torn)
                with open(self._wal_path, "r+b") as f:
                    f.truncate(off)
            except OSError:  # pragma: no cover - quarantine is best-effort
                log.exception("failed to quarantine torn journal tail")
        return out

    def _apply(self, state: dict[str, list[float]], payload: dict, now: float) -> None:
        apply_record(state, payload, now)

    # ------------------------------------------------------------- appends

    def append_match(self, pattern_id: str, n: int) -> None:
        self._append({"k": "m", "id": pattern_id, "n": int(n), "w": self._wall()})

    def append_reset(self, pattern_id: str | None) -> None:
        self._append({"k": "r", "id": pattern_id, "w": self._wall()})

    def append_barrier(self, ages: dict[str, list[float]]) -> None:
        self._append({"k": "b", "ages": ages, "w": self._wall()})

    def _append(self, payload_obj: dict) -> None:
        """One framed record: write+flush (OS page cache) now, fsync later
        on the group interval. NEVER raises into the request path — any
        failure marks the journal unhealthy for /q/health instead."""
        fp = self._fp
        if fp is None or self._wedged:
            return
        try:
            faults.fire("journal")
        except faults.InjectedFault:
            self.write_errors += 1
            self.healthy = False
            return
        torn = False
        try:
            faults.fire("journal_torn")
        except faults.InjectedFault:
            torn = True
        if self.degraded:
            with self._mu:
                if self._degraded_ring is not None:
                    self._degraded_ring.append(payload_obj)
                    self.degraded_records += 1
            return
        payload = json.dumps(payload_obj, separators=(",", ":")).encode("utf-8")
        frame = _FRAME.pack(len(payload), zlib.crc32(payload)) + payload
        if torn:
            # write a deliberately torn frame and wedge: the torn frame
            # must stay FINAL for recovery to exercise the truncate path
            frame = frame[: _FRAME.size + max(0, len(payload) // 2)]
        try:
            pressure.disk_write_guard("wal_append")
            with self._mu:
                if torn:
                    self._wedged = True
                    self.healthy = False
                fp.write(frame)
                fp.flush()
                self._dirty = True
                if not torn:
                    self.records += 1
                    self._since_snapshot += 1
        except (OSError, ValueError) as exc:
            self.write_errors += 1
            self.healthy = False
            log.error("journal append failed: %s", exc)
            pressure.note_write_error(exc, "wal_append")

    # --------------------------------------------------------- maintenance

    def start(
        self,
        source: Callable[[], dict[str, list[float]]],
        source_lock: threading.Lock,
    ) -> None:
        """Begin group-fsync + periodic-snapshot maintenance. ``source``
        reads the live tracker's portable snapshot; it is called under
        ``source_lock`` (the engine state lock) so it never races a
        request's finish phase."""
        self._source = source
        self._source_lock = source_lock
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._maintain, name="freq-journal", daemon=True
            )
            self._thread.start()

    def _maintain(self) -> None:
        interval = max(0.001, self.fsync_ms / 1000.0)
        while not pclock.wait(self._stop, interval):
            self.flush()
            if self._since_snapshot >= self.snapshot_every:
                self.snapshot_now()

    def flush(self) -> None:
        """Group fsync: durability point for everything appended so far.
        Called on the interval, on SIGTERM drain, and at interpreter exit."""
        try:
            with self._mu:
                fp = self._fp
                if fp is None or not self._dirty:
                    return
                pressure.disk_write_guard("fsync")
                fp.flush()
                os.fsync(fp.fileno())
                self._dirty = False
                self.fsyncs += 1
        except (OSError, ValueError) as exc:
            self.write_errors += 1
            self.healthy = False
            log.error("journal fsync failed: %s", exc)
            pressure.note_write_error(exc, "fsync")

    def wal_feed(self, offset: int, max_bytes: int = 1 << 20) -> tuple[int, int, bytes]:
        """Read raw frame bytes for the replication sender.

        Returns ``(epoch, wal_size, data)`` where ``data`` is up to
        ``max_bytes`` of the on-disk WAL starting at ``offset`` (frame
        boundaries NOT guaranteed — the caller trims to whole frames).
        Runs under ``_mu``, the same lock ``snapshot_now`` holds for its
        truncate + epoch bump, so the (epoch, size, bytes) triple is always
        consistent: a rotation can never truncate between the size read and
        the byte read. ``max_bytes <= 0`` reads nothing — the cheap way to
        sample (epoch, size).
        """
        offset = max(0, int(offset))
        with self._mu:
            fp = self._fp
            if fp is not None:
                try:
                    fp.flush()
                except (OSError, ValueError):  # pragma: no cover - fd gone
                    pass
            epoch = self.epoch
            try:
                with open(self._wal_path, "rb") as f:
                    f.seek(0, os.SEEK_END)
                    size = f.tell()
                    if max_bytes <= 0 or offset >= size:
                        return epoch, size, b""
                    f.seek(offset)
                    return epoch, size, f.read(max_bytes)
            except OSError:
                return epoch, 0, b""

    def snapshot_now(self) -> bool:
        """Write an atomic snapshot of the live tracker and truncate the
        journal. An injected/organic failure aborts WITHOUT truncating —
        the journal keeps the full tail, nothing is lost. Under hard
        disk pressure the writer skips atomically instead of raising
        (rearm() calls back in once the ladder clears)."""
        source, lock = self._source, self._source_lock
        if source is None or lock is None or self._fp is None:
            return False
        if pressure.writes_paused():
            self.snapshot_skips += 1
            return False
        with lock:
            ages = source()
        try:
            faults.fire("snapshot")
            pressure.disk_write_guard("snapshot_rotate")
            doc = {
                "version": 1,
                "epoch": self.epoch + 1,
                "wall": self._wall(),
                "ages": ages,
            }
            _atomic_write(
                self._snap_path,
                json.dumps(doc, separators=(",", ":")).encode("utf-8"),
            )
        except (faults.InjectedFault, OSError, ValueError) as exc:
            self.snapshot_errors += 1
            log.error("snapshot aborted (journal NOT truncated): %s", exc)
            if isinstance(exc, OSError):
                pressure.note_write_error(exc, "snapshot_rotate")
            return False
        # snapshot + sidecar durable -> the journal tail is now redundant
        try:
            with self._mu:
                fp = self._fp
                if fp is None:
                    return False
                fp.flush()
                fp.truncate(0)
                os.fsync(fp.fileno())
                self._dirty = False
                self._since_snapshot = 0
                self.epoch += 1
                self.snapshots += 1
        except (OSError, ValueError) as exc:
            self.write_errors += 1
            self.healthy = False
            log.error("journal truncate failed: %s", exc)
            pressure.note_write_error(exc, "snapshot_rotate")
            return False
        return True

    # ------------------------------------------------------ disk pressure

    def degrade(self) -> None:
        """Hard disk pressure: divert appends to a bounded in-memory
        ring and surface unhealthy. The ring is an *echo* — the live
        tracker still holds every mutation — so the only real loss is
        crash-durability of post-degrade mutations, which is exactly
        what the ``durability: degraded`` stamp announces."""
        with self._mu:
            if self.degraded:
                return
            self.degraded = True
            self._degraded_ring = deque(maxlen=pressure.DEGRADED_RING_RECORDS)
            self.healthy = False
        log.warning(
            "journal %s degraded: appends divert to a %d-record ring",
            self._wal_path, pressure.DEGRADED_RING_RECORDS,
        )

    def rearm(self) -> bool:
        """Recovery barrier after pressure clears: one clean snapshot of
        the live tracker (which the diverted ring records merely echoed)
        plus the WAL truncate re-establishes fsync'd journaling — a
        crash after this replays bit-identically to an unpressured run.
        The ring is dropped only on success; a failed snapshot leaves
        the journal degraded for the next poll to retry."""
        if not self.degraded:
            return True
        if not self.snapshot_now():
            return False
        with self._mu:
            self.degraded = False
            self._degraded_ring = None
            if not self._wedged:
                self.healthy = True
        log.warning("journal %s re-armed: fsync'd journaling restored",
                    self._wal_path)
        return True

    # ------------------------------------------------------------ shutdown

    def close(self) -> None:
        """Clean shutdown: stop maintenance, flush, close. After this a
        boot needs no replay beyond reading the (already-durable) tail."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        self.flush()
        with self._mu:
            fp, self._fp = self._fp, None
            if fp is not None:
                try:
                    fp.close()
                except OSError:  # pragma: no cover
                    pass

    def abandon(self) -> None:
        """Crash simulation for tests: stop maintenance and drop the file
        handle WITHOUT the final fsync/snapshot. Because every append
        already write+flushed to the OS page cache, this is byte-for-byte
        what a ``kill -9`` leaves behind."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        with self._mu:
            fp, self._fp = self._fp, None
            if fp is not None:
                try:
                    fp.close()  # per-append flush means no buffered bytes
                except OSError:  # pragma: no cover
                    pass

    # --------------------------------------------------------------- stats

    def stats(self) -> dict:
        with self._mu:
            return {
                "stateDir": self.state_dir,
                "healthy": self.healthy,
                "epoch": self.epoch,
                "records": self.records,
                "replayed": self.replayed,
                "fsyncs": self.fsyncs,
                "snapshots": self.snapshots,
                "writeErrors": self.write_errors,
                "snapshotErrors": self.snapshot_errors,
                "tornTails": self.torn_tails,
                "snapshotCorrupt": self.snapshot_corrupt,
                "degraded": self.degraded,
                "degradedRecords": self.degraded_records,
                "snapshotSkips": self.snapshot_skips,
            }


class DurableFrequencyTracker(GoldenFrequencyTracker):
    """GoldenFrequencyTracker whose every mutation is journaled. Dropped
    in as ``engine.frequency`` by :meth:`AnalysisEngine.attach_journal`;
    all mutation channels (fused finish phase, golden per-match recording,
    admin reset/restore, rollback ``_load_state``) route through the four
    overrides below, so nothing escapes the WAL."""

    def __init__(self, config: ScoringConfig, clock, journal: FrequencyJournal):
        super().__init__(config, clock=clock)
        self.journal = journal
        if journal.recovered_ages:
            # bypass the journaling restore() override: recovery replays
            # the log, it must not extend it
            GoldenFrequencyTracker.restore(self, journal.recovered_ages)

    def record_pattern_matches(self, pattern_id: str | None, n: int) -> None:
        if n <= 0 or pattern_id is None or pattern_id.strip() == "":
            return  # mirror the base guard so no-op calls stay un-journaled
        super().record_pattern_matches(pattern_id, n)
        self.journal.append_match(pattern_id, n)

    def reset_pattern_frequency(self, pattern_id: str) -> None:
        super().reset_pattern_frequency(pattern_id)
        self.journal.append_reset(pattern_id)

    def reset_all_frequencies(self) -> None:
        super().reset_all_frequencies()
        self.journal.append_reset(None)

    def restore(self, ages: dict[str, list[float]]) -> None:
        """Admin restore writes a journal *barrier* (full-state replace):
        a crash immediately afterwards recovers the restored state, never
        the pre-restore tail. Validation failures raise before the barrier
        — a rejected restore leaves the journal untouched."""
        super().restore(ages)
        self.journal.append_barrier(self.snapshot())

    def _load_state(self, state: dict[str, list[float]]) -> None:
        """Rollback path (request crash containment, batch demux). A
        barrier makes replay converge to the rolled-back state even though
        the aborted request's match records already hit the journal."""
        super()._load_state(state)
        self.journal.append_barrier(self.snapshot())
