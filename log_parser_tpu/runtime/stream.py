"""Streaming follow-mode: incremental tail ingestion with carried scan
state and monotone early-emit.

The one-shot engine sees a complete post-mortem blob; pod logs arrive as
*tails*, and the operator wants time-to-first-detection, not
time-to-post-mortem. This module is the session layer that turns the
batch pipeline into a streaming one without forking its semantics:

- **Reassembly.** Raw byte chunks pass through
  :class:`~log_parser_tpu.native.ingest.StreamNormalizer` (incremental
  UTF-8 ``errors="replace"`` — split-invariant, so a multi-byte sequence
  cut by a chunk boundary decodes exactly as the joined blob would) and
  an incremental ``\\r?\\n`` splitter that holds a trailing ``\\r`` until
  the next byte disambiguates separator from content. Every line is
  device-scored exactly once, when it completes.

- **Carried scan state.** The line that straddles a chunk boundary is
  not rescanned: :meth:`FusedMatchScore.host_carry` (ops/fused.py →
  ops/match.py) exposes the match cube's per-line automata — Shift-Or
  bit registers, dense-DFA states, union-DFA states — as a resumable
  carry that feeds forward across chunks and snapshots the exact cube
  row at any prefix. Whole lines completed inside one chunk batch
  through the normal residual cube dispatch; repeat lines are served by
  the line cache and never touch either path.

- **Monotone early-emit.** After each chunk the session re-finalizes the
  window (context/proximity/chronological factors legitimately move as
  the window grows; the frequency read is a rolled-back peek under
  ``state_lock`` — nothing is recorded until close). Events at or above
  the emit threshold produce ``emit`` frames; any change to an already
  emitted event — firming up, shifting down, or vanishing — produces an
  explicit ``revised`` frame. An emitted score is never silently
  retracted.

- **Replay theorem.** ``close()`` rebuilds the full-blob
  :class:`Corpus`, splices the engine's own override cube over the
  per-line bits accumulated above, and runs the exact ``_finish``
  sequence (read-before-record frequency, ``finalize_batch``, assembly)
  under ``_request_scope`` + ``state_lock``. Feeding a blob in N chunks
  of any split therefore yields final scores bit-identical to one-shot
  ``analyze()`` on the concatenation — pinned by tests/test_stream.py.

- **Reliability.** Sessions are first-class citizens of the existing
  layer: :class:`StreamManager` admits each open session through the
  shared admission gate (open sessions count against the in-flight
  budget) and reaps idle ones after ``--stream-ttl-s``; the
  ``quarantine`` fault site fires per chunk with the chunk's content as
  the key, so a poison frame strikes its own fingerprint and kills the
  SESSION, not the server; a non-poison device fault flips the session
  to a golden continuation (host path) that still closes with committed
  frequency state; an ``apply_library`` hot-swap is detected by reload
  epoch and the session re-bases — re-scores its window under the new
  bank inside the next chunk's ``_request_scope`` — emitting ``revised``
  frames for anything the new library no longer supports.
"""

from __future__ import annotations

import math
import threading
import time
import uuid

import numpy as np

from log_parser_tpu import _clock as pclock
from log_parser_tpu.golden.engine import (
    build_metadata,
    build_summary,
    extract_context,
)
from log_parser_tpu.models.analysis import AnalysisResult, MatchedEvent
from log_parser_tpu.models.pod import PodFailureData
from log_parser_tpu.native.ingest import Corpus, StreamNormalizer
from log_parser_tpu.ops.encode import DEFAULT_MAX_LINE_BYTES, _pad_rows
from log_parser_tpu.runtime import faults
from log_parser_tpu.runtime.finalize import finalize_batch
from log_parser_tpu.runtime.linecache import line_key, records_from_bits
from log_parser_tpu.runtime.quarantine import fingerprint as quarantine_fingerprint

DEFAULT_EMIT_THRESHOLD = 0.0
DEFAULT_STREAM_TTL_S = 300.0

# The streaming frame vocabulary (docs/OPS.md "Streaming" runbook rows —
# pinned by tools/hygiene.py check 12). Every NDJSON / gRPC frame a
# session produces carries exactly one of these in its "type" field.
FRAME_TYPES = {
    "emit": "event crossed the emit threshold for the first time",
    "revised": "an emitted event's score changed or was retracted",
    "final": "close(): the full one-shot-identical AnalysisResult",
    "error": "structured failure; the session is dead after this frame",
}


class StreamError(Exception):
    """Structured session failure: carried verbatim into an ``error``
    frame. ``reason`` is a stable machine code (``closed``, ``poison``,
    ``fault``, ``ttl``, ``admission``, ``internal``)."""

    def __init__(self, reason: str, message: str):
        super().__init__(message)
        self.reason = reason


def _is_pure_line(line: str) -> bytes | None:
    """The ingest-normalized bytes of ``line`` when its device bits are a
    pure function of content — ASCII, no content NUL, within the device
    line budget, no lone surrogates — else None. Mirrors the stable half
    of ``encode_lines``'s ``needs_host`` verdict (the width-dependent
    ``len > device_width`` term is handled by the override splice, which
    covers every ``needs_host`` line of the frame's corpus)."""
    try:
        b = line.encode("utf-8")
    except UnicodeEncodeError:
        return None
    if not b.isascii() or b"\x00" in b or len(b) > DEFAULT_MAX_LINE_BYTES:
        return None
    return b


def _scores_equal(a: float, b: float) -> bool:
    if math.isnan(a) and math.isnan(b):
        return True
    return a == b


class StreamSession:
    """One follow-mode session: feed byte chunks, receive frames, close
    for the one-shot-identical final result. Thread-safe per session;
    sessions only hold engine-wide resources (``_request_scope``,
    ``state_lock``) inside a single ``feed``/``close`` call, never while
    idle between chunks — so a hot reload quiesces normally and the
    session re-bases on its next chunk."""

    def __init__(
        self,
        engine,
        session_id: str,
        emit_threshold: float = DEFAULT_EMIT_THRESHOLD,
        manager: "StreamManager | None" = None,
    ):
        self.engine = engine
        self.session_id = session_id
        self.emit_threshold = float(emit_threshold)
        self.manager = manager
        self._lock = threading.RLock()
        self._start = pclock.mono()
        self.last_active = manager.clock() if manager else pclock.mono()

        self._normalizer = StreamNormalizer()
        self._text = ""  # full decoded window (the would-be blob)
        self._lines: list[str] = []  # completed (newline-terminated) lines
        self._bits: list[np.ndarray | None] = []  # pre-override rows
        self._pending = ""  # text since the last line terminator
        self._tail_fed = 0  # chars of _pending already fed to the carry
        self._tail_pure = True
        self._carry = engine.fused.host_carry()
        if self._carry is not None:
            self._carry.reset()
        self._epoch = engine.reload_epoch

        self.mode = "device"  # "device" | "golden"
        self.closed = False
        self.kill_reason: str | None = None
        # optional operator-facing detail for the dead-session error
        # frame — a "migrated" kill names the new owner here so the
        # client knows where to reconnect
        self.kill_message: str | None = None
        self._seq = 0
        self._chunks = 0  # fed chunks, for the session span
        # (line_idx, pattern_id) -> last reported score, for events that
        # crossed the emit threshold: the monotone-refinement ledger
        self._ledger: dict[tuple[int, str], float] = {}

    # ---------------------------------------------------------------- frames

    def _frame(self, ftype: str, **fields) -> dict:
        self._seq += 1
        frame = {"type": ftype, "session": self.session_id, "seq": self._seq}
        frame.update(fields)
        if self.manager is not None:
            self.manager._note_frame(ftype)
        return frame

    def _error_frame(self, err: StreamError) -> dict:
        return self._frame("error", reason=err.reason, message=str(err))

    def _diff_frames(self, current: dict[tuple[int, str], float]) -> list[dict]:
        """Ledger reconciliation: emit/revised frames for this window
        evaluation. ``current`` maps (0-based line, pattern id) to score."""
        frames: list[dict] = []
        for key, score in current.items():
            line_idx, pid = key
            prev = self._ledger.get(key)
            if prev is None:
                if score >= self.emit_threshold:
                    frames.append(
                        self._frame(
                            "emit", line=line_idx + 1, patternId=pid,
                            score=score,
                        )
                    )
                    self._ledger[key] = score
            elif not _scores_equal(prev, score):
                frames.append(
                    self._frame(
                        "revised", line=line_idx + 1, patternId=pid,
                        score=score, previousScore=prev,
                        retracted=bool(score < self.emit_threshold),
                    )
                )
                self._ledger[key] = score
        for key in [k for k in self._ledger if k not in current]:
            prev = self._ledger.pop(key)
            frames.append(
                self._frame(
                    "revised", line=key[0] + 1, patternId=key[1],
                    score=None, previousScore=prev, retracted=True,
                )
            )
        return frames

    # ------------------------------------------------------------- lifecycle

    def kill(self, reason: str, message: str | None = None) -> None:
        """Terminate the session (poison chunk, injected fault, TTL reap,
        transport drop, migration/drain). Idempotent; releases the
        admission slot. ``message`` rides the dead-session ``error``
        frame — a migration kill carries the new owner's URL."""
        with self._lock:
            if self.closed:
                return
            self.closed = True
            self.kill_reason = reason
            self.kill_message = message
        self._commit_session_span(reason)
        if self.manager is not None:
            self.manager._discard(self, reason)

    def _touch(self) -> None:
        self.last_active = (
            self.manager.clock() if self.manager else pclock.mono()
        )

    # ----------------------------------------------------------- span hooks

    def _note_chunk_span(self, t0: float, n_bytes: int, n_frames: int,
                         error: str | None = None) -> None:
        """Stage one per-chunk child span under the session's trace
        (trace id == session id, so mesh/demux work keyed by the session
        attributes here too)."""
        attrs = {"bytes": n_bytes, "frames": n_frames, "mode": self.mode}
        if error:
            attrs["error"] = error
        self.engine.obs.spans.annotate(
            self.session_id, "chunk", time.perf_counter() - t0, attrs=attrs
        )

    def _commit_session_span(self, outcome: str) -> None:
        """Commit the session's long-lived span; the chunk/rebase
        children staged under the session id attach here. force=True:
        sessions are rare relative to requests and the only place
        per-chunk causality lives — sampling must never drop them."""
        eng = self.engine
        eng.obs.spans.end_trace(
            self.session_id,
            duration_s=pclock.mono() - self._start,
            tenant=eng.obs_tenant,
            name="session",
            attrs={
                "outcome": outcome,
                "chunks": self._chunks,
                "frames": self._seq,
                "lines": len(self._lines),
                "mode": self.mode,
            },
            force=True,
        )

    # --------------------------------------------------------------- feeding

    def feed(self, chunk: bytes) -> list[dict]:
        """Ingest one byte chunk; returns the frames it produced. A dead
        session answers every feed with a single ``error`` frame."""
        with self._lock:
            if self.closed:
                return [
                    self._frame(
                        "error", reason=self.kill_reason or "closed",
                        message=self.kill_message or "session is closed",
                    )
                ]
            self._touch()
            t0 = time.perf_counter()
            try:
                with self.engine._request_scope():
                    frames = self._feed_in_scope(bytes(chunk))
                self._chunks += 1
                self._note_chunk_span(t0, len(chunk), len(frames))
                return frames
            except StreamError as err:
                frame = self._error_frame(err)
                # stage the chunk span BEFORE kill commits the session
                # trace, so the fatal chunk attaches to the tree
                self._note_chunk_span(t0, len(chunk), 1, error=err.reason)
                self.kill(err.reason)
                return [frame]
            except Exception as exc:  # wedged sessions are forbidden
                frame = self._frame(
                    "error", reason="internal", message=repr(exc)
                )
                self._note_chunk_span(t0, len(chunk), 1, error="internal")
                self.kill("internal")
                return [frame]

    def _feed_in_scope(self, chunk: bytes) -> list[dict]:
        eng = self.engine
        if eng.reload_epoch != self._epoch:
            self._rebase()
        text = self._normalizer.feed(chunk)
        try:
            faults.fire("stream", key=text)
        except Exception as exc:
            raise StreamError("fault", f"stream fault: {exc!r}") from exc
        if self.manager is not None:
            self.manager._note_chunk(len(chunk))
        self._text += text
        if self.mode == "golden":
            return self._provisional_golden()
        batch_idx = self._ingest_text(text)
        try:
            self._chunk_device_step(text, batch_idx)
        except Exception as exc:
            self._handle_device_exc(exc, text)
            return self._provisional_golden()
        return self._provisional_device()

    def _ingest_text(self, text: str) -> list[int]:
        """Incremental split: complete lines, keep the partial tail (and
        its carry) warm. Returns indices of completed lines that still
        need the chunk's residual cube dispatch."""
        eng = self.engine
        buf = self._pending + text
        pieces = buf.split("\n")
        batch_idx: list[int] = []
        for piece in pieces[:-1]:
            line = piece[:-1] if piece.endswith("\r") else piece
            idx = len(self._lines)
            self._lines.append(line)
            pure = _is_pure_line(line)
            if pure is None:
                self._bits.append(None)
                self._tail_pure = False  # consistency; reset below
            else:
                row = self._cache_lookup(pure)
                if row is not None:
                    self._bits.append(row)
                elif self._carry is not None and self._tail_pure:
                    # the straddler (or an in-chunk line): finish it on
                    # the carried automata state instead of rescanning
                    rest = line[self._tail_fed:]
                    if rest:
                        self._carry.feed(
                            rest.encode("utf-8", errors="replace")
                        )
                    self._bits.append(self._carry.snapshot_bits())
                    self._cache_populate(pure, self._bits[-1])
                else:
                    self._bits.append(None)  # filled by the chunk batch
                    batch_idx.append(idx)
            if self._carry is not None:
                self._carry.reset()
            self._tail_fed = 0
            self._tail_pure = True
        self._pending = pieces[-1]
        # advance the tail carry, holding back a trailing "\r" (separator
        # vs content is decided by the NEXT character) and stopping for
        # good once the tail is no longer device-pure
        if self._tail_pure and _is_pure_line(self._pending) is None:
            self._tail_pure = False
        if self._carry is not None and self._tail_pure:
            target = len(self._pending)
            if self._pending.endswith("\r"):
                target -= 1
            if target > self._tail_fed:
                self._carry.feed(
                    self._pending[self._tail_fed:target].encode(
                        "utf-8", errors="replace"
                    )
                )
                self._tail_fed = target
        return batch_idx

    def _cache_lookup(self, line_bytes: bytes) -> np.ndarray | None:
        cache = self.engine.line_cache
        if cache is None:
            return None
        packed = cache.lookup_packed([line_key(line_bytes)], counts=[1])
        if packed[0] is None:
            return None
        return cache.unpack([packed[0]])[0]

    def _cache_populate(self, line_bytes: bytes, row: np.ndarray) -> None:
        cache = self.engine.line_cache
        if cache is not None:
            cache.populate_rows(
                [line_key(line_bytes)], np.asarray(row, dtype=bool)[None, :]
            )

    def _chunk_device_step(self, chunk_text: str, batch_idx: list[int]) -> None:
        """The chunk's device dispatch, under the watchdog with the same
        chaos points as the one-shot path — keyed by THIS chunk's content,
        so a ``match=`` poison spec fires on (and quarantines) exactly the
        chunk that carries it."""
        eng = self.engine

        def _device_step():
            faults.fire("quarantine", key=chunk_text)  # conlint: contained-by-caller (watchdog.run)
            faults.fire("device")  # conlint: contained-by-caller (watchdog.run)
            if not batch_idx:
                return None
            lines_b = [
                self._lines[i].encode("utf-8", errors="replace")
                for i in batch_idx
            ]
            u = len(lines_b)
            width = max(32, -(-max(len(b) for b in lines_b) // 32) * 32)
            pad = _pad_rows(u, eng._corpus_min_rows())
            u8 = np.zeros((pad, width), dtype=np.uint8)
            lengths = np.zeros(pad, dtype=np.int32)
            for j, b in enumerate(lines_b):
                u8[j, : len(b)] = np.frombuffer(b, dtype=np.uint8)
                lengths[j] = len(b)
            return eng._run_cube(u8, lengths, u)

        fresh = eng.watchdog.run(_device_step)
        if batch_idx:
            fresh = np.asarray(fresh)[: len(batch_idx)].astype(bool)
            for j, i in enumerate(batch_idx):
                self._bits[i] = fresh[j]
                self._cache_populate(
                    self._lines[i].encode("utf-8", errors="replace"), fresh[j]
                )

    def _handle_device_exc(self, exc: Exception, chunk_text: str) -> None:
        """Poison kills the session (strikes its chunk fingerprint); any
        other device-classified failure flips this session to a golden
        continuation. Non-device failures propagate as session errors."""
        from log_parser_tpu.runtime.engine import is_device_error

        eng = self.engine
        if not is_device_error(exc):
            raise StreamError("fault", f"chunk ingest failed: {exc!r}") from exc
        if eng._strike_worthy(exc):
            fp = quarantine_fingerprint(chunk_text)
            eng.quarantine.strike(fp)
            if self.manager is not None:
                self.manager._note_poison()
            raise StreamError(
                "poison",
                f"poison chunk (fingerprint {fp[:12]}…): {exc!r}",
            ) from exc
        if not eng.fallback_to_golden:
            raise StreamError("fault", f"device failed: {exc!r}") from exc
        self.mode = "golden"
        if self.manager is not None:
            self.manager._note_golden()

    # ------------------------------------------------------- window evals

    def _assemble_bits(self, corpus: Corpus, tail_bits) -> np.ndarray:
        n = corpus.n_lines
        bits = np.zeros((n, self.engine.bank.n_columns), dtype=bool)
        for i in range(min(n, len(self._lines))):
            row = self._bits[i]
            if row is not None:
                bits[i] = row
        if tail_bits is not None and n == len(self._lines) + 1:
            bits[n - 1] = tail_bits
        return bits

    def _records_for(self, corpus: Corpus, bits: np.ndarray):
        eng = self.engine
        overrides = eng._overrides(corpus)
        if overrides is not None:
            om, ov = overrides
            n = corpus.n_lines
            bits = np.where(om[:n], ov[:n], bits)
        recs = records_from_bits(bits, corpus.n_lines, eng.bank, eng.tables)
        return eng._verify_approx(corpus, recs)

    def _provisional_device(self) -> list[dict]:
        """Re-finalize the current window read-only: stored per-line bits
        + the tail carry's snapshot + the engine's own override cube,
        finalized against a frequency PEEK (read under ``state_lock``,
        never recorded) — the factors legitimately move as the window
        grows, and the ledger diff turns movement into frames."""
        eng = self.engine
        corpus = Corpus(self._text, min_rows=eng._corpus_min_rows())
        tail_bits = None
        if (
            self._carry is not None
            and self._tail_pure
            and corpus.n_lines == len(self._lines) + 1
        ):
            tail_bits = self._carry.snapshot_bits()
        bits = self._assemble_bits(corpus, tail_bits)
        recs = self._records_for(corpus, bits)
        freq_base, freq_exists = self._freq_peek()
        fin = finalize_batch(
            eng.bank, eng.tables, eng.config, recs, corpus.n_lines,
            freq_base, freq_exists,
        )
        current = {
            (int(fin.line[i]), eng.bank.patterns[int(fin.pattern[i])].id):
                float(fin.scores[i])
            for i in range(len(fin.scores))
        }
        return self._diff_frames(current)

    def _freq_peek(self) -> tuple[np.ndarray, np.ndarray]:
        eng = self.engine
        freq_base = np.zeros(max(1, eng.bank.n_freq_slots), dtype=np.float64)
        freq_exists = np.zeros(max(1, eng.bank.n_freq_slots), dtype=bool)
        with eng.state_lock:
            for slot, pid in enumerate(eng.bank.freq_ids):
                freq_base[slot] = eng.frequency.get_windowed_count(pid)
                freq_exists[slot] = eng.frequency.has_entry(pid)
        return freq_base, freq_exists

    def _provisional_golden(self) -> list[dict]:
        """Golden-continuation window eval: run the host analyzer over the
        window with the shared frequency tracker rolled back — the peek
        must not record (close commits exactly once)."""
        eng = self.engine
        with eng.state_lock:
            saved = eng.frequency._save_state()
            try:
                res = eng.golden_fallback.analyze(
                    PodFailureData(logs=self._text)
                )
            finally:
                eng.frequency._load_state(saved)
        current = {
            (ev.line_number - 1, ev.matched_pattern.id): float(ev.score)
            for ev in res.events
        }
        return self._diff_frames(current)

    # --------------------------------------------------------------- rebase

    def _rebase(self) -> None:
        """A hot reload swapped the library while this session was open:
        drop every stored bit row (the column space changed), rebuild the
        carry against the new fused program, and re-score the window under
        the new bank. Caller is inside ``_request_scope`` — the swap
        itself already completed, this is the re-base half of the
        drain-or-rebase contract."""
        eng = self.engine
        t0 = time.perf_counter()
        self._epoch = eng.reload_epoch
        self._carry = eng.fused.host_carry()
        if self._carry is not None:
            self._carry.reset()
        self._tail_fed = 0
        self._bits = [None] * len(self._lines)
        if self.mode != "golden":
            batch_idx = []
            for i, line in enumerate(self._lines):
                pure = _is_pure_line(line)
                if pure is None:
                    continue
                row = self._cache_lookup(pure)
                if row is not None:
                    self._bits[i] = row
                else:
                    batch_idx.append(i)
            self._chunk_device_step("", batch_idx)
            # re-feed the partial tail so its carry resumes under the new
            # automata
            if self._carry is not None and self._tail_pure:
                target = len(self._pending)
                if self._pending.endswith("\r"):
                    target -= 1
                if target > 0:
                    self._carry.feed(
                        self._pending[:target].encode(
                            "utf-8", errors="replace"
                        )
                    )
                self._tail_fed = max(target, 0)
        eng.obs.spans.annotate(
            self.session_id, "rebase", time.perf_counter() - t0,
            attrs={"epoch": self._epoch, "lines": len(self._lines),
                   "mode": self.mode},
        )
        if self.manager is not None:
            self.manager._note_rebase()

    # ------------------------------------------------------------ migration

    def export_carry(self) -> dict:
        """Portable session state for a tenant migration bundle
        (runtime/migrate.py): the decoded window text, the monotone-emit
        ledger, and the frame sequence. Device artifacts (bit rows, the
        automata carry) deliberately do NOT travel — the importer
        re-scores the window under its own bank, which the migration
        protocol has already verified is content-identical, so the
        replayed scores match bit-for-bit. Caller holds the quiesce
        gate, so the cut is consistent; bytes still undecoded in the
        normalizer are flushed into the window (and ingested, so the
        source session stays coherent if the migration aborts) — a
        multi-byte sequence torn exactly at the cut decodes as
        replacement characters, the same verdict a torn end-of-stream
        gets."""
        with self._lock:
            tail = self._normalizer.flush()
            if tail:
                self._text += tail
                self._ingest_text(tail)
            return {
                "sessionId": self.session_id,
                "mode": self.mode,
                "emitThreshold": self.emit_threshold,
                "text": self._text,
                "seq": self._seq,
                "chunks": self._chunks,
                "ledger": [
                    [line_idx, pid, score]
                    for (line_idx, pid), score in self._ledger.items()
                ],
            }

    def restore_carry(self, carry: dict) -> None:
        """Rebuild this freshly-opened session from an exported carry:
        re-ingest the window text (scoring uncached lines once under the
        importer's bank) and restore the ledger + sequence so the
        client's monotone-emit contract continues unbroken across the
        move."""
        with self._lock:
            self.mode = str(carry.get("mode", "device"))
            self._seq = int(carry.get("seq", 0))
            self._chunks = int(carry.get("chunks", 0))
            self.emit_threshold = float(
                carry.get("emitThreshold", self.emit_threshold)
            )
            self._ledger = {
                (int(line_idx), str(pid)): float(score)
                for line_idx, pid, score in carry.get("ledger", ())
            }
            text = str(carry.get("text", ""))
            if not text:
                return
            with self.engine._request_scope():
                self._text = text
                if self.mode != "golden":
                    batch_idx = self._ingest_text(text)
                    self._chunk_device_step(text, batch_idx)

    def rebase_onto(self, engine) -> None:
        """Live-session half of a local tenant handoff: re-point this
        session at the destination engine and re-base its window there
        (the same machinery as a hot-reload rebase), so the next feed
        continues seamlessly under the new owner."""
        with self._lock:
            self.engine = engine
            with engine._request_scope():
                self._epoch = None  # force: the epoch spaces differ
                self._rebase()

    # ---------------------------------------------------------------- close

    def close(self) -> list[dict]:
        """End of stream: resolve the reassembly tail, score it, and run
        the one-shot finish sequence over the accumulated window. The
        final frame's result is bit-identical to ``analyze()`` on the
        concatenated blob (the replay theorem); frequency state commits
        exactly once, here."""
        with self._lock:
            if self.closed:
                return [
                    self._frame(
                        "error", reason=self.kill_reason or "closed",
                        message=self.kill_message or "session is closed",
                    )
                ]
            self._touch()
            try:
                with self.engine._request_scope():
                    frames = self._close_in_scope()
                self.closed = True
                self.kill_reason = None
                self._commit_session_span("closed")
                if self.manager is not None:
                    self.manager._discard(self, "closed")
                return frames
            except StreamError as err:
                frame = self._error_frame(err)
                self.kill(err.reason)
                return [frame]
            except Exception as exc:
                frame = self._frame(
                    "error", reason="internal", message=repr(exc)
                )
                self.kill("internal")
                return [frame]

    def _close_in_scope(self) -> list[dict]:
        eng = self.engine
        if eng.reload_epoch != self._epoch:
            self._rebase()
        tail = self._normalizer.flush()
        if tail:
            self._text += tail
            if self.mode != "golden":
                self._ingest_text(tail)
        if self.mode == "golden":
            with eng.state_lock:
                result = eng._golden_serve(PodFailureData(logs=self._text))
            return self._final_frames(result)

        corpus = Corpus(self._text, min_rows=eng._corpus_min_rows())
        n = corpus.n_lines
        try:
            tail_bits = self._close_tail_bits(corpus)
            bits = self._assemble_bits(corpus, tail_bits)
            recs = self._records_for(corpus, bits)
        except Exception as exc:
            self._handle_device_exc(exc, self._pending)
            with eng.state_lock:
                result = eng._golden_serve(PodFailureData(logs=self._text))
            return self._final_frames(result)

        with eng.state_lock:
            saved = eng.frequency._save_state()
            try:
                faults.fire("stream_close")
                freq_base = np.zeros(
                    max(1, eng.bank.n_freq_slots), dtype=np.float64
                )
                freq_exists = np.zeros(
                    max(1, eng.bank.n_freq_slots), dtype=bool
                )
                for slot, pid in enumerate(eng.bank.freq_ids):
                    freq_base[slot] = eng.frequency.get_windowed_count(pid)
                    freq_exists[slot] = eng.frequency.has_entry(pid)
                fin = finalize_batch(
                    eng.bank, eng.tables, eng.config, recs, n,
                    freq_base, freq_exists,
                )
                for slot, count in enumerate(
                    fin.slot_batch_counts[: eng.bank.n_freq_slots]
                ):
                    eng.frequency.record_pattern_matches(
                        eng.bank.freq_ids[slot], int(count)
                    )
                events: list[MatchedEvent] = []
                for i in range(len(fin.scores)):
                    line_idx = int(fin.line[i])
                    pattern = eng.bank.patterns[int(fin.pattern[i])]
                    events.append(
                        MatchedEvent(
                            line_number=line_idx + 1,
                            matched_pattern=pattern,
                            context=extract_context(corpus, line_idx, pattern),
                            score=float(fin.scores[i]),
                        )
                    )
                result = AnalysisResult(
                    events=events,
                    analysis_id=str(uuid.uuid4()),
                    metadata=build_metadata(
                        self._start, n, eng.bank.pattern_sets
                    ),
                    summary=build_summary(events),
                )
            except Exception as exc:
                eng.frequency._load_state(saved)
                raise StreamError(
                    "fault", f"close finalize failed: {exc!r}"
                ) from exc
        return self._final_frames(result)

    def _close_tail_bits(self, corpus: Corpus) -> np.ndarray | None:
        """Device bits for the unterminated tail line, if the final corpus
        keeps one: finish it on the carry when it tracked the whole tail,
        else score it as a one-line residual."""
        eng = self.engine
        n = corpus.n_lines
        if n != len(self._lines) + 1:
            return None
        tail = corpus.line(n - 1)
        pure = _is_pure_line(tail)
        if pure is None:
            return None  # fully overridden by the splice
        row = self._cache_lookup(pure)
        if row is not None:
            return row
        if self._carry is not None and self._tail_pure:
            rest = tail[self._tail_fed:]
            if rest:
                self._carry.feed(rest.encode("utf-8", errors="replace"))
            self._tail_fed = len(tail)
            row = self._carry.snapshot_bits()
            self._cache_populate(pure, row)
            return row
        batch_idx = [len(self._lines)]
        self._lines.append(tail)
        self._bits.append(None)
        self._chunk_device_step(tail, batch_idx)
        row = self._bits.pop()
        self._lines.pop()
        return row

    def _final_frames(self, result: AnalysisResult) -> list[dict]:
        current = {
            (ev.line_number - 1, ev.matched_pattern.id): float(ev.score)
            for ev in result.events
        }
        frames = self._diff_frames(current)
        frames.append(self._frame("final", result=result.to_dict(drop_none=True)))
        return frames


class StreamManager:
    """Session registry + reliability wiring: admission-gated opens, TTL
    reaping, and the ``/trace/last`` ``stream`` counter block."""

    def __init__(
        self,
        engine,
        emit_threshold: float = DEFAULT_EMIT_THRESHOLD,
        ttl_s: float = DEFAULT_STREAM_TTL_S,
        clock=pclock.mono,
        start_reaper: bool = True,
    ):
        self.engine = engine
        self.emit_threshold = float(emit_threshold)
        self.ttl_s = float(ttl_s)
        self.clock = clock
        self._lock = threading.Lock()
        self._sessions: dict[str, StreamSession] = {}
        self._next_id = 0
        # counters (GET /trace/last "stream"; guarded by _lock)
        self.sessions_opened = 0
        self.sessions_closed = 0
        self.sessions_killed = 0
        self.sessions_reaped = 0
        self.sessions_rebased = 0
        self.sessions_migrated = 0  # moved OUT by a tenant migration
        self.sessions_adopted = 0  # moved/restored IN by a migration
        self.chunks_ingested = 0
        self.bytes_ingested = 0
        self.frames_emitted = 0
        self.frames_revised = 0
        self.golden_continuations = 0
        self.poison_kills = 0
        self._reaper: threading.Thread | None = None
        self._stop = threading.Event()
        if start_reaper and self.ttl_s > 0:
            self._reaper = threading.Thread(
                target=self._reap_loop, name="stream-reaper", daemon=True
            )
            self._reaper.start()

    # ----------------------------------------------------------- lifecycle

    def open(self, deadline_ms: float | None = None) -> StreamSession:
        """Open one session through the shared admission gate — an open
        session holds an in-flight slot until it closes, is killed, or is
        reaped, so streaming load and one-shot load share one budget.
        Raises :class:`AdmissionRejected` when the gate refuses."""
        from log_parser_tpu.serve.admission import shared_gate

        gate = shared_gate(self.engine)
        gate.acquire(deadline_ms=deadline_ms, batchable=False)
        with self._lock:
            self._next_id += 1
            sid = f"s{self._next_id:06d}"
            sess = StreamSession(
                self.engine, sid, self.emit_threshold, manager=self
            )
            self._sessions[sid] = sess
            self.sessions_opened += 1
        return sess

    def get(self, session_id: str) -> StreamSession | None:
        with self._lock:
            return self._sessions.get(session_id)

    # ------------------------------------------------------------ migration

    def adopt(self, sess: StreamSession) -> StreamSession:
        """Move a LIVE session from another manager onto this engine (the
        local-handoff half of a tenant migration): acquire this engine's
        admission slot, release the source's, re-register the session
        (keeping its id unless taken) and re-base its window here. The
        session object survives — the client's next feed lands on the
        new owner without ever seeing an error frame."""
        from log_parser_tpu.serve.admission import shared_gate

        shared_gate(self.engine).acquire(batchable=False)
        src = sess.manager
        if src is not None and src is not self:
            moved_out = False
            with src._lock:
                if src._sessions.pop(sess.session_id, None) is not None:
                    moved_out = True
                    src.sessions_migrated += 1
            if moved_out:
                shared_gate(src.engine).release()
        with self._lock:
            sid = sess.session_id
            if sid in self._sessions:
                self._next_id += 1
                sid = f"s{self._next_id:06d}"
                sess.session_id = sid
            self._sessions[sid] = sess
            self.sessions_adopted += 1
        sess.manager = self
        sess.rebase_onto(self.engine)
        return sess

    def adopt_carry(self, carry: dict) -> StreamSession:
        """Restore an exported session carry (cross-process migration):
        open a fresh admission-gated session here and replay the carried
        window into it. The restored session keeps the source's frame
        sequence, so the client's monotone contract holds if it
        reconnects by session id."""
        sess = self.open()
        try:
            sess.restore_carry(carry)
        except Exception:
            sess.kill("internal")
            raise
        with self._lock:
            self.sessions_adopted += 1
        return sess

    def _discard(self, sess: StreamSession, reason: str) -> None:
        from log_parser_tpu.serve.admission import shared_gate

        released = False
        with self._lock:
            if self._sessions.pop(sess.session_id, None) is not None:
                released = True
                if reason == "closed":
                    self.sessions_closed += 1
                elif reason == "ttl":
                    self.sessions_reaped += 1
                else:
                    self.sessions_killed += 1
        if released:
            shared_gate(self.engine).release()

    # --------------------------------------------------------------- reaper

    def reap_now(self) -> int:
        """Kill every session idle past the TTL; returns how many died.
        The background reaper calls this on a cadence; tests with an
        injected clock call it directly."""
        if self.ttl_s <= 0:
            return 0
        now = self.clock()
        with self._lock:
            # Clock stepped backwards (injected/wall clocks only — the
            # default is monotonic): rebase instead of letting the negative
            # idle age shield the session from the TTL forever.
            for s in self._sessions.values():
                if s.last_active > now:
                    s.last_active = now
            stale = [
                s for s in self._sessions.values()
                if now - s.last_active > self.ttl_s
            ]
        for sess in stale:
            sess.kill("ttl")
        return len(stale)

    def _reap_loop(self) -> None:
        interval = max(0.05, min(self.ttl_s / 4.0, 1.0))
        while not pclock.wait(self._stop, interval):
            self.reap_now()

    def shutdown(self) -> None:
        self._stop.set()
        with self._lock:
            live = list(self._sessions.values())
        for sess in live:
            sess.kill("shutdown")

    # ------------------------------------------------------------- counters

    def _note_chunk(self, n_bytes: int) -> None:
        with self._lock:
            self.chunks_ingested += 1
            self.bytes_ingested += n_bytes

    def _note_frame(self, ftype: str) -> None:
        with self._lock:
            if ftype == "emit":
                self.frames_emitted += 1
            elif ftype == "revised":
                self.frames_revised += 1

    def _note_golden(self) -> None:
        with self._lock:
            self.golden_continuations += 1

    def _note_poison(self) -> None:
        with self._lock:
            self.poison_kills += 1

    def _note_rebase(self) -> None:
        with self._lock:
            self.sessions_rebased += 1

    def stats(self) -> dict:
        with self._lock:
            return {
                "openSessions": len(self._sessions),
                "sessionsOpened": self.sessions_opened,
                "sessionsClosed": self.sessions_closed,
                "sessionsKilled": self.sessions_killed,
                "sessionsReaped": self.sessions_reaped,
                "sessionsRebased": self.sessions_rebased,
                "sessionsMigrated": self.sessions_migrated,
                "sessionsAdopted": self.sessions_adopted,
                "chunksIngested": self.chunks_ingested,
                "bytesIngested": self.bytes_ingested,
                "framesEmitted": self.frames_emitted,
                "framesRevised": self.frames_revised,
                "goldenContinuations": self.golden_continuations,
                "poisonKills": self.poison_kills,
            }


_shared_lock = threading.Lock()

# /metrics view over StreamManager.stats() — registered against the
# engine's obs bundle in shared_manager() (log_parser_tpu/obs)
METRIC_SAMPLES = (
    ("openSessions", "logparser_stream_sessions", {}),
    ("chunksIngested", "logparser_stream_chunks_total", {}),
    ("framesEmitted", "logparser_stream_frames_total", {}),
)


def shared_manager(engine) -> StreamManager:
    """ONE manager per engine, shared across transports — the streaming
    analogue of ``serve.admission.shared_gate``. HTTP ``/parse/stream``
    and gRPC ``StreamParse`` sessions land in the same registry, so they
    draw on one admission budget, one TTL reaper, and one ``stream``
    counter block on ``/trace/last``. Thresholds come from the same env
    vars the serve flags mirror."""
    import os

    with _shared_lock:
        mgr = getattr(engine, "stream_manager", None)
        if mgr is None:
            mgr = StreamManager(
                engine,
                emit_threshold=float(
                    os.environ.get(
                        "LOG_PARSER_TPU_STREAM_EMIT_THRESHOLD",
                        str(DEFAULT_EMIT_THRESHOLD),
                    )
                ),
                ttl_s=float(
                    os.environ.get(
                        "LOG_PARSER_TPU_STREAM_TTL_S", str(DEFAULT_STREAM_TTL_S)
                    )
                ),
            )
            engine.stream_manager = mgr
            obs = getattr(engine, "obs", None)
            if obs is not None:
                obs.add_stats_collector(
                    f"stream-{id(mgr)}", mgr.stats, METRIC_SAMPLES,
                    labels={"tenant": getattr(engine, "obs_tenant", "default")},
                )
        return mgr
