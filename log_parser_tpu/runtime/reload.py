"""Zero-downtime pattern-library hot reload, canary-gated.

The reference service can only change its pattern library by restarting
the JVM (PatternService loads once at boot); production log-parsing
fleets roll pattern changes into *running* processes (PAPERS.md — the
Dynatrace DPL conversion pipeline, CelerLog's dynamic routing). Here the
swap is made safe in three stages:

1. **build off to the side** — a fresh :class:`AnalysisEngine` compiles
   the new MatcherBanks/DfaBank/fused ladder without touching the live
   engine (fault site ``reload_build``);
2. **canary-validate** — the fresh engine's *device* output is compared
   event-for-event (line, pattern id, score to 1e-9) against a fresh
   golden host engine on a built-in validation corpus, augmented with
   lines synthesized from the new library's own required literals so new
   patterns actually fire (fault site ``reload_canary``);
3. **atomic swap** — :meth:`AnalysisEngine.apply_library` quiesces the
   request gate (in-flight and already-enqueued batched requests finish
   on the old banks), swaps every library-derived component under the
   state lock, carries frequency entries of surviving pattern ids over,
   and bumps the reload epoch. On a distributed coordinator the epoch is
   broadcast inside the quiesced section so followers swap in lockstep
   (or the mesh marks itself DEGRADED).

Any failure in stages 1-2 raises :class:`ReloadError` and the live
engine is untouched — the HTTP layer turns that into a structured 409.

``PatternWatcher`` is the ``--watch-patterns`` mtime poller: the same
reload path, triggered by an on-disk change to the pattern directory.
"""

from __future__ import annotations

import logging
import os
import threading
import time

import yaml

from log_parser_tpu import _clock as pclock
from log_parser_tpu.models.pattern import PatternSet
from log_parser_tpu.models.pod import PodFailureData
from log_parser_tpu.patterns.loader import (
    PatternValidationError,
    load_pattern_directory,
    validate_pattern_set,
)
from log_parser_tpu.runtime import faults
from log_parser_tpu.runtime.engine import AnalysisEngine

log = logging.getLogger(__name__)

# Built-in validation corpus: generic log shapes that exercise ingest,
# context extraction, severity scoring, and the sequence/proximity paths
# regardless of which library is being loaded. Library-specific lines are
# synthesized from the new bank's literals at canary time.
VALIDATION_LOGS = """\
2024-01-01T00:00:00Z INFO startup: service listening on :8080
2024-01-01T00:00:01Z WARN disk usage at 91% on /var/lib
2024-01-01T00:00:02Z ERROR OOMKilled: container exceeded memory limit
java.lang.OutOfMemoryError: Java heap space
    at com.example.Worker.process(Worker.java:42)
    at com.example.Main.run(Main.java:17)
2024-01-01T00:00:03Z ERROR connection refused: upstream db:5432
2024-01-01T00:00:04Z FATAL CrashLoopBackOff: back-off restarting failed container
2024-01-01T00:00:05Z WARN retrying request (attempt 3/5)
2024-01-01T00:00:06Z ERROR java.net.SocketTimeoutException: Read timed out
2024-01-01T00:00:07Z INFO health probe ok
"""

_SCORE_TOL = 1e-9
_MAX_LITERAL_LINES = 64


class ReloadError(Exception):
    """A pattern reload rejected before the swap — the live engine is
    untouched. ``stage`` is ``"build"``, ``"lint"``, ``"canary"``, or
    ``"swap"``. ``findings`` (lint/schema rejections) ride along into
    the structured 409 body so the operator sees every violation, not
    just the first."""

    def __init__(self, stage: str, reason: str, findings: list[dict] | None = None):
        super().__init__(f"pattern reload failed at {stage}: {reason}")
        self.stage = stage
        self.reason = reason
        self.findings = findings

    def to_json(self) -> dict:
        out = {"error": "reload rejected", "stage": self.stage,
               "reason": self.reason}
        if self.findings:
            out["findings"] = self.findings
        return out


def parse_yaml_sets(text: str) -> list[PatternSet]:
    """Pattern sets from an inline YAML body (one document per set, or
    one document holding a list of set mappings). Raises ReloadError on
    anything malformed — inline bodies fail loudly, unlike the directory
    walk's log-and-skip (an operator POSTing a library wants the error)."""
    try:
        docs = [d for d in yaml.safe_load_all(text) if d is not None]
    except yaml.YAMLError as exc:
        raise ReloadError("build", f"invalid YAML: {exc}") from exc
    flat: list[dict] = []
    for doc in docs:
        items = doc if isinstance(doc, list) else [doc]
        for item in items:
            if not isinstance(item, dict):
                raise ReloadError(
                    "build", f"pattern set must be a mapping, got {type(item).__name__}"
                )
            flat.append(item)
    if not flat:
        raise ReloadError("build", "no pattern sets in body")
    try:
        sets = [PatternSet.from_dict(d) for d in flat]
    except Exception as exc:
        raise ReloadError("build", f"invalid pattern set: {exc}") from exc
    for i, pattern_set in enumerate(sets):
        try:
            validate_pattern_set(pattern_set, source=f"document {i}")
        except PatternValidationError as exc:
            raise ReloadError(
                "build", str(exc), findings=exc.findings
            ) from exc
    return sets


def canary_corpus(bank) -> str:
    """The built-in corpus plus one synthetic line per pattern embedding
    a required literal from its primary column — so a brand-new pattern
    demonstrably fires through the device path before it goes live."""
    lines = [VALIDATION_LOGS]
    emitted = 0
    for p in range(bank.n_patterns):
        if emitted >= _MAX_LITERAL_LINES:
            break
        col = bank.columns[int(bank.primary_columns[p])]
        if not col.literals:
            continue
        lit = min(col.literals, key=lambda l: (len(l.text), l.text))
        try:
            text = lit.text.decode("ascii")
        except UnicodeDecodeError:
            continue
        lines.append(f"canary probe {text} end\n")
        emitted += 1
    return "".join(lines)


def lint_stage(sets: list[PatternSet], mode: str, engine=None) -> dict | None:
    """Pre-canary lint stage: static analysis of the candidate library
    (log_parser_tpu/analysis/) BEFORE any engine is built.

    ``mode``: ``"off"`` skips entirely; ``"warn"`` records findings (on
    the engine's ``last_lint`` for /trace/last and in the success
    envelope) but never rejects; ``"block"`` raises :class:`ReloadError`
    at stage ``"lint"`` when any gating (error/warn-severity) finding
    exists — the 409 body lists every finding. Returns the lint summary
    dict (None when off)."""
    if mode == "off":
        return None
    from log_parser_tpu.analysis import lint_pattern_sets

    report = lint_pattern_sets(sets)
    summary = report.summary()
    if engine is not None:
        engine.last_lint = summary
    if report.gating and mode == "block":
        gating = report.gating_findings
        raise ReloadError(
            "lint",
            f"{len(gating)} gating lint finding(s): "
            + ", ".join(sorted({f.rule for f in gating})),
            findings=[f.to_json() for f in gating],
        )
    if report.gating:
        log.warning(
            "pattern lint found %d gating finding(s) (mode=warn, "
            "proceeding): %s",
            len(report.gating_findings),
            sorted({f.rule for f in report.gating_findings}),
        )
    return summary


def build_candidate(
    sets: list[PatternSet], config, engine_clock=None
) -> AnalysisEngine:
    """Stage 1: compile the new library entirely off to the side."""
    try:
        faults.fire("reload_build")
        if not sets:
            raise ValueError("no pattern sets")
        source = AnalysisEngine(
            sets, config, clock=engine_clock or pclock.mono
        )
        # canary must not hide device failures behind the host fallback
        source.fallback_to_golden = False
        return source
    except ReloadError:
        raise
    except Exception as exc:
        raise ReloadError("build", str(exc)) from exc


def canary_validate(source: AnalysisEngine) -> int:
    """Stage 2: run the candidate's device pipeline against a fresh golden
    host engine on the validation corpus; any divergence (count, line,
    pattern id, score beyond 1e-9) rejects the library. Both sides start
    from empty frequency state, so frequency evolution is identical by
    construction. Returns the number of events validated."""
    from log_parser_tpu.golden.engine import GoldenAnalyzer

    try:
        faults.fire("reload_canary")
        data = PodFailureData(
            pod="reload-canary",
            logs=canary_corpus(source.bank),
            events=None,
        )
        got = source.analyze(data)
        want = GoldenAnalyzer(source.bank.pattern_sets, source.config).analyze(data)
    except ReloadError:
        raise
    except Exception as exc:
        raise ReloadError("canary", str(exc)) from exc
    if len(got.events) != len(want.events):
        raise ReloadError(
            "canary",
            f"device produced {len(got.events)} event(s), golden "
            f"{len(want.events)}",
        )
    for i, (g, w) in enumerate(zip(got.events, want.events)):
        if g.line_number != w.line_number:
            raise ReloadError(
                "canary",
                f"event {i}: line {g.line_number} != golden {w.line_number}",
            )
        gid = g.matched_pattern.id if g.matched_pattern else None
        wid = w.matched_pattern.id if w.matched_pattern else None
        if gid != wid:
            raise ReloadError(
                "canary", f"event {i}: pattern {gid!r} != golden {wid!r}"
            )
        if abs(g.score - w.score) > _SCORE_TOL:
            raise ReloadError(
                "canary",
                f"event {i}: score {g.score!r} != golden {w.score!r}",
            )
    return len(got.events)


class PatternReloader:
    """The full reload pipeline against one live engine. Serialized on an
    internal lock: concurrent reload requests queue rather than racing
    two builds (the second sees the first's epoch in its response)."""

    def __init__(
        self,
        engine: AnalysisEngine,
        pattern_dir: str | None = None,
        lint_mode: str = "warn",  # off | warn | block (--lint-patterns)
    ):
        self.engine = engine
        self.pattern_dir = pattern_dir
        self.lint_mode = lint_mode
        self._lock = threading.Lock()

    def reload(
        self,
        *,
        pattern_dir: str | None = None,
        yaml_text: str | None = None,
        timeout_s: float = 30.0,
    ) -> dict:
        """Build + canary + swap. Raises :class:`ReloadError` (engine
        untouched) on any failure; returns the success envelope."""
        with self._lock:
            engine = self.engine
            try:
                if yaml_text is not None:
                    sets = parse_yaml_sets(yaml_text)
                else:
                    directory = pattern_dir or self.pattern_dir
                    if not directory:
                        raise ReloadError(
                            "build", "no pattern directory configured and no "
                            "inline YAML body",
                        )
                    sets = load_pattern_directory(directory)
                    if not sets:
                        raise ReloadError(
                            "build", f"no pattern sets loaded from {directory!r}"
                        )
                lint = lint_stage(sets, self.lint_mode, engine=engine)
                source = build_candidate(
                    sets, engine.config, engine_clock=engine.frequency.clock
                )
                validated = canary_validate(source)
                pre_swap = None
                broadcast = getattr(engine, "broadcast_reload", None)
                if callable(broadcast):
                    pre_swap = lambda: broadcast(sets)  # noqa: E731
                try:
                    epoch = engine.apply_library(
                        source, timeout_s=timeout_s, pre_swap=pre_swap
                    )
                except (TimeoutError, RuntimeError) as exc:
                    raise ReloadError("swap", str(exc)) from exc
            except ReloadError as exc:
                engine.reload_failures += 1
                engine.last_reload_error = str(exc)
                log.error("%s (old banks stay live)", exc)
                raise
            engine.reload_count += 1
            engine.last_reload_error = None
            log.info(
                "pattern library reloaded: epoch %d, %d set(s), %d "
                "pattern(s), %d canary event(s)",
                epoch, len(sets), source.bank.n_patterns, validated,
            )
            envelope = {
                "status": "reloaded",
                "epoch": epoch,
                "patternSets": len(sets),
                "patterns": source.bank.n_patterns,
                "canaryEvents": validated,
            }
            if lint is not None:
                envelope["lint"] = lint
            return envelope


class PatternWatcher:
    """``--watch-patterns``: poll the pattern directory's latest mtime
    and run the reload pipeline when it changes. A failed reload (canary
    rejection, mid-edit broken YAML) is logged and retried on the NEXT
    mtime change — the old banks serve throughout."""

    def __init__(
        self,
        reloader: PatternReloader,
        directory: str,
        interval_s: float = 2.0,
    ):
        self.reloader = reloader
        self.directory = directory
        self.interval_s = interval_s
        self.reload_attempts = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._last_sig = self._signature()

    def _signature(self) -> tuple:
        """(path, mtime_ns, size) of every pattern file — catches edits,
        adds, and deletes without hashing content on every poll."""
        sig = []
        try:
            for root, _dirs, files in sorted(
                (r, d, f) for r, d, f in os.walk(self.directory)
            ):
                for name in sorted(files):
                    if not name.endswith((".yml", ".yaml")):
                        continue
                    path = os.path.join(root, name)
                    try:
                        st = os.stat(path)
                    except OSError:
                        continue
                    sig.append((path, st.st_mtime_ns, st.st_size))
        except OSError:
            pass
        return tuple(sig)

    def start(self) -> "PatternWatcher":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="pattern-watch", daemon=True
            )
            self._thread.start()
            log.info(
                "watching %s for pattern changes (every %gs)",
                self.directory, self.interval_s,
            )
        return self

    def _run(self) -> None:
        while not pclock.wait(self._stop, self.interval_s):
            sig = self._signature()
            if sig == self._last_sig:
                continue
            self._last_sig = sig
            self.reload_attempts += 1
            try:
                self.reloader.reload(pattern_dir=self.directory)
            except ReloadError:
                # already logged with stage + reason; old banks stay live
                pass
            except Exception:
                log.exception("pattern watcher reload failed")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
