"""Cross-request dynamic micro-batching — the serving-side throughput
lever between the admission gate and the engine.

Without this layer every request runs the device pipeline alone: under
concurrent small parses the chip spends most of its time on per-request
dispatch overhead and padding, and the engine's ``state_lock`` turns N
clients into a serial stream (SURVEY.md §5.2). Continuous batching is the
standard fix in serving stacks, and shape-routed grouping before the
expensive matcher is exactly where dynamic-routing parsers like CelerLog
get their throughput (PAPERS.md).

Data flow (docs/ARCHITECTURE.md "Cross-request micro-batching"):

1. **submit** (caller thread): ingest + host-regex overrides — the same
   prepare work ``AnalysisEngine._prepare`` does, minus the device step —
   then the prepared corpus enqueues into a *bucket* keyed by its padded
   row count. Buckets exist so one flush compiles one ``[R, B, T]`` shape:
   row counts are already quantized (fractional power-of-two rungs × the
   engine's min-rows floor, ops/encode.py ``_pad_rows``), widths to
   power-of-two rungs, and R pads to the next power of two below
   ``batch_max`` — so the jit-shape space stays as bounded as the
   unbatched path's.
2. **scheduler** (one background thread): flushes a bucket when it is
   FULL (``batch_max`` queued), when the oldest entry has waited
   ``wait_ms``, or when the earliest enqueued request's admission
   DEADLINE approaches (a tight deadline must not sit out the coalescing
   window). Each flush stacks the bucket into one padded device batch and
   runs ONE vmapped fused program (ops/fused.py
   :class:`~log_parser_tpu.ops.fused.FusedBatchMatchScore`) through the
   engine's watchdog — per-request ``n_lines`` masks inside the vmap
   guarantee scores never bleed across requests.
3. **demux** (scheduler thread): per-request records resolve in ENQUEUE
   order — approx verification, then the frequency-coupled finish under
   ``engine.state_lock`` with the same save/rollback the unbatched path
   uses. The frequency read-before-record ordering is therefore exactly
   what a serial stream in enqueue order would produce. Failures stay
   per-request: a device-classified error falls back to the golden host
   path for THAT request only; a logic bug propagates to its caller and
   its batchmates never notice.

**Bisection** (``_resolve_records``): a device-classified fault on the
fused batched step no longer sinks the whole flush to golden. The batch
is split log₂-wise — each half retried as its own smaller device batch —
until the poison row(s) are isolated: the healthy majority is served
ON-DEVICE exactly as if the poison had never shared their flush, and
only the culprits take the golden fallback (which strikes their
fingerprint into ``runtime/quarantine.py`` so the NEXT arrival never
reaches the device step at all). Demux still runs in enqueue order over
the concatenated per-item outcomes, so frequency serial-equivalence is
untouched. A watchdog circuit-open error (``pre_run``) skips bisection —
every sub-batch would short-circuit identically — as does a non-device
logic error (it would reproduce deterministically on every split).

Chaos sites (runtime/faults.py): ``batcher`` fires at flush start (so
``batcher_slow`` delays a flush and ``batcher_raise`` fails a whole batch
into per-request fallback), ``quarantine`` fires per request inside the
batched device step keyed by the request's log blob (``match=`` poisons
one row of a healthy batch), ``bisect`` fires at each split decision
(``bisect_raise`` aborts isolation and fails the faulted sub-batch
whole), ``batcher_demux`` fires per request during demux (a dropped
demux slot fails one request, not the batch), and ``batcher_oversize`` —
when armed — makes the flush take EVERYTHING queued in the bucket,
ignoring ``batch_max`` (an oversized batch exercising the R-padding
ladder).
"""

from __future__ import annotations

import threading
import time
from typing import TYPE_CHECKING

import numpy as np

from log_parser_tpu import _clock as pclock
from log_parser_tpu.native.ingest import Corpus
from log_parser_tpu.ops.encode import _pad_rows
from log_parser_tpu.runtime import faults
from log_parser_tpu.runtime.linecache import (
    dedup_slots,
    line_key,
    records_from_bits,
)
from log_parser_tpu.utils.trace import PhaseTrace

if TYPE_CHECKING:  # import cycle: engine imports nothing from here at boot
    from log_parser_tpu.models.analysis import AnalysisResult
    from log_parser_tpu.models.pod import PodFailureData


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


class _Pending:
    """One enqueued request: prepare outputs + the rendezvous the caller
    blocks on. ``result``/``error`` are written by the scheduler thread
    before ``done`` is set."""

    __slots__ = (
        "data", "start", "trace", "corpus", "om", "ov",
        "deadline", "enqueued_at", "done", "result", "error", "seq",
    )

    def __init__(self, data, start, trace, corpus, om, ov, deadline, seq):
        self.data = data
        self.start = start
        self.trace = trace
        self.corpus = corpus
        self.om = om
        self.ov = ov
        self.deadline = deadline  # monotonic seconds, or None
        self.enqueued_at = pclock.mono()
        self.done = threading.Event()
        self.result = None
        self.error: BaseException | None = None
        self.seq = seq


class MicroBatcher:
    """Background scheduler coalescing concurrent analyze() calls into one
    padded device batch per shape bucket. Created via
    ``engine.enable_batching()``; transports call ``engine.analyze_batched``
    which routes here."""

    def __init__(self, engine, wait_ms: float = 2.0, batch_max: int = 8):
        from log_parser_tpu.ops.fused import FusedBatchMatchScore

        self.engine = engine
        self.wait_s = max(0.0, float(wait_ms)) / 1e3
        self.batch_max = max(1, int(batch_max))
        self.program = FusedBatchMatchScore(engine.fused)
        self._cv = threading.Condition()
        self._queues: dict[int, list[_Pending]] = {}  # bucket rows -> FIFO
        self._closed = False
        self._seq = 0
        self._thread: threading.Thread | None = None
        # counters (GET /trace/last "batcher"; guarded by _cv)
        self.requests_batched = 0
        self.batches_flushed = 0
        self.last_batch_size = 0
        self.max_batch_seen = 0
        self.flush_full = 0
        self.flush_wait = 0
        self.flush_deadline = 0
        self.demux_errors = 0
        self.bisects = 0
        self.bisect_aborts = 0
        self.bisect_isolated = 0
        # the flush trace id dispatch spans attach to — scheduler-thread
        # only (set around _resolve_records; bisection retries run on
        # the same thread, so their dispatch spans land on the same
        # flush trace)
        self._active_flush: str | None = None

    # ---------------------------------------------------------------- API

    def start(self) -> "MicroBatcher":
        self._thread = threading.Thread(
            target=self._scheduler, name="micro-batcher", daemon=True
        )
        self._thread.start()
        return self

    def close(self, timeout_s: float = 10.0) -> None:
        """Stop accepting work, flush what is queued, join the scheduler.
        Late submit() calls run unbatched through the engine."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout_s)

    def submit(
        self,
        data: "PodFailureData",
        deadline_ms: float | None = None,
        request_id: str | None = None,
    ):
        """Blocking analyze-through-the-batcher: prepare on THIS thread,
        coalesce on the scheduler, return this request's result (or raise
        its per-request error). Semantics match ``analyze_pipelined``
        request-for-request. ``request_id`` rides the request's
        PhaseTrace through the flush so the obs ring can attribute the
        shared device step back to the inbound X-Request-Id.

        The whole call sits inside the engine's request scope: a pattern
        reload that arrives after this request enqueued waits for its
        demux, so already-enqueued batches always finish on the banks
        they were prepared against."""
        with self.engine._request_scope():
            # quarantined fingerprints never enqueue: they would poison a
            # flush their batchmates share — straight to the host path
            fp = self.engine._quarantine_check(data)
            if fp is not None:
                start = pclock.mono()
                with self.engine.state_lock:
                    result = self.engine._serve_quarantined(data, fp)
                self.engine._note_golden(
                    start, "batched", request_id, "quarantined"
                )
                return result
            pending = self._enqueue(data, deadline_ms, request_id)
            if pending is None:  # closed: serve unbatched, same contract
                return self.engine.analyze_pipelined(
                    data, request_id=request_id
                )
            pending.done.wait()
            if pending.error is not None:
                raise pending.error
            return pending.result

    # ------------------------------------------------------------- enqueue

    def _enqueue(self, data, deadline_ms, request_id=None) -> _Pending | None:
        """Prepare (ingest + overrides) on the caller thread and queue the
        request into its shape bucket. Returns None when closed. A prepare
        failure takes the engine's normal fallback/propagate path — under
        ``state_lock``, exactly like ``_analyze``'s prepare except-arm."""
        start = pclock.mono()
        trace = PhaseTrace()
        trace.route = "batched"
        # always a concrete id: the flush span links its member traces
        # by this value, and a span-link must resolve even when the
        # client sent no X-Request-Id (obs/spans.py mints link span ids
        # deterministically from the trace id, so no lookup is needed)
        trace.request_id = request_id or self.engine.obs.new_request_id()
        try:
            with trace.phase("ingest"):
                faults.fire("ingest")
                corpus = Corpus(
                    data.logs or "", min_rows=self.engine._corpus_min_rows()
                )
                corpus.encoded  # materialize outside the scheduler
            with trace.phase("overrides"):
                overrides = self.engine._overrides(corpus)
        except Exception as exc:
            with self.engine.state_lock:
                result = self.engine._serve_fallback(
                    data, exc,
                    request_id=trace.request_id, start=start,
                    route="batched",
                )
            done = _Pending(data, start, trace, None, None, None, None, -1)
            done.result = result
            done.done.set()
            return done
        om, ov = overrides if overrides is not None else (None, None)
        deadline = (
            start + deadline_ms / 1e3
            if deadline_ms is not None and deadline_ms > 0
            else None
        )
        with self._cv:
            if self._closed:
                return None
            pending = _Pending(
                data, start, trace, corpus, om, ov, deadline, self._seq
            )
            self._seq += 1
            rows = corpus.encoded.u8.shape[0]
            self._queues.setdefault(rows, []).append(pending)
            self.requests_batched += 1
            self._cv.notify_all()
        return pending

    # ----------------------------------------------------------- scheduler

    def _flush_at(self, item: _Pending) -> float:
        """When this entry stops waiting for batchmates: its coalescing
        window closes at ``enqueued_at + wait_s``, but an admission
        deadline pulls the flush earlier — leaving a ``wait_s`` margin for
        the device step, floored at the enqueue time (a request that
        arrives nearly dead flushes immediately rather than never)."""
        at = item.enqueued_at + self.wait_s
        if item.deadline is not None:
            at = min(at, max(item.enqueued_at, item.deadline - self.wait_s))
        return at

    def _pick_flush(self, now: float):
        """(bucket, reason) ready to flush now, or (None, earliest time a
        bucket becomes ready). Caller holds ``_cv``."""
        soonest = None
        for rows, q in self._queues.items():
            if not q:
                continue
            if len(q) >= self.batch_max:
                return rows, "full"
            at = min(self._flush_at(i) for i in q)
            if at <= now:
                # deadline-pulled when the wait window alone wouldn't
                # have fired yet
                wait_only = min(i.enqueued_at for i in q) + self.wait_s
                return rows, ("deadline" if at < wait_only - 1e-9 else "wait")
            soonest = at if soonest is None else min(soonest, at)
        return None, soonest

    def _scheduler(self) -> None:
        while True:
            with self._cv:
                while True:
                    now = pclock.mono()
                    bucket, when = self._pick_flush(now)
                    if bucket is not None:
                        reason = when
                        break
                    if self._closed and not any(self._queues.values()):
                        return
                    self._cv.wait(
                        None if when is None else max(0.0, when - now)
                    )
                q = self._queues[bucket]
                take = min(len(q), self.batch_max)
                try:
                    # chaos: an armed oversize fault widens this flush to
                    # the whole bucket, past batch_max
                    faults.fire("batcher_oversize")
                except faults.InjectedFault:
                    take = len(q)
                items = q[:take]
                del q[:take]
                self.batches_flushed += 1
                self.last_batch_size = len(items)
                self.max_batch_seen = max(self.max_batch_seen, len(items))
                if reason == "full":
                    self.flush_full += 1
                elif reason == "deadline":
                    self.flush_deadline += 1
                else:
                    self.flush_wait += 1
            try:
                self._flush(items, reason)
            except BaseException:  # pragma: no cover - must never kill the loop
                import logging

                logging.getLogger(__name__).exception(
                    "micro-batcher flush failed after demux; "
                    "requests were already resolved"
                )

    # --------------------------------------------------------------- flush

    def _flush(self, items: list[_Pending], reason: str = "wait") -> None:
        engine = self.engine
        spans = engine.obs.spans
        # the flush is its own trace: it belongs to N request traces at
        # once, so it LINKS every member request (and every member
        # back-links it through trace.links) instead of parenting under
        # any single one — the fan-in the flat trace ring cannot express
        flush_id = engine.obs.new_request_id()
        flush_t0 = pclock.mono()
        now = pclock.mono()
        for item in items:
            wait_s = now - item.enqueued_at
            item.trace.add("batch_wait", wait_s)
            item.trace.links.append(flush_id)
            item.trace.span_attrs.update({"flush": flush_id})
            spans.annotate(
                item.trace.request_id, "enqueue", wait_s,
                attrs={"flush": flush_id, "reason": reason},
            )
        t0 = time.perf_counter()
        self._active_flush = flush_id
        try:
            # chaos at the flush boundary: batcher_slow delays the whole
            # batch; batcher_raise fails it into per-request fallback
            faults.fire("batcher")
            resolved = self._resolve_records(items)
        except Exception as exc:
            # pre-device failure (injected batcher fault, stacking bug):
            # every request takes the per-request fallback decision
            resolved = [exc] * len(items)
        finally:
            self._active_flush = None
        dt = time.perf_counter() - t0
        for item in items:
            item.trace.add("device", dt)
        demux_t0 = time.perf_counter()
        demux_errs = 0
        # demux in enqueue order: the frequency evolution equals a serial
        # stream's (read-before-record per request, under state_lock).
        # ``resolved`` holds per-item device records OR the exception that
        # survived bisection for that row — failures stay per-request.
        fallbacks = 0
        for item, recs in zip(items, resolved):
            if isinstance(recs, BaseException):
                fallbacks += 1
                # this row's (sub-)batch faulted: the engine's normal
                # fallback/propagate decision, individually — a device
                # error serves golden (and strikes quarantine), a logic
                # bug propagates to this caller alone
                try:
                    with engine.state_lock:
                        item.result = engine._serve_fallback(
                            item.data, recs,
                            request_id=item.trace.request_id,
                            start=item.start, route="batched",
                        )
                except BaseException as per_req:  # noqa: BLE001
                    item.error = per_req
                finally:
                    item.done.set()
                continue
            try:
                faults.fire("batcher_demux")
                with item.trace.phase("verify"):
                    recs = engine._verify_approx(item.corpus, recs)
                from log_parser_tpu.runtime.engine import _Prepared

                prepared = _Prepared(
                    item.start, item.trace, item.corpus, recs, item.data
                )
                with item.trace.phase("lock_wait"):
                    engine.state_lock.acquire()
                try:
                    saved_freq = engine.frequency._save_state()
                    try:
                        item.result = engine._finish(prepared)
                    except Exception as exc:
                        engine.frequency._load_state(saved_freq)
                        item.result = engine._serve_fallback(
                            item.data, exc,
                            request_id=item.trace.request_id,
                            start=item.start, route="batched",
                        )
                finally:
                    engine.state_lock.release()
            except BaseException as exc:  # noqa: BLE001 - delivered to caller
                with self._cv:
                    self.demux_errors += 1
                demux_errs += 1
                item.error = exc
            finally:
                item.done.set()
        spans.annotate(
            flush_id, "demux", time.perf_counter() - demux_t0,
            attrs={"requests": len(items), "errors": demux_errs,
                   "fallbacks": fallbacks},
        )
        # commit the flush trace whole (force=True: flushes are rare
        # relative to requests and are the one place fan-in causality
        # lives — sampling must never drop them)
        spans.end_trace(
            flush_id,
            duration_s=pclock.mono() - flush_t0,
            tenant=engine.obs_tenant,
            name="flush",
            attrs={
                "members": len(items),
                "reason": reason,
                "bucket": items[0].corpus.encoded.u8.shape[0],
            },
            links=[item.trace.request_id for item in items],
            force=True,
        )

    # ----------------------------------------------------------- bisection

    def _resolve_records(self, items: list[_Pending], depth: int = 0):
        """Per-item outcomes for one flush: device records on success, or
        the exception each row is charged with. On a device-classified
        fault the batch splits in half and each half retries as its own
        smaller device batch (log₂ extra steps), isolating poison row(s)
        so the healthy majority still serves ON-DEVICE. Outcomes
        concatenate in the original order, so the enqueue-order demux —
        and with it frequency serial-equivalence — is untouched."""
        from log_parser_tpu.runtime.engine import is_device_error

        if depth == 0 and self.engine.line_cache is not None:
            resolved = self._cached_batch(items, self.engine.line_cache)
            if resolved is not None:
                return resolved
            # residual device step failed: retry the WHOLE flush on the
            # uncached vmapped path below, so bisection, per-row poison
            # isolation, and quarantine striking behave exactly cache-off
        try:
            return self._device_batch(items)
        except Exception as exc:
            if len(items) == 1:
                if depth > 0:
                    with self._cv:
                        self.bisect_isolated += 1
                return [exc]
            if not is_device_error(exc):
                # deterministic logic error: every split reproduces it
                return [exc] * len(items)
            if getattr(exc, "pre_run", False):
                # watchdog circuit open — the device step never ran and
                # every sub-batch would short-circuit identically
                return [exc] * len(items)
            try:
                faults.fire("bisect")
            except faults.InjectedFault:
                with self._cv:
                    self.bisect_aborts += 1
                return [exc] * len(items)
            with self._cv:
                self.bisects += 1
            mid = len(items) // 2
            return self._resolve_records(
                items[:mid], depth + 1
            ) + self._resolve_records(items[mid:], depth + 1)

    def _cached_batch(self, items: list[_Pending], cache):
        """Resolve one flush through the line cache: per-item lookups,
        ONE compacted residual cube dispatch for the unique misses across
        the WHOLE flush (the cross-request half of the dedup), host-side
        override splice + extraction per item. Returns per-item records,
        or None when the residual device step fails — the caller then
        retries the flush wholesale on the uncached path.

        A flush whose lines are all cache hits performs zero device
        dispatches, and the keyed poison fault fires only for items that
        actually contributed a residual row — a request served wholly
        from cache can never strike quarantine."""
        engine = self.engine
        # per-item array-speed dedup (linecache.dedup_slots), then merge
        # at the UNIQUE level into a flush-global map keyed by digest —
        # the cache keys on digests already, so digest identity IS line
        # identity here. Per unique slot: the (item, line) the encode
        # would be sliced from; prefer a non-needs_host appearance — a
        # truncated/replaced encode is width-dependent and must neither
        # populate the cache nor serve another item's clean line. Within
        # one item duplicate content shares one verdict (same bytes, same
        # width), so the item-local representative is exact.
        slot_of: dict[bytes, int] = {}  # digest -> flush-global slot
        uniq_src: list[tuple[int, int]] = []
        keys: list[bytes] = []  # digest per slot; insertion == slot order
        per_item: list[np.ndarray] = []  # per item: line index -> slot
        for r, item in enumerate(items):
            corpus = item.corpus
            enc = corpus.encoded
            ded = dedup_slots(corpus, interner=engine.key_interner)
            if ded is None:
                # lone-surrogate corpus: no contiguous byte view — build
                # the item-local unique set with the per-line dict loop
                local_of: dict[bytes, int] = {}
                reps: list[int] = []
                ls = np.empty(corpus.n_lines, dtype=np.int64)
                for i in range(corpus.n_lines):
                    lb = corpus.line_key_bytes(i)
                    s = local_of.get(lb)
                    if s is None:
                        s = len(reps)
                        local_of[lb] = s
                        reps.append(i)
                    ls[i] = s
                local_keys = [line_key(lb) for lb in local_of]
            else:
                ls, rep_arr, local_keys, _ = ded
                reps = rep_arr.tolist()
            g_of_local = np.empty(max(len(reps), 1), dtype=np.int64)
            for s_local, (k, i) in enumerate(zip(local_keys, reps)):
                g = slot_of.get(k)
                if g is None:
                    g = len(uniq_src)
                    slot_of[k] = g
                    uniq_src.append((r, i))
                    keys.append(k)
                else:
                    sr, si = uniq_src[g]
                    if (
                        items[sr].corpus.encoded.needs_host[si]
                        and not enc.needs_host[i]
                    ):
                        uniq_src[g] = (r, i)
                g_of_local[s_local] = g
            per_item.append(g_of_local[ls] if len(ls) else ls)
        U = len(uniq_src)
        all_slots = (
            np.concatenate(per_item) if per_item else np.zeros(0, dtype=np.int64)
        )
        counts = np.bincount(all_slots, minlength=max(U, 1))
        packed = cache.lookup_packed(keys, counts=counts.tolist())
        miss_slots = [s for s in range(U) if packed[s] is None]

        miner = engine.miner
        if miner is not None:
            # miss-stream tap: one non-blocking bounded-queue offer per
            # unique novel line (sampling + drop accounting live in the
            # tap); mining happens on the miner thread, never here
            for s in miss_slots:
                r, i = uniq_src[s]
                miner.tap.offer(
                    items[r].corpus.line_key_bytes(i), int(counts[s])
                )

        fresh = None
        if miss_slots:
            u = len(miss_slots)
            T = max(i.corpus.encoded.u8.shape[1] for i in items)
            pad = _pad_rows(u, engine._corpus_min_rows())
            res_u8 = np.zeros((pad, T), dtype=np.uint8)
            res_len = np.zeros(pad, dtype=np.int32)
            contributed = sorted({uniq_src[s][0] for s in miss_slots})
            for j, s in enumerate(miss_slots):
                r, i = uniq_src[s]
                enc = items[r].corpus.encoded
                res_u8[j, : enc.u8.shape[1]] = enc.u8[i]
                res_len[j] = enc.lengths[i]

            def _device_step():
                for r in contributed:
                    faults.fire("quarantine", key=items[r].data.logs or "")  # conlint: contained-by-caller (watchdog.run)
                faults.fire("device")  # conlint: contained-by-caller (watchdog.run)
                return engine._run_cube(res_u8, res_len, u)

            t0 = time.perf_counter()
            try:
                fresh = engine.watchdog.run(_device_step)[:u]
            except Exception as exc:
                self._dispatch_span(time.perf_counter() - t0, {
                    "rows": pad, "width": T, "lines": u,
                    "residual": True, "error": type(exc).__name__,
                })
                return None
            self._dispatch_span(time.perf_counter() - t0, {
                "rows": pad, "width": T, "lines": u, "residual": True,
                "wasteRatio": round((pad - u) / pad, 4) if pad else 0.0,
            })
            cache.note_residual(u, int(counts[miss_slots].sum()) - u)
            keep = [
                j
                for j, s in enumerate(miss_slots)
                if not items[uniq_src[s][0]].corpus.encoded.needs_host[
                    uniq_src[s][1]
                ]
            ]
            cache.populate_rows(
                [keys[miss_slots[j]] for j in keep], fresh[keep]
            )

        bits_u = np.zeros((U, cache.n_columns), dtype=bool)
        hit_slots = [s for s in range(U) if packed[s] is not None]
        if hit_slots:
            bits_u[hit_slots] = cache.unpack([packed[s] for s in hit_slots])
        if fresh is not None:
            bits_u[miss_slots] = fresh
        out = []
        for r, item in enumerate(items):
            n = item.corpus.n_lines
            if n:
                bits = bits_u[per_item[r]]  # fan unique rows back out
            else:
                bits = np.zeros((0, cache.n_columns), dtype=bool)
            if item.om is not None:
                bits = np.where(item.om[:n], item.ov[:n], bits)
            out.append(records_from_bits(bits, n, engine.bank, engine.tables))
        engine._k_hint = max(r.n_matches for r in out)
        return out

    def _device_batch(self, items: list[_Pending]):
        """Stack the bucket into one padded [R, B, T] batch, run the
        vmapped program through the watchdog, return per-item records."""
        engine = self.engine
        B = items[0].corpus.encoded.u8.shape[0]
        T = max(i.corpus.encoded.u8.shape[1] for i in items)
        R = _next_pow2(len(items))
        C = engine.bank.n_columns
        lines = np.zeros((R, B, T), dtype=np.uint8)
        lens = np.zeros((R, B), dtype=items[0].corpus.encoded.lengths.dtype)
        nlin = np.zeros((R,), dtype=np.int32)
        has_ov = any(i.om is not None for i in items)
        om = np.zeros((R, B, C), dtype=bool) if has_ov else None
        ov = np.zeros((R, B, C), dtype=bool) if has_ov else None
        for r, item in enumerate(items):
            enc = item.corpus.encoded
            # width padding is semantically neutral: bytes past a line's
            # length are already the zero padding byte at any width rung
            lines[r, :, : enc.u8.shape[1]] = enc.u8
            lens[r] = enc.lengths
            nlin[r] = item.corpus.n_lines
            if item.om is not None:
                om[r] = item.om
                ov[r] = item.ov
        # rows R >= len(items) are dummy slots: n_lines == 0 masks every
        # line invalid, so they produce zero matches at zero risk

        def _device_step():
            # chaos: a keyed quarantine fault poisons the row(s) whose log
            # blob contains match= — the fused step dies exactly as a real
            # poison pill would, exercising bisection end to end
            for item in items:
                faults.fire("quarantine", key=item.data.logs or "")  # conlint: contained-by-caller (watchdog.run)
            faults.fire("device")  # conlint: contained-by-caller (watchdog.run)
            return self.program.run(
                lines, lens, nlin, om, ov, k_hint=engine._k_hint
            )

        t0 = time.perf_counter()
        try:
            recs_list = engine.watchdog.run(_device_step)
        except BaseException as exc:
            # a faulted dispatch still records its span — carrying the
            # fault site — before bisection splits the batch; each
            # retried sub-batch lands as another dispatch span on the
            # same flush trace
            self._dispatch_span(time.perf_counter() - t0, {
                "rows": B, "width": T, "batchSlots": R,
                "dummySlots": R - len(items),
                "error": type(exc).__name__,
            })
            raise
        attrs = engine._note_kernel_dispatch(
            B, width=T, batch_slots=R, dummy_slots=R - len(items)
        ) or {"rows": B, "width": T}
        self._dispatch_span(time.perf_counter() - t0, attrs)
        engine._k_hint = max(r.n_matches for r in recs_list)
        return recs_list[: len(items)]

    def _dispatch_span(self, duration_s: float, attrs: dict) -> None:
        """Stage one device-dispatch child span under the active flush
        trace (no-op for unbatched callers — their dispatch attrs ride
        the request trace via ``_run_device``/``_run_cube`` instead)."""
        fid = self._active_flush
        if fid is not None:
            self.engine.obs.spans.annotate(
                fid, "dispatch", duration_s, attrs=attrs
            )

    # ------------------------------------------------------- observability

    def stats(self) -> dict:
        with self._cv:
            return {
                "waitMs": self.wait_s * 1e3,
                # sampled by the obs engine collector through
                # METRIC_SAMPLES below — keep key renames in sync
                "batchMax": self.batch_max,
                "queueDepth": sum(len(q) for q in self._queues.values()),
                "buckets": sorted(
                    rows for rows, q in self._queues.items() if q
                ),
                "requestsBatched": self.requests_batched,
                "batchesFlushed": self.batches_flushed,
                "lastBatchSize": self.last_batch_size,
                "maxBatchSeen": self.max_batch_seen,
                "flushFull": self.flush_full,
                "flushWait": self.flush_wait,
                "flushDeadline": self.flush_deadline,
                "demuxErrors": self.demux_errors,
                "bisects": self.bisects,
                "bisectAborts": self.bisect_aborts,
                "bisectIsolated": self.bisect_isolated,
            }


# /metrics view over MicroBatcher.stats() — read by the obs engine
# collector at scrape time (log_parser_tpu/obs), never a second tally
METRIC_SAMPLES = (
    ("queueDepth", "logparser_batch_queue_depth", {}),
    ("requestsBatched", "logparser_requests_batched_total", {}),
    ("batchesFlushed", "logparser_batches_flushed_total", {}),
)
