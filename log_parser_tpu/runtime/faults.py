"""Deterministic fault injection — the chaos half of the robustness story.

The reference service has exactly one failure mode: the JVM falls over
(SURVEY.md §5.2/§5.3). This framework instead carries explicit degradation
machinery (watchdog circuit breaker, golden host fallback, admission
control), and machinery like that is only trustworthy if its failure paths
are *exercised on purpose*. This module is the single switchboard for
doing so: named injection points threaded through the pipeline and the
transports, driven by a config/env DSL with a seeded PRNG and per-point
trigger counts, so every chaos scenario replays identically.

DSL (``LOG_PARSER_TPU_FAULTS``, comma-separated specs)::

    device_raise:0.5,device_hang:2@after=3,ingest_slow:0.05@times=10

Each spec is ``<site>_<action>[:<arg>][@mod=value]*``:

- site: where to inject — ``device``, ``ingest``, ``finalize``, ``http``,
  ``shim``, ``broadcast`` (coordinator-side transport, pre-collective),
  ``follower`` (a follower failing/stalling a dispatch, fired before the
  coordinator commits to the collective), ``heartbeat`` (the liveness
  probe of parallel/resilience.py), ``cache`` (on-disk cache reads —
  contained as a miss, libcache/xlacache), ``batcher`` (micro-batcher
  flush start — ``slow`` delays a flush, ``raise`` fails the whole batch
  into per-request fallback), ``batcher_demux`` (per request during batch
  demux — a dropped demux slot fails ONE request, never its batchmates),
  ``batcher_oversize`` (armed ``raise`` makes the next flush take the
  whole bucket past ``--batch-max`` — an oversized batch),
  ``journal`` (a WAL append failing — contained: the journal goes
  unhealthy, the request is still served), ``journal_torn`` (write half
  a frame then wedge the journal — the recovery-time torn-tail case),
  ``snapshot`` (background snapshot write fails — the WAL is NOT
  truncated, nothing is lost), ``reload_build`` / ``reload_canary``
  (candidate library build / canary validation fails during a hot
  reload — structured 409, the old banks keep serving),
  ``stream`` (per streaming chunk, keyed by the chunk's decoded text —
  a raise kills ONE session with a structured ``error`` frame, never
  the server; runtime/stream.py), ``stream_close`` (the streaming
  finish sequence — a raise rolls back the session's frequency commit
  before the error frame goes out). Any string
  works; sites are just names the code fires, see :func:`fire` call
  sites;
- action: ``raise`` (raise :class:`InjectedFault`; at the ``device`` site
  :class:`InjectedDeviceFault`, which ``is_device_error`` classifies as a
  device failure so the golden fallback serves it), ``hang`` (block for
  ``arg`` seconds — ``inf`` blocks until :meth:`FaultRegistry.lift`),
  ``slow`` (add ``arg`` seconds of latency);
- arg: probability in (0, 1] for ``raise`` (default 1), seconds for
  ``hang``/``slow``;
- mods: ``after=N`` (skip the first N evaluations at the site),
  ``times=N`` (inject at most N times), ``p=F`` (probability gate for
  ``hang``/``slow``), ``match=SUBSTR`` (content-conditional: the spec is
  eligible only at keyed fire sites — :func:`fire` called with
  ``key=...`` — whose key contains ``SUBSTR``; an unkeyed evaluation
  never matches. This is how a *poison request* is simulated
  deterministically: ``quarantine_raise@match=MARKER`` fails exactly the
  requests carrying MARKER in their logs, wherever they land — alone,
  inside a fused batch, or inside a bisected sub-batch).

The ``quarantine`` site (fired per request at the device-step boundary
with the request's log content as the key) raises
:class:`InjectedPoisonFault` — a *device-classified* fault that, unlike
every other injected fault, also accrues a quarantine strike: it stands
in for an organic poison pill, so the quarantine/bisection machinery
must react to it exactly as to the real thing. Streaming sessions fire
the same site per *chunk* with the chunk's decoded text as the key, so
a ``match=`` spec kills exactly the session that ingests the marker.

Seed: ``LOG_PARSER_TPU_FAULT_SEED`` (default 0). Probabilistic specs draw
from one ``random.Random(seed)`` in evaluation order, so a single-threaded
request sequence reproduces decision-for-decision; count-based specs
(``after``/``times``, p=1) are reproducible even under concurrency.

Zero-cost when idle: :func:`fire` is a module-function no-op until a
registry is installed (env at boot, or :func:`install` from tests).
"""

from __future__ import annotations

import dataclasses
import math
import os
import random
import threading

ENV_SPECS = "LOG_PARSER_TPU_FAULTS"
ENV_SEED = "LOG_PARSER_TPU_FAULT_SEED"

_ACTIONS = ("raise", "hang", "slow")


class InjectedFault(RuntimeError):
    """An injected (not organic) failure. Deliberately NOT classified as a
    device error: an injected ingest/finalize/transport fault must take the
    same propagate-to-500 path a real logic bug would."""

    def __init__(self, point: str, nth: int):
        super().__init__(f"injected fault {point!r} (trigger #{nth})")
        self.point = point
        self.nth = nth


class InjectedDeviceFault(InjectedFault):
    """An injected *device-layer* failure — ``is_device_error`` returns
    True for this class, so the golden fallback (and the breaker
    bookkeeping around it) reacts exactly as it would to a real dead
    backend."""


class InjectedPoisonFault(InjectedDeviceFault):
    """An injected poison *request* (the ``quarantine`` fire site):
    device-classified like :class:`InjectedDeviceFault`, but additionally
    treated as ORGANIC by the quarantine strike rule — injected backend
    chaos (``device_raise``) must never quarantine innocent traffic,
    while an injected poison pill must exercise the whole
    strike/quarantine/bisection ladder end to end."""


class FaultSpecError(ValueError):
    """Malformed ``LOG_PARSER_TPU_FAULTS`` entry."""


@dataclasses.dataclass
class FaultSpec:
    point: str  # full spec name, e.g. "device_hang"
    site: str  # "device"
    action: str  # "hang"
    arg: float  # probability (raise) or seconds (hang/slow)
    p: float = 1.0  # probability gate
    after: int = 0  # skip the first N evaluations
    times: int | None = None  # max injections
    match: str | None = None  # eligible only when the fire key contains this
    # runtime state
    calls: int = 0  # evaluations at this site
    fired: int = 0  # actual injections
    lifted: bool = False
    # hang/slow waiters block on this; lift() releases them
    release: threading.Event = dataclasses.field(
        default_factory=threading.Event
    )


def parse_spec(entry: str) -> FaultSpec:
    """One DSL entry -> FaultSpec. See the module docstring for grammar."""
    entry = entry.strip()
    head, *mods = entry.split("@")
    name, _, argtext = head.partition(":")
    name = name.strip()
    site, sep, action = name.rpartition("_")
    if not sep or action not in _ACTIONS or not site:
        raise FaultSpecError(
            f"bad fault point {name!r} (want <site>_<raise|hang|slow>)"
        )
    arg = 1.0 if action == "raise" else 30.0
    if argtext:
        try:
            arg = float(argtext)
        except ValueError as exc:
            raise FaultSpecError(f"bad arg in {entry!r}") from exc
    spec = FaultSpec(point=name, site=site, action=action, arg=arg)
    if action == "raise":
        if not 0.0 < arg <= 1.0:
            raise FaultSpecError(
                f"raise probability must be in (0, 1]: {entry!r}"
            )
        spec.p = arg
    elif arg < 0:
        raise FaultSpecError(f"negative delay in {entry!r}")
    for mod in mods:
        key, sep, value = mod.partition("=")
        key = key.strip()
        if not sep:
            raise FaultSpecError(f"bad modifier {mod!r} in {entry!r}")
        try:
            if key == "after":
                spec.after = int(value)
            elif key == "times":
                spec.times = int(value)
            elif key == "p":
                spec.p = float(value)
                if not 0.0 < spec.p <= 1.0:
                    raise FaultSpecError(
                        f"p must be in (0, 1]: {entry!r}"
                    )
            elif key == "match":
                if not value:
                    raise FaultSpecError(f"empty match in {entry!r}")
                spec.match = value
            else:
                raise FaultSpecError(f"unknown modifier {key!r} in {entry!r}")
        except ValueError as exc:
            raise FaultSpecError(f"bad modifier {mod!r} in {entry!r}") from exc
    return spec


class FaultRegistry:
    """Parsed fault specs + the seeded PRNG + trigger bookkeeping."""

    def __init__(self, specs: list[FaultSpec], seed: int = 0):
        self.seed = seed
        self.specs = specs
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._by_site: dict[str, list[FaultSpec]] = {}
        for spec in specs:
            self._by_site.setdefault(spec.site, []).append(spec)

    @classmethod
    def parse(cls, text: str, seed: int = 0) -> "FaultRegistry":
        specs = [parse_spec(e) for e in text.split(",") if e.strip()]
        return cls(specs, seed)

    @classmethod
    def from_env(cls, env=None) -> "FaultRegistry | None":
        env = os.environ if env is None else env
        text = env.get(ENV_SPECS, "").strip()
        if not text:
            return None
        return cls.parse(text, int(env.get(ENV_SEED, "0")))

    # ------------------------------------------------------------- firing

    def fire(self, site: str, key: str | None = None) -> None:
        """Evaluate every spec registered at ``site``; the first that
        triggers performs its action (raise / hang / slow). Evaluation
        order is declaration order, draws come from the one seeded RNG.
        ``key`` is the content a ``match=`` spec filters on (the request's
        log blob at per-request sites); a spec with ``match`` set is
        skipped entirely — no counter or RNG advance — when the key does
        not contain its substring."""
        chosen: FaultSpec | None = None
        with self._lock:
            for spec in self._by_site.get(site, ()):
                if spec.match is not None and (
                    key is None or spec.match not in key
                ):
                    continue
                spec.calls += 1
                if spec.lifted or spec.calls <= spec.after:
                    continue
                if spec.times is not None and spec.fired >= spec.times:
                    continue
                if spec.p < 1.0 and self._rng.random() >= spec.p:
                    continue
                if chosen is None:  # later specs still advance counters/RNG
                    spec.fired += 1
                    chosen = spec
        if chosen is None:
            return
        if chosen.action == "raise":
            if site == "quarantine":
                exc_t = InjectedPoisonFault
            elif site == "device":
                exc_t = InjectedDeviceFault
            else:
                exc_t = InjectedFault
            raise exc_t(chosen.point, chosen.fired)
        # hang/slow: block on the spec's release event so lift() can free
        # waiters; a finite arg is simply the wait timeout
        chosen.release.wait(None if math.isinf(chosen.arg) else chosen.arg)

    # --------------------------------------------------------- management

    def lift(self, point: str | None = None) -> None:
        """Disable matching specs (all when ``point`` is None) and release
        anything currently blocked in their hang/slow waits."""
        with self._lock:
            for spec in self.specs:
                if point is None or spec.point == point:
                    spec.lifted = True
                    spec.release.set()

    def counts(self) -> dict[str, int]:
        """Injections actually performed, per spec point."""
        with self._lock:
            return {s.point: s.fired for s in self.specs}

    def stats(self) -> dict:
        """Reproducibility/observability surface (GET /trace/last)."""
        with self._lock:
            return {
                "seed": self.seed,
                "fired": {s.point: s.fired for s in self.specs},
                "calls": {s.point: s.calls for s in self.specs},
            }


# ------------------------------------------------------- module switchboard

_REGISTRY: FaultRegistry | None = None
_ENV_LOADED = False
_INSTALL_LOCK = threading.Lock()


def install(registry: FaultRegistry | None) -> None:
    """Install (or clear, with None) the active registry — tests and the
    servers' boot paths. Clearing lifts the outgoing registry first so no
    hung waiter outlives it."""
    global _REGISTRY, _ENV_LOADED
    with _INSTALL_LOCK:
        if registry is None and _REGISTRY is not None:
            _REGISTRY.lift()
        _REGISTRY = registry
        _ENV_LOADED = True


def ensure_env() -> None:
    """Parse ``LOG_PARSER_TPU_FAULTS`` once (no-op when unset or when a
    registry was already installed explicitly)."""
    global _REGISTRY, _ENV_LOADED
    with _INSTALL_LOCK:
        if _ENV_LOADED:
            return
        _ENV_LOADED = True
        _REGISTRY = FaultRegistry.from_env()


def active() -> FaultRegistry | None:
    return _REGISTRY


def fire(site: str, key: str | None = None) -> None:
    """Injection point — a no-op unless a registry is installed."""
    reg = _REGISTRY
    if reg is not None:
        reg.fire(site, key)


def stats() -> dict | None:
    reg = _REGISTRY
    return None if reg is None else reg.stats()
